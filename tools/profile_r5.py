"""Round-5 serving-path profiler: where do HTTP tokens/sec and burst
TTFT go between the engine and the client?

Runs the SAME 8B-geometry engine bench.py uses, once engine-side and
once through the real aiohttp endpoint, with two instruments:

1. A per-request stage timeline (monkeypatched engine hooks): submit ->
   slot assign -> prefill dispatch -> prefill harvest, plus the
   client-observed first-content time, all on one perf_counter clock.
   Reported as percentiles relative to the wave t0.
2. An in-process sampling profiler (sys._current_frames every ~4 ms)
   aggregated per thread-group and top frames, so the one-core host's
   GIL budget is visible: who is burning the core while the wave runs.

Usage: python tools/profile_r5.py [--tokens N] [--slots N]
"""

from __future__ import annotations

import argparse
import collections
import sys
import threading
import time


class Sampler:
    def __init__(self, interval=0.004):
        self.interval = interval
        self.counts: collections.Counter = collections.Counter()
        self.thread_counts: collections.Counter = collections.Counter()
        self._stop = threading.Event()
        self._thread = None
        self._names = {}

    def start(self):
        self._names = {t.ident: t.name for t in threading.enumerate()}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="profiler-sampler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join()

    def _run(self):
        me = threading.get_ident()
        while not self._stop.is_set():
            for t in threading.enumerate():
                self._names.setdefault(t.ident, t.name)
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                name = self._names.get(ident, str(ident))
                # group thread families
                for pfx in ("srv-blocking", "stream-bridge", "engine",
                            "MainThread", "asyncio"):
                    if name.startswith(pfx):
                        name = pfx
                        break
                # skip idle frames (waits/sleeps don't burn the core)
                top = frame
                code = top.f_code
                key = f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}"
                idle = any(s in key for s in (
                    "wait", "sleep", "select:", "get:", "_run:_run"))
                stack = []
                f = frame
                for _ in range(4):
                    if f is None:
                        break
                    c = f.f_code
                    stack.append(
                        f"{c.co_filename.rsplit('/', 1)[-1]}:"
                        f"{c.co_name}:{f.f_lineno}")
                    f = f.f_back
                sig = " < ".join(stack)
                self.thread_counts[(name, "idle" if idle else "busy")] += 1
                if not idle:
                    self.counts[(name, sig)] += 1
            time.sleep(self.interval)

    def report(self, top_n=25):
        print("\n=== sampler: thread budget (samples) ===")
        for (name, st), c in sorted(self.thread_counts.items(),
                                    key=lambda kv: -kv[1]):
            print(f"  {name:24s} {st:5s} {c}")
        print(f"\n=== sampler: top busy stacks ===")
        for (name, sig), c in self.counts.most_common(top_n):
            print(f"  {c:6d} [{name}] {sig}")


TL = collections.defaultdict(dict)  # req id -> stage -> t
TL_LOCK = threading.Lock()
FLIGHTS = []  # (kind, detail, t_enqueue, t_harvest)


def instrument_engine():
    from localai_tfp_tpu.engine import engine as em

    orig_submit_many = em.LLMEngine.submit_many
    orig_assign = em.LLMEngine._assign
    orig_enq = em.LLMEngine._enqueue_prefill_final
    orig_cpf = em.LLMEngine._complete_prefill_final
    orig_harvest = em.LLMEngine._harvest

    def _harvest(self):
        did = False
        while self._flights and self._flights[0].ready():
            fl = self._flights[0]
            detail = (f"k={fl.meta.get('k')}" if fl.kind == "decodek"
                      else f"n={len(fl.meta.get('pairs', []))}")
            FLIGHTS.append((fl.kind, detail, fl.t_enqueue,
                            time.perf_counter()))
            # delegate one completion at a time so we time each pop
            fl2 = self._flights.popleft()
            if fl2.kind == "prefill_final":
                self._complete_prefill_final(fl2)
            else:
                self._complete_decodek(fl2)
            did = True
        return did

    em.LLMEngine._harvest = _harvest

    def submit_many(self, reqs):
        t = time.perf_counter()
        with TL_LOCK:
            for r in reqs:
                TL[r.id]["submit"] = t
        return orig_submit_many(self, reqs)

    def _assign(self, slot, req, out):
        TL[req.id]["assign"] = time.perf_counter()
        return orig_assign(self, slot, req, out)

    def _enqueue_prefill_final(self, group, bucket):
        t = time.perf_counter()
        for s in group:
            if s.request is not None:
                TL[s.request.id].setdefault("pf_dispatch", t)
        return orig_enq(self, group, bucket)

    def _complete_prefill_final(self, fl):
        t = time.perf_counter()
        for _, (s, req) in enumerate(fl.meta["pairs"]):
            TL[req.id]["pf_harvest"] = t
        return orig_cpf(self, fl)

    em.LLMEngine.submit_many = submit_many
    em.LLMEngine._assign = _assign
    em.LLMEngine._enqueue_prefill_final = _enqueue_prefill_final
    em.LLMEngine._complete_prefill_final = _complete_prefill_final


def pct(xs, p):
    if not xs:
        return float("nan")
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


def report_flights(t0, label=""):
    print(f"\n=== flights ({label}): enqueue->harvest, ms after t0 ===")
    rows = [f for f in FLIGHTS if f[3] >= t0]
    for kind, detail, te, th in rows[-48:]:
        print(f"  {kind:14s} {detail:8s} enq={((te - t0) * 1e3):8.1f} "
              f"harv={((th - t0) * 1e3):8.1f} "
              f"dt={((th - te) * 1e3):7.1f}")


def report_timeline(t0, client_first=None, label=""):
    stages = ["submit", "assign", "pf_dispatch", "pf_harvest"]
    with TL_LOCK:
        rows = {k: dict(v) for k, v in TL.items() if "submit" in v
                and v["submit"] >= t0}
    print(f"\n=== timeline ({label}): {len(rows)} requests, "
          f"ms after wave t0 ===")
    for st in stages:
        xs = [(v[st] - t0) * 1e3 for v in rows.values() if st in v]
        if xs:
            print(f"  {st:12s} n={len(xs):3d} p10={pct(xs, .10):7.1f} "
                  f"p50={pct(xs, .50):7.1f} p90={pct(xs, .90):7.1f} "
                  f"max={max(xs):7.1f}")
    if client_first:
        xs = sorted(client_first)
        print(f"  {'client_1st':12s} n={len(xs):3d} p10={pct(xs, .10):7.1f} "
              f"p50={pct(xs, .50):7.1f} p90={pct(xs, .90):7.1f} "
              f"max={max(xs):7.1f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--slots", type=int, default=64)
    ap.add_argument("--skip-engine", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    sys.path.insert(0, "/root/repo")
    import bench

    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.models.llm_spec import LLMSpec

    instrument_engine()
    tok = bench.WideByteTok() if hasattr(bench, "WideByteTok") else None
    if tok is None:
        # bench defines it inside main(); replicate
        from localai_tfp_tpu.engine.tokenizer import ByteTokenizer

        class WideByteTok(ByteTokenizer):
            def decode(self, ids):
                return "".join(
                    chr(32 + (i % 95)) for i in ids
                    if i not in (self.bos_id, *self.eos_ids))

        tok = WideByteTok()

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        print("WARNING: not on TPU; numbers are meaningless", flush=True)

    spec8 = LLMSpec(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
        rope_theta=500000.0,
    )
    print("building int8 params...", flush=True)
    t = time.perf_counter()
    params8 = bench._fast_int8_params(spec8)
    print(f"params in {time.perf_counter() - t:.1f}s", flush=True)
    eng = LLMEngine(
        spec8, params8, tok, n_slots=args.slots, max_seq=1024,
        decode_steps=16, cache_dtype="int8", autostart=False,
    )
    eng.start()
    t = time.perf_counter()
    eng.warmup()
    print(f"warmup in {time.perf_counter() - t:.1f}s", flush=True)

    # tunnel RTT floor: trivial dispatch -> is_ready latency
    tiny = jnp.zeros((8,), jnp.float32)
    bump = jax.jit(lambda x: x + 1)
    bump(tiny).block_until_ready()
    for trial in range(3):
        t = time.perf_counter()
        y = bump(tiny)
        while not y.is_ready():
            time.sleep(2e-4)
        print(f"rtt_floor[{trial}] = "
              f"{(time.perf_counter() - t) * 1e3:.1f} ms", flush=True)

    n_tok = args.tokens
    # one warmup wave then one measured wave, engine-side
    if not args.skip_engine:
        bench._run_wave(eng, tok, args.slots, n_tok, "benchmark " * 12)
        bench._run_wave(eng, tok, args.slots, n_tok, "benchmark " * 12)
        smp = Sampler()
        t0 = time.perf_counter()
        smp.start()
        total, wall, tt, errs = bench._run_wave(
            eng, tok, args.slots, n_tok, "benchmark " * 12)
        smp.stop()
        print(f"\nENGINE wave: {total} tok in {wall:.2f}s = "
              f"{total / wall:.1f} tok/s; ttft p50="
              f"{tt[len(tt) // 2]:.0f}ms", flush=True)
        report_timeline(t0, [x for x in tt], label="engine")
        report_flights(t0 - 2.0, label="engine (incl 2s before t0)")
        smp.report()

    # HTTP leg: replicate bench._bench_http but with instrumentation
    import asyncio
    import json as _json
    import os
    import tempfile

    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import LoadedModel
    from localai_tfp_tpu.server.app import build_app
    from localai_tfp_tpu.server.state import Application
    from localai_tfp_tpu.workers.llm import JaxLLMBackend

    tmp = tempfile.mkdtemp(prefix="prof-srv-")
    models = os.path.join(tmp, "models")
    os.makedirs(models)
    with open(os.path.join(models, "bench.yaml"), "w") as f:
        f.write(
            "name: bench\n"
            "backend: jax-llm\n"
            "parameters:\n  model: bench\n"
            "template:\n"
            '  chat_message: "{{.RoleName}}: {{.Content}}"\n'
            '  chat: "{{.Input}}\\nassistant:"\n'
        )
    state = Application(ApplicationConfig(
        models_path=models,
        generated_content_dir=os.path.join(tmp, "generated"),
        upload_dir=os.path.join(tmp, "uploads"),
        config_dir=os.path.join(tmp, "configuration"),
    ))
    backend = JaxLLMBackend()
    backend.engine, backend.tokenizer = eng, tok
    backend.spec, backend._state = eng.spec, "READY"
    state.model_loader._models["bench"] = LoadedModel(
        "bench", "jax-llm", backend)
    app = build_app(state)

    n_req = args.slots
    smp = Sampler()
    res = {}

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def one(i, t0, ttfts):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": "benchmark " * 10 + str(i)}],
                    "max_tokens": n_tok, "stream": True,
                    "temperature": 0.8, "top_k": 40, "top_p": 0.95,
                    "ignore_eos": True,
                }
                total = 0
                async with sess.post(
                    url, json=body, headers={"Extra-Usage": "1"},
                ) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = _json.loads(line[6:])
                        ch = d["choices"][0]
                        if (ch["delta"].get("content")
                                and ttfts[i] is None):
                            ttfts[i] = (time.perf_counter() - t0) * 1e3
                        if ch.get("finish_reason"):
                            u = d.get("usage") or {}
                            total = u.get("completion_tokens", 0)
                return total

            for run in range(3):
                ttfts = [None] * n_req
                if run == 2:
                    smp.start()
                t0 = time.perf_counter()
                totals = await asyncio.gather(
                    *[one(i, t0, ttfts) for i in range(n_req)])
                wall = time.perf_counter() - t0
                if run == 2:
                    smp.stop()
                    res["tok_s"] = sum(totals) / wall
                    res["t0"] = t0
                    res["ttfts"] = [t for t in ttfts if t is not None]
                    res["wall"] = wall
        await runner.cleanup()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(drive())
    finally:
        loop.close()

    tt = sorted(res["ttfts"])
    print(f"\nHTTP wave: {res['tok_s']:.1f} tok/s over {res['wall']:.2f}s; "
          f"ttft p50={tt[len(tt) // 2]:.0f}ms p95="
          f"{tt[int(len(tt) * .95)]:.0f}ms", flush=True)
    report_timeline(res["t0"], res["ttfts"], label="http")
    report_flights(res["t0"] - 2.0, label="http (incl 2s before t0)")
    smp.report()
    eng.close()


if __name__ == "__main__":
    main()
