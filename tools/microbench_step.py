"""Where does the 8B decode step's ~28 ms/token-step go?

r5 flight traces: a k=16 decode scan executes in ~450 ms on an idle
chip (64 slots, int8 weights + int8 KV) — ~28 ms per step vs a ~10 ms
weight-read roofline — and the [64, 4] prefill_final program takes
~235 ms. This tool times the pieces in isolation on the real chip:

  forward-only scan  : k steps of forward + argmax (no sampler)
  full scan          : the engine's real _decode_k (forward + sampler)
  sampler-only scan  : k sampler calls on fixed logits
  prefill_final      : the engine's real [64, W] prefill program

Usage: python tools/microbench_step.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(label, fn, n=4):
    # one untimed call to absorb compile / cache load
    out = fn()
    for x in (out if isinstance(out, tuple) else (out,)):
        try:
            x.block_until_ready()
        except Exception:
            pass
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        for x in (out if isinstance(out, tuple) else (out,)):
            try:
                x.block_until_ready()
            except Exception:
                pass
        best = min(best, time.perf_counter() - t0)
    print(f"{label:28s} {best * 1e3:8.1f} ms", flush=True)
    return out, best


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    import bench

    from localai_tfp_tpu.engine.engine import (LLMEngine, _sample_masked)
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import LLMSpec
    from localai_tfp_tpu.models.transformer import forward

    class WideByteTok(ByteTokenizer):
        def decode(self, ids):
            return "".join(chr(32 + (i % 95)) for i in ids
                           if i not in (self.bos_id, *self.eos_ids))

    spec = LLMSpec(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
        rope_theta=500000.0,
    )
    print("building params...", flush=True)
    params = bench._fast_int8_params(spec)
    S, K, W = 64, 16, 1024
    eng = LLMEngine(
        spec, params, WideByteTok(), n_slots=S, max_seq=W,
        decode_steps=K, cache_dtype="int8", autostart=False,
    )
    use_kernel = eng._use_kernel
    print(f"use_kernel={use_kernel}", flush=True)

    from functools import partial

    from jax import lax

    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, 1000, (S, 1), np.int32))
    pos0 = jnp.full((S,), 128, jnp.int32)
    active = jnp.ones((S,), bool)
    sids = eng._all_slot_ids

    # --- sampler only: k sampler calls on fixed logits
    logits = jnp.asarray(np.random.default_rng(1).standard_normal(
        (S, spec.vocab_size)).astype(np.float32))

    @jax.jit
    def sampler_scan(sampling):
        def step(s, _):
            toks, s = _sample_masked(s, sids, logits, active, None)
            return s, toks

        s, toks = lax.scan(step, sampling, None, length=K)
        return toks, s

    sampling = eng.sampling
    (toks, sampling), dt_samp = timeit("sampler-only scan k=16",
                                       lambda: sampler_scan(sampling))

    # --- forward only (argmax): same window slicing as the real scan
    from localai_tfp_tpu.engine.engine import _window_cache

    @partial(jax.jit, donate_argnums=(2,))
    def fwd_scan(params, tokens, cache, pos0):
        cache, restore = _window_cache(cache, W)

        def step(carry, _):
            tokens, pos, cache = carry
            logits, cache = forward(spec, params, tokens, pos, cache,
                                    None, use_kernel)
            toks = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
            pos = jnp.where(active, pos + 1, pos)
            return (toks[:, None], pos, cache), toks

        (t2, p2, cache), seq = lax.scan(
            step, (tokens, pos0, cache), None, length=K)
        return seq.T, restore(cache)

    cache = eng.cache

    def run_fwd():
        nonlocal cache
        seq, cache = fwd_scan(params, tokens, cache, pos0)
        return (seq,)

    _, dt_fwd = timeit("forward-only scan k=16", run_fwd)

    # --- the engine's real full scan
    fn = eng._decode_k_fn(K, W)
    state = {"cache": cache, "sampling": sampling,
             "tok": tokens, "pos": pos0}

    def run_full():
        seq, t2, p2, state["cache"], state["sampling"] = fn(
            params, state["tok"], state["cache"], state["pos"], sids,
            state["sampling"], active)
        state["tok"], state["pos"] = t2, p2
        return (seq,)

    _, dt_full = timeit("full decode scan k=16", run_full)

    print(f"\nper-step: fwd {dt_fwd / K * 1e3:.1f} ms, "
          f"full {dt_full / K * 1e3:.1f} ms, "
          f"sampler-only {dt_samp / K * 1e3:.1f} ms", flush=True)

    # hand the donated-chain live buffers back to the engine: _dev_exec
    # reads self.cache/self.sampling, and the originals were consumed by
    # the scans above
    eng.cache = state["cache"]
    eng.sampling = state["sampling"]

    # --- prefill_final [64, 4] (the burst-TTFT floor)
    reset = {k: np.asarray(v) for k, v in {
        "temperature": np.full(S, 0.8, np.float32),
        "top_k": np.full(S, 40, np.int32),
        "top_p": np.full(S, 0.95, np.float32),
        "min_p": np.zeros(S, np.float32),
        "repeat_penalty": np.zeros(S, np.float32),
        "freq_penalty": np.zeros(S, np.float32),
        "presence_penalty": np.zeros(S, np.float32),
        "repeat_last_n": np.full(S, 64, np.int32),
        "seeds": np.zeros(S, np.int32),
        "has_seed": np.zeros(S, bool),
        "typical_p": np.ones(S, np.float32),
        "mirostat": np.zeros(S, np.int32),
        "mirostat_tau": np.full(S, 5.0, np.float32),
        "mirostat_eta": np.full(S, 0.1, np.float32),
    }.items()}
    # decompose prefill_final: forward_hidden vs the sampler tail
    from localai_tfp_tpu.models.transformer import _lm_head, forward_hidden
    from localai_tfp_tpu.ops.sampling import (reset_slots, sample,
                                              seed_windows)

    sids_np = jnp.arange(S, dtype=jnp.int32)

    @partial(jax.jit, donate_argnums=(2,))
    def pf_fwd(params, tokens, cache, pos0, slot_ids):
        return forward_hidden(spec, params, tokens, pos0, cache, slot_ids)

    @jax.jit
    def pf_tail(params, sampling, slot_ids, hidden, n_chunk, tails,
                tail_lens, reset_cols):
        sampling = reset_slots(sampling, slot_ids, *reset_cols)
        sampling = seed_windows(sampling, slot_ids, tails, tail_lens)
        last_h = jax.vmap(
            lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 0)[0]
        )(hidden, n_chunk)
        logits = _lm_head(spec, params, last_h[:, None, :])[:, 0]
        toks, sampling = sample(sampling, slot_ids, logits, mask=None)
        return toks, sampling

    tok4 = jnp.zeros((S, 4), jnp.int32)
    pos4 = jnp.full((S,), 64, jnp.int32)

    def run_pf_fwd():
        hidden, eng.cache = pf_fwd(params, tok4, eng.cache, pos4, sids_np)
        return (hidden,)

    (hidden4,), _ = timeit("pf forward_hidden [64,4]", run_pf_fwd)

    @partial(jax.jit, donate_argnums=(2,))
    def pf_fwd_id(params, tokens, cache, pos0):
        return forward_hidden(spec, params, tokens, pos0, cache, None)

    def run_pf_fwd_id():
        hidden, eng.cache = pf_fwd_id(params, tok4, eng.cache, pos4)
        return (hidden,)

    timeit("pf fwd identity [64,4]", run_pf_fwd_id)

    tok128 = jnp.zeros((S, 128), jnp.int32)

    def run_pf_fwd_id128():
        hidden, eng.cache = pf_fwd_id(params, tok128, eng.cache, pos4)
        return (hidden,)

    timeit("pf fwd identity [64,128]", run_pf_fwd_id128)
    reset_cols = tuple(jnp.asarray(v) for v in (
        np.full(S, 0.8, np.float32), np.full(S, 40, np.int32),
        np.full(S, 0.95, np.float32), np.zeros(S, np.float32),
        np.zeros(S, np.float32), np.zeros(S, np.float32),
        np.zeros(S, np.float32), np.full(S, 64, np.int32),
        np.zeros(S, np.int32), np.zeros(S, bool),
        np.ones(S, np.float32), np.zeros(S, np.int32),
        np.full(S, 5.0, np.float32), np.full(S, 0.1, np.float32)))
    tails_j = jnp.zeros((S, eng.sampling.window), jnp.int32)
    tlens_j = jnp.zeros((S,), jnp.int32)
    nchunk_j = jnp.ones((S,), jnp.int32)

    def run_pf_tail():
        toks, _ = pf_tail(params, eng.sampling, sids_np, hidden4,
                          nchunk_j, tails_j, tlens_j, reset_cols)
        return (toks,)

    timeit("pf sampler tail only", run_pf_tail)

    for Wp in (4, 128):
        payload = {
            "toks": np.zeros((S, Wp), np.int32),
            "pos0": np.full((S,), 64, np.int32),
            "slot_ids": np.arange(S, dtype=np.int32),
            "masks": None,
            "n_chunk": np.full((S,), 1, np.int32),
            "tails": np.zeros((S, eng.sampling.window), np.int32),
            "tail_lens": np.zeros((S,), np.int32),
            "reset": reset,
            "window": W,
        }

        def run_pf(payload=payload):
            return (eng._dev_exec("prefill_final", payload),)

        timeit(f"prefill_final [64,{Wp}]", run_pf)


if __name__ == "__main__":
    main()
