"""Meshed boot-time check: cold precompile vs artifact+warmup-reuse.

PR 12 routes every meshed dispatch kind through the sharded ragged
branch, which collapses the meshed warmup ladder to one variant per
token-budget shape AND lets a meshed ``engine.warmup()`` participate in
the persistent-cache warmup-reuse path (the marker-skip that
single-chip engines got in the artifact-cache PR). This tool makes the
payoff a one-command number: boot the SAME meshed paged engine twice in
fresh processes sharing one persistent compilation cache dir —

  cold:  empty cache dir, full precompile pass (every jit variant is a
         real compile)
  reuse: warm cache dir, the completed-warmup marker short-circuits the
         whole pass (any variant a request later touches loads from the
         persistent cache instead of compiling)

and print both walls. Each leg is its own process because the in-process
jit cache would make any second warmup trivially fast regardless of the
persistent cache (the thing being measured).

The legs only build + warm up — no decode is served. The persistent
compilation cache on this CPU stack miscompiles donated-buffer reuse
(the test suite never enables it for the same reason), and boot wall is
the measurement anyway.

Usage:
  python tools/profile_boot.py               # 8 virtual CPU devices
  python tools/profile_boot.py --devices 4
  python tools/profile_boot.py --cache-dir D # persist D across runs
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _leg(cache_dir: str, n_devices: int) -> dict:
    """One boot, in THIS process: force the host device count, enable
    the persistent cache, construct the meshed paged engine, warm up."""
    from __graft_entry__ import _force_host_devices

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = _force_host_devices(
        os.environ.get("XLA_FLAGS", ""), n_devices)

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params
    from localai_tfp_tpu.parallel.mesh import make_mesh

    devs = jax.devices("cpu")[:n_devices]
    if len(devs) != n_devices:
        raise SystemExit(
            f"needed {n_devices} CPU devices, got {len(devs)}")
    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=1024)
    n = len(devs)
    model_ax = next((m for m in (4, 2)
                     if n % m == 0 and spec.kv_dim % m == 0), 1)
    data_ax = 2 if (n // model_ax) % 2 == 0 else 1
    mesh = make_mesh({"data": data_ax, "seq": 1, "model": model_ax},
                     devices=devs[:data_ax * model_ax])
    params = init_params(jax.random.PRNGKey(0), spec,
                         dtype=jnp.float32)
    t0 = time.perf_counter()
    # max_seq above the 256 window floor: a real ladder is what the
    # cold pass pays for and the marker-skip saves
    eng = LLMEngine(spec, params, tk, n_slots=2, max_seq=1024,
                    prefill_buckets=(8, 32), decode_steps=4,
                    cache_dtype=jnp.float32, mesh=mesh,
                    autostart=False)
    build_s = time.perf_counter() - t0
    if not eng._paged:
        raise SystemExit("engine fell back to dense on this mesh")
    t1 = time.perf_counter()
    eng.warmup()
    warmup_s = time.perf_counter() - t1
    out = {
        "boot_s": round(build_s + warmup_s, 2),
        "build_s": round(build_s, 2),
        "warmup_s": round(warmup_s, 2),
        "warmup_variants": int(eng.warmup_variants),
        "warmup_reused": bool(eng.warmup_reused),
        "mesh_devices": data_ax * model_ax,
        "mesh_data": data_ax,
        "mesh_model": model_ax,
    }
    eng.close()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--cache-dir", default=None,
                    help="persistent compile cache dir shared by both "
                         "legs (default: a fresh temp dir)")
    ap.add_argument("--leg", choices=("cold", "reuse"), default=None,
                    help=argparse.SUPPRESS)  # child-process entry
    args = ap.parse_args()

    if args.leg is not None:
        out = _leg(args.cache_dir, args.devices)
        out["mode"] = args.leg
        print("BOOT_LEG " + json.dumps(out))
        return

    import shutil
    import tempfile

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="boot-cache-")
    own_dir = args.cache_dir is None

    def run(leg: str) -> dict:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--leg", leg, "--cache-dir", cache_dir,
             "--devices", str(args.devices)],
            capture_output=True, text=True, timeout=1800)
        for line in proc.stdout.splitlines():
            if line.startswith("BOOT_LEG "):
                return json.loads(line[len("BOOT_LEG "):])
        raise SystemExit(
            f"{leg} leg failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-2000:]}")

    try:
        cold = run("cold")  # empty dir: every variant really compiles
        reuse = run("reuse")  # same dir: the warmup marker skips the pass
        if cold["warmup_reused"]:
            raise SystemExit("cold leg unexpectedly hit a warmup marker "
                             f"in {cache_dir} — pass a fresh --cache-dir")
        if not reuse["warmup_reused"]:
            raise SystemExit("reuse leg did not hit the warmup marker")
        speedup = cold["boot_s"] / max(reuse["boot_s"], 1e-9)
        print(json.dumps({
            "cold": cold,
            "reuse": reuse,
            "boot_speedup": round(speedup, 2),
        }, indent=2))
    finally:
        if own_dir:
            shutil.rmtree(cache_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
