#!/usr/bin/env python3
"""Offline renderer for /debug/timeline Chrome-trace JSON.

The flight recorder (telemetry/flightrec.py) exports a Perfetto-loadable
timeline; this tool renders the same file in a terminal for hosts with
no browser at hand — one ASCII lane per track plus per-name duration
stats:

    $ curl -s localhost:8080/debug/timeline > timeline.json
    $ python tools/trace_viewer.py timeline.json
    timeline: 1832 events over 2417.3 ms (ring 8192, dropped 0)

    track device           128 spans
      step:decodek      ▏   ██ █ ████ ██████  ... ▕
    ...
    span durations (ms):                 n      p50      p95      max
      step:decodek                     96     1.84     2.91     4.40

Accepts a file path or an http(s) URL (fetched with stdlib urllib).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from collections import defaultdict

LANE_COLS = 72


def load(src: str) -> dict:
    if src.startswith(("http://", "https://")):
        url = src.rstrip("/")
        if not url.endswith("/debug/timeline"):
            url += "/debug/timeline"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read().decode("utf-8"))
    with open(src, encoding="utf-8") as f:
        return json.load(f)


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def render(doc: dict, out) -> int:
    events = doc.get("traceEvents") or []
    tracks: dict[int, str] = {}
    spans = []  # (tid, name, ts_us, dur_us)
    instants = []  # (tid, name, ts_us)
    counters: dict[str, list] = defaultdict(list)  # name -> (ts, value)
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") == "thread_name":
                tracks[ev.get("tid", 0)] = ev["args"]["name"]
            continue
        if ph == "X":
            spans.append((ev.get("tid", 0), ev["name"], ev["ts"],
                          ev.get("dur", 0.0)))
        elif ph == "i":
            instants.append((ev.get("tid", 0), ev["name"], ev["ts"]))
        elif ph == "C":
            counters[ev["name"]].append(
                (ev["ts"], ev.get("args", {}).get("value", 0)))
    timed = ([ts for _, _, ts, _ in spans]
             + [ts for _, _, ts in instants]
             + [ts for series in counters.values() for ts, _ in series])
    if not timed:
        print("timeline: empty (no events recorded yet)", file=out)
        return 0
    t_lo = min(timed)
    t_hi = max([ts + dur for _, _, ts, dur in spans] + timed)
    width_us = max(t_hi - t_lo, 1.0)
    other = doc.get("otherData") or {}
    print(f"timeline: {len(spans) + len(instants)} events over "
          f"{width_us / 1e3:.1f} ms (ring {other.get('ring_capacity')}, "
          f"dropped {other.get('dropped')})", file=out)

    def col(ts_us: float) -> int:
        return min(LANE_COLS - 1,
                   int((ts_us - t_lo) / width_us * LANE_COLS))

    for tid in sorted(tracks):
        tname = tracks[tid]
        tr_spans = [s for s in spans if s[0] == tid]
        tr_inst = [i for i in instants if i[0] == tid]
        if not tr_spans and not tr_inst:
            continue
        print(f"\ntrack {tname:<16} {len(tr_spans)} spans, "
              f"{len(tr_inst)} instants", file=out)
        by_name: dict[str, list] = defaultdict(list)
        for _, name, ts, dur in tr_spans:
            by_name[name].append((ts, dur))
        for _, name, ts in tr_inst:
            by_name[name].append((ts, 0.0))
        for name in sorted(by_name):
            lane = [" "] * LANE_COLS
            for ts, dur in by_name[name]:
                a, b = col(ts), col(ts + dur)
                for c in range(a, b + 1):
                    lane[c] = "█"
            print(f"  {name:<18} ▏{''.join(lane)}▕", file=out)

    by_span: dict[str, list] = defaultdict(list)
    for _, name, _, dur in spans:
        by_span[name].append(dur / 1e3)
    if by_span:
        print(f"\nspan durations (ms): {'':>14} {'n':>6} {'p50':>8} "
              f"{'p95':>8} {'max':>8}", file=out)
        for name in sorted(by_span):
            ds = sorted(by_span[name])
            print(f"  {name:<30} {len(ds):>6} "
                  f"{_percentile(ds, 0.50):>8.2f} "
                  f"{_percentile(ds, 0.95):>8.2f} {ds[-1]:>8.2f}",
                  file=out)
    for name in sorted(counters):
        vals = [v for _, v in counters[name]]
        print(f"counter {name:<22} samples {len(vals):>5}  "
              f"min {min(vals):g}  max {max(vals):g}  "
              f"last {vals[-1]:g}", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render /debug/timeline Chrome-trace JSON as ASCII")
    ap.add_argument("source",
                    help="path to a saved timeline.json, or a server "
                         "base URL / /debug/timeline URL")
    args = ap.parse_args(argv)
    try:
        doc = load(args.source)
    except OSError as e:
        print(f"trace_viewer: cannot load {args.source}: {e}",
              file=sys.stderr)
        return 1
    return render(doc, sys.stdout)


if __name__ == "__main__":
    sys.exit(main())
