"""Bisect the 8B prefill_final dispatch: which sub-graph costs ~400ms?

Times (enqueue -> result ready) for:
  A. full _prefill_final jit (what the engine dispatches)
  B. forward_hidden only (same shapes)
  C. forward_hidden + lm_head + plain sample
  D. reset_slots + seed_windows only
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/.cache/localai_xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

from bench import _fast_int8_params  # noqa: E402

from localai_tfp_tpu.engine.engine import LLMEngine  # noqa: E402
from localai_tfp_tpu.engine.tokenizer import ByteTokenizer  # noqa: E402
from localai_tfp_tpu.models.llm_spec import LLMSpec  # noqa: E402

spec = LLMSpec(
    vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
    n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
    rope_theta=500000.0,
)
params = _fast_int8_params(spec)
eng = LLMEngine(spec, params, ByteTokenizer(), n_slots=64, max_seq=1024,
                decode_steps=16, cache_dtype="int8", autostart=False)

B, bucket = 64, 32
W = eng.sampling.window
rng = np.random.default_rng(0)
toks = rng.integers(0, 200, (B, bucket)).astype(np.int32)
pos0 = np.zeros((B,), np.int32)
sids = np.arange(B, dtype=np.int32)
n_chunk = np.full((B,), bucket, np.int32)
tails = rng.integers(0, 200, (B, W)).astype(np.int32)
tail_lens = np.full((B,), 16, np.int32)
reset_np = eng._reset_columns([], 1)
reset = tuple(jnp.asarray(np.repeat(v, B, axis=0))
              for v in reset_np.values())


def flight(make):
    # compile
    out = make()
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        out = make()
        leaf = jax.tree_util.tree_leaves(out)[0]
        try:
            leaf.copy_to_host_async()
        except Exception:
            pass
        while not leaf.is_ready():
            time.sleep(0.0005)
        best = min(best, (time.perf_counter() - t0) * 1e3)
    return best


from localai_tfp_tpu.models.transformer import (  # noqa: E402
    KVCache, _lm_head, forward_hidden,
)
from localai_tfp_tpu.ops.sampling import (  # noqa: E402
    reset_slots, sample, seed_windows,
)

# ---- A: the engine's own compiled prefill_final (cache/sampling donated:
# recreate per call) ----
fn = eng._prefill_final_fn(eng.max_seq)


_state = {"cache": eng.cache, "sampling": eng.sampling}


def run_full():
    out, _state["cache"], _state["sampling"] = fn(
        eng.params, jnp.asarray(toks), _state["cache"],
        jnp.asarray(pos0), _state["sampling"], jnp.asarray(sids),
        jnp.asarray(n_chunk), jnp.asarray(tails),
        jnp.asarray(tail_lens), None, reset, None)
    return out


print(f"A full prefill_final      {flight(run_full):8.1f} ms", flush=True)


@__import__("functools").partial(jax.jit, donate_argnums=(2,))
def fwd_only(params, toks, cache, pos0, sids):
    h, cache = forward_hidden(spec, params, toks, pos0, cache, sids)
    return h[:, -1, :].sum(), cache


def run_fwd():
    out, _state["cache"] = fwd_only(
        eng.params, jnp.asarray(toks), _state["cache"],
        jnp.asarray(pos0), jnp.asarray(sids))
    return out


print(f"B forward_hidden only     {flight(run_fwd):8.1f} ms", flush=True)


@__import__("functools").partial(jax.jit, donate_argnums=(2, 5))
def fwd_head_sample(params, toks, cache, pos0, sids, sampling, n_chunk):
    h, cache = forward_hidden(spec, params, toks, pos0, cache, sids)
    last = jax.vmap(
        lambda hh, n: jax.lax.dynamic_slice_in_dim(hh, n - 1, 1, 0)[0]
    )(h, n_chunk)
    logits = _lm_head(spec, params, last[:, None, :])[:, 0]
    t, sampling = sample(sampling, sids, logits)
    return t, cache, sampling


def run_fhs():
    t, _state["cache"], _state["sampling"] = fwd_head_sample(
        eng.params, jnp.asarray(toks), _state["cache"],
        jnp.asarray(pos0), jnp.asarray(sids), _state["sampling"],
        jnp.asarray(n_chunk))
    return t


print(f"C fwd+head+sample         {flight(run_fhs):8.1f} ms", flush=True)


@__import__("functools").partial(jax.jit, donate_argnums=(0,))
def reset_seed(sampling, sids, tails, tail_lens, reset):
    sampling = reset_slots(sampling, sids, *reset)
    sampling = seed_windows(sampling, sids, tails, tail_lens)
    return sampling.history_pos, sampling


def run_rs():
    out, _state["sampling"] = reset_seed(
        _state["sampling"], jnp.asarray(sids), jnp.asarray(tails),
        jnp.asarray(tail_lens), reset)
    return out


print(f"D reset+seed only         {flight(run_rs):8.1f} ms", flush=True)
