"""Per-component timing of the 8B decode step on TPU: isolates the
transformer forward, lm_head, sampler (top_k vs approx_max_k), and
penalty machinery to find where the ~31ms/step goes.

Chained-timing method (block_until_ready is optimistic over the
tunnel): (N dependent iterations + download) - (1 + download) / (N-1).
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

S, V, D = 64, 128256, 4096


def timed(fn, carry0, n=8, reps=3):
    np.asarray(jax.tree_util.tree_leaves(fn(carry0))[0]).reshape(-1)[0]

    def once(n):
        best = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            c = carry0
            for _ in range(n):
                c = fn(c)
            np.asarray(jax.tree_util.tree_leaves(c)[0]).reshape(-1)[:1]
            best = min(best, time.perf_counter() - t0)
        return best

    t1, tn = once(1), once(n)
    return (tn - t1) / (n - 1) * 1e3


def main():
    rng = np.random.default_rng(0)

    # --- sampler-ish ops on [S, V] logits ---
    logits = jnp.asarray(rng.standard_normal((S, V), np.float32))

    @jax.jit
    def f_topk(lg):
        vals, idx = jax.lax.top_k(lg, 128)
        return lg + vals[:, :1] * 1e-9  # chainable

    @jax.jit
    def f_approx(lg):
        vals, idx = jax.lax.approx_max_k(lg, 128)
        return lg + vals[:, :1] * 1e-9

    @jax.jit
    def f_argmax(lg):
        return lg + jnp.max(lg, axis=-1, keepdims=True) * 1e-9

    print(f"top_k(128) on [{S},{V}]: {timed(f_topk, logits):8.2f} ms",
          flush=True)
    print(f"approx_max_k(128):       {timed(f_approx, logits):8.2f} ms",
          flush=True)
    print(f"plain max:               {timed(f_argmax, logits):8.2f} ms",
          flush=True)

    # --- penalties: gather counts + where-chains on [S, V] ---
    counts = jnp.asarray(rng.integers(0, 3, (S, V), np.int32))

    @jax.jit
    def f_pen(lg):
        present = counts > 0
        rp = jnp.full((S, 1), 1.1, jnp.float32)
        pen = jnp.where(lg > 0, lg / rp, lg * rp)
        out = jnp.where(present, pen, lg)
        out = out - counts.astype(jnp.float32) * 0.1
        return out

    print(f"penalty chain [S,V]:     {timed(f_pen, logits):8.2f} ms",
          flush=True)

    # --- full sample() from the repo ---
    from localai_tfp_tpu.ops.sampling import SamplingState, sample

    st = SamplingState.create(S, V, window=256)
    ids = jnp.arange(S, dtype=jnp.int32)

    @jax.jit
    def f_sample(carry):
        lg, st = carry
        tok, st = sample(st, ids, lg)
        return (lg + tok[:, None].astype(jnp.float32) * 1e-9, st)

    print(f"full sample():           {timed(f_sample, (logits, st)):8.2f}"
          " ms", flush=True)

    # --- lm_head int8 [S,D]x[D,V] ---
    q = jnp.asarray(rng.integers(-127, 128, (D, V), np.int8))
    sc = jnp.full((V,), 1e-4, jnp.float32)
    x = jnp.asarray(rng.standard_normal((S, D), np.float32) * .1,
                    jnp.bfloat16)

    @jax.jit
    def f_lmhead(x):
        y = (x @ q.astype(x.dtype)) * sc.astype(x.dtype)
        return x + y[:, :D] * 1e-9

    print(f"lm_head int8 [S,D]@[D,V]:{timed(f_lmhead, x):8.2f} ms",
          flush=True)

    # --- ragged decode-attention kernel, 32 layers, ctx ~384 ---
    from localai_tfp_tpu.models.llm_spec import LLMSpec
    from localai_tfp_tpu.models.transformer import KVCache, forward

    spec = LLMSpec(
        vocab_size=V, d_model=D, n_layers=32, n_heads=32,
        n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
        rope_theta=500000.0,
    )
    from bench import _fast_int8_params

    params = _fast_int8_params(spec)
    cache = KVCache.create(spec, S, 1024, "int8")
    pos0 = jnp.full((S,), 384, jnp.int32)

    @jax.jit
    def f_fwd_kernel(carry):
        toks, cache = carry
        lg, cache = forward(spec, params, toks, pos0, cache, None, True)
        nxt = jnp.argmax(lg[:, -1, :], -1).astype(jnp.int32)[:, None]
        return (nxt, cache)

    @jax.jit
    def f_fwd_xla(carry):
        toks, cache = carry
        lg, cache = forward(spec, params, toks, pos0, cache, None, False)
        nxt = jnp.argmax(lg[:, -1, :], -1).astype(jnp.int32)[:, None]
        return (nxt, cache)

    toks = jnp.ones((S, 1), jnp.int32)
    print(f"forward+argmax (kernel): {timed(f_fwd_kernel, (toks, cache), n=4):8.2f} ms",
          flush=True)
    cache2 = KVCache.create(spec, S, 1024, "int8")
    print(f"forward+argmax (xla):    {timed(f_fwd_xla, (toks, cache2), n=4):8.2f} ms",
          flush=True)


if __name__ == "__main__":
    main()
