"""Chaos profile: serving-survival numbers under injected faults.

Drives the robustness work end to end on a small CPU config and prints
one JSON report with the acceptance numbers the robustness PR tracks:

  engine leg (in-process LLMEngine):
    shed_rate              — fraction of a 4x-overcommit flood refused
                             at admission (bounded queue)
    retry_after_s          — backoff hint stamped on shed terminals
    deadline_queued/decode — both deadline stages observed terminally
    device_fault           — InjectedFault storm at engine.device_step:
                             terminal completeness + survived followup
    terminal_completeness  — EVERY submitted stream ended in exactly
                             one terminal event (the core contract)

  disagg leg (prefill + decode engines under the migration relay):
    migrate_fault / handoff_fault / device_fault storms against the
    disagg.migrate and disagg.handoff injection points and the shared
    device-step funnel: every request must still end in exactly one
    terminal (served, graceful re-prefill fallback, or error), a calm
    followup must be served, and both KV pools PLUS the host
    interchange must come out leak-clean

  gallery leg (one paged engine, engine/weight_pager.py):
    faults on the weights.demote D2H page-out (the model must stay hot
    and keep serving) and on the weights.fetch H2D layer stream (the
    promotion must fall back to one cold blocking load and the request
    still serve, with exactly one terminal event). Pager accounting
    must come out leak-clean after both storms.

  federation leg (balancer + 2 member instances over localhost HTTP):
    failover_latency_s     — kill a member; time until the breaker
                             opens via the active /healthz probe
                             (contract: < 2 s, vs STALE_S=60 passive)
    rerouted_ok            — connect-failure retry served the request
                             from the surviving node

  tracing leg (in-process balancer + ONE REAL server subprocess):
    an injected federated.upstream fault forces a reroute while a
    client-minted traceparent rides the request; the report joins the
    balancer's proxy trace (fault delivery + retry + terminal as span
    events) with the member process's /debug/traces?id= entry — one
    trace id spanning both processes.

Run:  python tools/profile_chaos.py [--flood N] [--probe-s S]

CPU smoke (tiny model, fast settings — what CI can afford):

  python tools/profile_chaos.py --smoke
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _build_engine(n_slots=4, max_seq=128):
    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    eng = LLMEngine(spec, params, tk, n_slots=n_slots, max_seq=max_seq,
                    prefill_buckets=(8, 32, 128), cache_dtype=jnp.float32)
    return eng, tk


def _drain(q, timeout=120):
    """(n_terminal_events, final). n_terminal MUST come out 1."""
    n_term, final = 0, None
    while final is None:
        ev = q.get(timeout=timeout)
        if ev.done:
            n_term, final = n_term + 1, ev
    # anything after the terminal breaks the exactly-once contract
    time.sleep(0.02)
    try:
        while True:
            if q.get_nowait().done:
                n_term += 1
    except Exception:
        pass
    return n_term, final


def engine_leg(flood: int) -> dict:
    from localai_tfp_tpu.engine.engine import GenRequest
    from localai_tfp_tpu.utils import faultinject as fi

    eng, tk = _build_engine()
    out: dict = {}
    complete = True
    try:
        # warm the jit paths so timings below measure policy, not compile
        eng.generate(GenRequest(prompt_ids=tk.encode("warm"), max_tokens=4,
                                ignore_eos=True))

        # ---- bounded-admission flood: 4x overcommit ----
        eng.max_queue = max(1, flood // 4)
        reqs = [GenRequest(prompt_ids=tk.encode(f"flood {i}"), max_tokens=4,
                           ignore_eos=True) for i in range(flood)]
        t0 = time.perf_counter()
        qs = eng.submit_many(reqs)
        finals = []
        for q in qs:
            n, ev = _drain(q)
            complete &= n == 1
            finals.append(ev)
        shed = [f for f in finals if f.finish_reason == "shed"]
        out["flood_requests"] = flood
        out["max_queue"] = eng.max_queue
        out["shed_rate"] = round(len(shed) / flood, 3)
        out["retry_after_s"] = (round(shed[0].retry_after_s, 2)
                                if shed else None)
        out["flood_wall_s"] = round(time.perf_counter() - t0, 3)
        eng.max_queue = 0

        # ---- deadlines: queued + mid-decode stage ----
        n, ev = _drain(eng.submit(GenRequest(
            prompt_ids=tk.encode("late"), max_tokens=4, ignore_eos=True,
            timeout_s=1e-6)))
        complete &= n == 1
        out["deadline_queued"] = ev.finish_reason == "deadline_exceeded"
        fi.arm("engine.device_step:delay@80")
        n, ev = _drain(eng.submit(GenRequest(
            prompt_ids=tk.encode("slow"), max_tokens=120, ignore_eos=True,
            timeout_s=0.5)))
        fi.disarm()
        complete &= n == 1
        out["deadline_decode"] = (ev.finish_reason == "deadline_exceeded"
                                  and 0 < ev.completion_tokens < 120)

        # ---- device-step fault storm, then a clean followup ----
        fi.arm("engine.device_step:rate@0.3@11")
        reasons: list[str] = []
        for i in range(8):
            n, ev = _drain(eng.submit(GenRequest(
                prompt_ids=tk.encode(f"storm {i}"), max_tokens=6,
                ignore_eos=True)))
            complete &= n == 1
            reasons.append(ev.finish_reason)
        injected = fi.counts()["engine.device_step"][1]
        fi.disarm()
        ev = eng.generate(GenRequest(prompt_ids=tk.encode("calm"),
                                     max_tokens=4, ignore_eos=True))
        out["device_fault"] = {
            "injected": injected,
            "errored": reasons.count("error"),
            "served": reasons.count("length"),
            "survived_followup": ev.finish_reason == "length",
        }
        out["terminal_completeness"] = complete
        if eng._pool is not None:
            eng._pool.leak_check()
            out["kv_pool_leak_check"] = "clean"
    finally:
        eng.close()
    return out


def disagg_leg(flood: int) -> dict:
    """Chaos on the disaggregated relay: migration-capture faults,
    handoff faults, and a device-step storm across BOTH engines — every
    request must still end in exactly one terminal (served, fallback
    re-prefill, or error), and both pools plus the host interchange
    must come out leak-clean."""
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import GenRequest
    from localai_tfp_tpu.engine.kv_migrate import (DisaggRouter,
                                                   build_prefill_engine)
    from localai_tfp_tpu.utils import faultinject as fi

    saved = {k: os.environ.get(k) for k in
             ("LOCALAI_DISAGG_MIN_PROMPT", "LOCALAI_KV_PAGE")}
    os.environ["LOCALAI_DISAGG_MIN_PROMPT"] = "32"
    # 16-token pages: the default 256-token page sizes the pool at
    # exactly one page per slot, so staging an adoption would always
    # hit pool exhaustion and the leg would only ever measure fallbacks
    os.environ.setdefault("LOCALAI_KV_PAGE", "16")
    eng, tk = _build_engine(max_seq=256)
    prefill = build_prefill_engine(eng.spec, eng.params, tk, decode=eng,
                                   cache_dtype=jnp.float32)
    router = DisaggRouter(prefill, eng)
    router.start()
    out: dict = {}
    long = "disagg chaos probe " + "x " * 24

    def storm(tag: str) -> list:
        reqs = [GenRequest(prompt_ids=tk.encode(f"{tag} {i:02d} " + long),
                           max_tokens=4, ignore_eos=True)
                for i in range(flood)]
        reasons = []
        for q in router.submit_many(reqs):
            n, ev = _drain(q)
            nonlocal_complete[0] &= n == 1
            reasons.append(ev.finish_reason)
        return reasons

    nonlocal_complete = [True]
    try:
        # warm the relay (compiles + a clean adoption)
        ev = router.generate(GenRequest(prompt_ids=tk.encode("w " + long),
                                        max_tokens=4, ignore_eos=True))
        assert ev.finish_reason == "length", ev.error

        legs = {
            "migrate_fault": "disagg.migrate:rate@0.5@3",
            "handoff_fault": "disagg.handoff:rate@0.5@5",
            "device_fault": "engine.device_step:rate@0.2@13",
        }
        for name, spec in legs.items():
            fb0 = eng._migrator.counters["adoptions"]
            fi.arm(spec)
            reasons = storm(name)
            injected = {p: c[1] for p, c in fi.counts().items()}
            fi.disarm()
            out[name] = {
                "injected": injected,
                "reasons": {r: reasons.count(r) for r in set(reasons)},
                "served_or_errored": all(
                    r in ("length", "error", "stop") for r in reasons),
                "adoptions": eng._migrator.counters["adoptions"] - fb0,
            }
        # a clean followup proves both engines survived the storms
        ev = router.generate(GenRequest(prompt_ids=tk.encode("calm " + long),
                                        max_tokens=4, ignore_eos=True))
        out["survived_followup"] = ev.finish_reason == "length"
        out["terminal_completeness"] = nonlocal_complete[0]
        out["fallbacks"] = router.prefill._migrator.counters[
            "capture_faults"]
        time.sleep(0.3)
        eng._pool.leak_check()
        prefill._pool.leak_check()
        assert router.bus.live_blocks() == 0, "interchange leak"
        out["kv_pool_leak_check"] = "clean"
        out["interchange_leak_check"] = "clean"
    finally:
        fi.disarm()
        router.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def gallery_leg() -> dict:
    """Chaos on the weight pager: a demote fault must leave the model
    hot and serving; a fetch fault mid-promotion must fall back to one
    cold blocking load with the request still served — exactly one
    terminal event either way, and the pager leak-clean after both."""
    from localai_tfp_tpu.engine.engine import GenRequest
    from localai_tfp_tpu.utils import faultinject as fi

    saved = os.environ.get("LOCALAI_WEIGHT_PAGING")
    os.environ["LOCALAI_WEIGHT_PAGING"] = "on"
    eng, tk = _build_engine()
    out: dict = {}

    def demote_now(timeout=30.0):
        t0 = time.monotonic()
        while not eng._pager.request_demote():
            if time.monotonic() - t0 > timeout:
                raise TimeoutError("engine never went quiet")
            time.sleep(0.01)
        assert eng._pager.settle(timeout)

    try:
        pager = eng._pager
        ev = eng.generate(GenRequest(prompt_ids=tk.encode("warm"),
                                     max_tokens=4, ignore_eos=True))
        assert ev.finish_reason == "length", ev.error

        # ---- fault on the D2H page-out: abandon, stay hot, serve ----
        fi.arm("weights.demote:fail@1")
        demote_now()
        fi.disarm()
        n, ev = _drain(eng.submit(GenRequest(
            prompt_ids=tk.encode("after demote fault"), max_tokens=4,
            ignore_eos=True)))
        out["demote_fault"] = {
            "stayed_hot": pager.state == "hot"
            and eng.params is not None,
            "faulted_demotes": pager.counters["faulted_demotes"],
            "served": ev.finish_reason == "length" and n == 1,
        }

        # ---- fault on the H2D layer stream: cold fallback, serve ----
        demote_now()
        assert pager.state == "warm" and eng.params is None
        fi.arm("weights.fetch:fail@1")
        n, ev = _drain(eng.submit(GenRequest(
            prompt_ids=tk.encode("after fetch fault"), max_tokens=4,
            ignore_eos=True)))
        fi.disarm()
        out["fetch_fault"] = {
            "cold_fallbacks": pager.counters["cold_fallbacks"],
            "promoted_hot": pager.state == "hot",
            "served": ev.finish_reason == "length",
            "one_terminal": n == 1,
        }
        pager.leak_check()
        out["pager_leak_check"] = "clean"
        out["stats"] = pager.stats()
    finally:
        fi.disarm()
        eng.close()
        if saved is None:
            os.environ.pop("LOCALAI_WEIGHT_PAGING", None)
        else:
            os.environ["LOCALAI_WEIGHT_PAGING"] = saved
    return out


def _spawn_member(models_dir: str, cwd: str, port: int):
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("LOCALAI_FAULTS", None)  # faults stay balancer-side here
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    return subprocess.Popen(
        [sys.executable, "-m", "localai_tfp_tpu.cli", "run",
         "--models-path", models_dir, "--address", "127.0.0.1",
         "--port", str(port)],
        cwd=cwd, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


async def tracing_leg() -> dict:
    """One trace id across two processes: an in-process balancer (with
    an injected upstream fault forcing a failover) proxying to a REAL
    server subprocess, joined by ``/debug/traces?id=``."""
    import socket
    import tempfile
    import urllib.request

    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )
    from localai_tfp_tpu.telemetry.tracing import (
        TRACER, make_traceparent, mint_trace_id,
    )
    from localai_tfp_tpu.utils import faultinject as fi

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    out: dict = {}
    member = None
    with tempfile.TemporaryDirectory() as tmp:
        models = os.path.join(tmp, "models")
        cwd = os.path.join(tmp, "member")
        os.makedirs(models)
        os.makedirs(cwd)
        # zero-checkpoint config: the tts backend serves /v1/models
        # with no model files, so the member boots in seconds
        with open(os.path.join(models, "voice.yaml"), "w") as f:
            f.write("name: voice\nbackend: jax-tts\n")
        member = _spawn_member(models, cwd, port)
        try:
            base = f"http://127.0.0.1:{port}"
            t0 = time.time()
            while time.time() - t0 < 120:
                try:
                    urllib.request.urlopen(base + "/readyz", timeout=2)
                    break
                except Exception:
                    time.sleep(0.3)
            else:
                raise TimeoutError("member server never became ready")

            tok = generate_token()
            fed = FederatedServer(tok, probe_s=0.0)
            client = TestClient(TestServer(fed.build_app()))
            await client.start_server()
            try:
                # the SAME member registered under two node ids: the
                # injected first-attempt fault reroutes to "the other
                # node" and still lands — a failover that needs only
                # one real process
                for nid in ("m1", "m2"):
                    r = await client.post("/federation/register", json={
                        "token": tok, "id": nid, "name": nid,
                        "address": base})
                    assert r.status == 200

                fi.arm("federated.upstream:fail@1")
                tid = mint_trace_id()
                r = await client.get(
                    "/v1/models",
                    headers={"traceparent": make_traceparent(tid)})
                out["proxied_status"] = r.status
                out["echoed_traceparent"] = tid in r.headers.get(
                    "traceparent", "")
                fi.disarm()

                balancer = TRACER.lookup(tid)
                names = [n["name"] for tr in balancer
                         for n in tr.get("span_events", [])]
                points = [n.get("point") for tr in balancer
                          for n in tr.get("span_events", [])]
                with urllib.request.urlopen(
                        f"{base}/debug/traces?id={tid}",
                        timeout=10) as resp:
                    remote = json.loads(resp.read()).get("traces", [])
                out["trace_id"] = tid
                out["balancer_entries"] = len(balancer)
                out["fault_on_trace"] = "federated.upstream" in points
                out["failover_on_trace"] = "retry" in names
                out["member_entries"] = len(remote)
                out["member_joined_by_trace_id"] = all(
                    tr.get("trace_id") == tid for tr in remote) and bool(
                    remote)
                out["one_trace_id_both_processes"] = (
                    out["fault_on_trace"] and out["failover_on_trace"]
                    and out["member_joined_by_trace_id"])
            finally:
                fi.disarm()
                await client.close()
        finally:
            if member is not None:
                member.terminate()
                try:
                    member.wait(timeout=10)
                except Exception:
                    member.kill()
    return out


async def federation_leg(probe_s: float) -> dict:
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )

    async def handler(request):
        return web.json_response({"ok": True})

    def member():
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        return TestServer(app)

    doomed, healthy = member(), member()
    await doomed.start_server()
    await healthy.start_server()
    tok = generate_token()
    fed = FederatedServer(tok, probe_s=probe_s)
    client = TestClient(TestServer(fed.build_app()))
    await client.start_server()
    out: dict = {"probe_s": probe_s}
    try:
        for nid, m in (("a-doomed", doomed), ("b-healthy", healthy)):
            r = await client.post("/federation/register", json={
                "token": tok, "id": nid, "name": nid,
                "address": f"http://127.0.0.1:{m.port}"})
            assert r.status == 200

        # kill a member: how long until the breaker routes around it?
        t0 = time.monotonic()
        await doomed.close()
        node = fed.registry._nodes["a-doomed"]
        while (fed.registry.state(node) != "open"
               and time.monotonic() - t0 < 10.0):
            await asyncio.sleep(0.02)
        opened = fed.registry.state(node) == "open"
        out["failover_latency_s"] = (round(time.monotonic() - t0, 2)
                                     if opened else None)
        out["failover_under_2s"] = opened and out["failover_latency_s"] < 2

        # connect-failure retry: the request lands on the survivor even
        # if the balancer tries the corpse first
        r = await client.post("/v1/models", data=b"x")
        out["rerouted_ok"] = (r.status == 200
                              and fed.registry._nodes[
                                  "b-healthy"].requests_served >= 1)
    finally:
        await client.close()
        await healthy.close()
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--flood", type=int, default=32,
                    help="flood size for the bounded-admission leg")
    ap.add_argument("--probe-s", type=float, default=0.1,
                    help="active /healthz probe interval for the "
                         "failover-latency leg")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings (flood=12)")
    args = ap.parse_args()
    if args.smoke:
        args.flood = 12

    report = {
        "engine": engine_leg(args.flood),
        "disagg": disagg_leg(max(4, args.flood // 4)),
        "gallery": gallery_leg(),
        "federation": asyncio.run(federation_leg(args.probe_s)),
        "tracing": asyncio.run(tracing_leg()),
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
