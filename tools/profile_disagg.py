"""Disaggregated-serving profiler: decode ITL with prefill off-loaded.

The disaggregated relay (engine/kv_migrate.py) exists for one number:
the inter-token cadence of LIVE decode streams while long prompts keep
arriving. On a single engine every admitted long prompt runs its
prefill dispatch inside the same serial device loop that produces
decode tokens, so active streams stall for the full prefill. With
disaggregation the prefill runs on a sibling engine and the finished
KV pages migrate through the host interchange — the decode loop only
ever pays a page-scatter adoption.

This tool drives the SAME workload through both configurations and
prints one JSON report:

  off leg (single engine):  N sustained decode streams + a flood of
      long prompts admitted mid-decode; per-stream inter-token gaps.
  on  leg (prefill + decode engines under DisaggRouter): identical
      traffic; additionally migration wall p50/p95, the zero-re-prefill
      cross-check (the decode engine's prompt-token counter must not
      move during the flood, and the migrated-pages counter must equal
      flood_requests x pages_per_prompt), and the router path counts.
  identity leg: one seeded request (temperature/top_k/seed) run on a
      plain engine and through the relay — the outputs must match
      byte for byte (the migrated sampler row carries the rng state).

Acceptance gates (process exits non-zero if any fail): decode ITL p99
AND the max inter-token gap must be STRICTLY better with disagg on,
migrated requests re-prefill zero tokens, and the seeded outputs are
identical.

Run:  python tools/profile_disagg.py [--streams N] [--flood M]
          [--decode-tokens D]

CPU smoke (tiny model, fast settings — what CI can afford):

  python tools/profile_disagg.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import queue as _queue
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_ENV_KNOBS = ("LOCALAI_DISAGG_MIN_PROMPT",
              "LOCALAI_DISAGG_MIGRATE_DEADLINE_S")


def _pct(xs, p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1))))]


def _model():
    import jax
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    return spec, params, tk


def _decode_engine(spec, params, tk, max_seq=512,
                   buckets=(8, 32, 256)):
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import LLMEngine

    return LLMEngine(spec, params, tk, n_slots=4, max_seq=max_seq,
                     prefill_buckets=buckets, cache_dtype=jnp.float32)


def _watch(q, times: list, finals: list) -> None:
    """Drain one stream, stamping the arrival time of every
    token-bearing event (buffered emission coalesces identically in
    both legs, so the gap series is comparable)."""
    while True:
        ev = q.get(timeout=600)
        if ev.token_id is not None:
            times.append(time.perf_counter())
        if ev.done:
            finals.append(ev)
            return


def _leg(sub, tk, n_streams: int, flood_n: int, d_tokens: int,
         long_body: str) -> dict:
    """One contrast leg: sustain ``n_streams`` decode streams on
    ``sub`` (an engine or a router — same submit surface), flood
    ``flood_n`` long prompts mid-decode, return the gap series."""
    from localai_tfp_tpu.engine.engine import GenRequest

    times: list[list[float]] = [[] for _ in range(n_streams)]
    finals: list[list] = [[] for _ in range(n_streams)]
    qs = sub.submit_many([
        GenRequest(prompt_ids=tk.encode(f"stream {i:02d}"),
                   max_tokens=d_tokens, temperature=0.0,
                   ignore_eos=True)
        for i in range(n_streams)])
    watchers = []
    for i, q in enumerate(qs):
        t = threading.Thread(target=_watch, args=(q, times[i], finals[i]),
                             daemon=True)
        t.start()
        watchers.append(t)
    # flood only once every stream is decoding: the gaps then measure
    # admission interference, not startup order
    t0 = time.perf_counter()
    while (any(len(ts) < 2 for ts in times)
           and time.perf_counter() - t0 < 120):
        time.sleep(0.005)
    t_flood = time.perf_counter()
    flood_qs = []
    for j in range(flood_n):
        flood_qs += sub.submit_many([GenRequest(
            prompt_ids=tk.encode(f"ctx {j:02d} " + long_body),
            max_tokens=4, temperature=0.0, ignore_eos=True)])
        time.sleep(0.05)
    flood_finals = []
    for q in flood_qs:
        while True:
            ev = q.get(timeout=600)
            if ev.done:
                flood_finals.append(ev)
                break
    for t in watchers:
        t.join(timeout=600)
    gaps = [1e3 * (b - a)
            for ts in times for a, b in zip(ts, ts[1:])]
    assert gaps, "streams produced no inter-token gaps"
    bad = [f.finish_reason for f in flood_finals + sum(finals, [])
           if f.finish_reason != "length"]
    return {
        "streams": n_streams, "flood_requests": flood_n,
        "decode_tokens": d_tokens,
        "itl_p50_ms": round(_pct(gaps, 50), 2),
        "itl_p99_ms": round(_pct(gaps, 99), 2),
        "max_gap_ms": round(max(gaps), 2),
        "gap_samples": len(gaps),
        "flood_wall_s": round(time.perf_counter() - t_flood, 3),
        "non_length_finishes": bad,
    }


def _warm(sub, tk, long_body: str, n_streams: int) -> None:
    """Compile every dispatch variant the measured waves hit — short
    prefill, the long prefill bucket, decode AT MEASUREMENT
    CONCURRENCY (the step dispatch specializes on active-slot count),
    and — through a router — the probe/adoption path — so gaps measure
    scheduling, not the jit."""
    from localai_tfp_tpu.engine.engine import GenRequest

    qs = sub.submit_many(
        [GenRequest(prompt_ids=tk.encode(f"warm stream {i:02d}"),
                    max_tokens=12, temperature=0.0, ignore_eos=True)
         for i in range(n_streams)]
        + [GenRequest(prompt_ids=tk.encode("warm " + long_body),
                      max_tokens=4, temperature=0.0, ignore_eos=True)])
    for q in qs:
        while True:
            ev = q.get(timeout=600)
            if ev.done:
                assert ev.finish_reason == "length", ev.error
                break


def identity_leg(spec, params, tk) -> dict:
    """Seeded relay identity: the migrated sampler row must continue
    the EXACT rng/penalty stream, so plain-engine output and relay
    output match byte for byte."""
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import GenRequest
    from localai_tfp_tpu.engine.kv_migrate import (DisaggRouter,
                                                   build_prefill_engine)

    prompt = "disaggregated migration identity probe " + "w " * 24

    def seeded(sub):
        return sub.generate(GenRequest(
            prompt_ids=tk.encode(prompt), max_tokens=12,
            temperature=0.8, top_k=40, seed=7, ignore_eos=True))

    plain = _decode_engine(spec, params, tk)
    try:
        ref = seeded(plain)
    finally:
        plain.close()
    decode = _decode_engine(spec, params, tk)
    prefill = build_prefill_engine(spec, params, tk, decode=decode,
                                   cache_dtype=jnp.float32)
    router = DisaggRouter(prefill, decode)
    router.start()
    try:
        got = seeded(router)
        migrated = decode._migrator.counters["adoptions"] == 1
    finally:
        router.close()
    return {
        "prompt_tokens": len(tk.encode(prompt)),
        "migrated": migrated,
        "off_text": ref.full_text,
        "on_text": got.full_text,
        "identical": (got.full_text == ref.full_text
                      and got.completion_tokens == ref.completion_tokens
                      and migrated),
    }


def disagg_contrast(smoke: bool = True, n_streams: int = 3,
                    flood_n: int = 0, d_tokens: int = 0) -> dict:
    """The full contrast report (importable — bench.py's extra.disagg
    block calls this on the smoke settings)."""
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.kv_migrate import (DisaggRouter,
                                                   build_prefill_engine)
    from localai_tfp_tpu.telemetry.registry import REGISTRY

    flood_n = flood_n or (4 if smoke else 12)
    d_tokens = d_tokens or (64 if smoke else 192)
    long_body = "w " * 112  # ~230 tokens: the 256-token prefill bucket

    saved = {k: os.environ.get(k) for k in _ENV_KNOBS}
    os.environ["LOCALAI_DISAGG_MIN_PROMPT"] = "64"
    os.environ["LOCALAI_DISAGG_MIGRATE_DEADLINE_S"] = "60"
    os.environ.setdefault("LOCALAI_KV_PAGE", "16")
    spec, params, tk = _model()
    report: dict = {"smoke": smoke}
    try:
        # ---- off leg: one engine owns both prefill and decode ----
        eng = _decode_engine(spec, params, tk)
        try:
            # full variant warmup: the adaptive k-scan picks its scan
            # length at run time, and a cold k jitting mid-measurement
            # would swamp the gap series in BOTH legs
            eng.warmup()
            _warm(eng, tk, long_body, n_streams)
            report["off"] = _leg(eng, tk, n_streams, flood_n, d_tokens,
                                 long_body)
            eng._pool.leak_check()
        finally:
            eng.close()

        # ---- on leg: prefill sibling + migration relay ----
        decode = _decode_engine(spec, params, tk)
        prefill = build_prefill_engine(spec, params, tk, decode=decode,
                                       cache_dtype=jnp.float32)
        router = DisaggRouter(prefill, decode)
        router.start()
        try:
            # time every successful collect: the same window the
            # router prices as migration wall
            mig_ms: list[float] = []
            real_collect = router.bus.collect

            def timed_collect(rid, timeout):
                c0 = time.perf_counter()
                h, why = real_collect(rid, timeout)
                if h is not None:
                    mig_ms.append(1e3 * (time.perf_counter() - c0))
                return h, why

            router.bus.collect = timed_collect
            router.warmup()
            _warm(router, tk, long_body, n_streams)
            base = REGISTRY.snapshot()
            mig_ms.clear()
            prompt0 = decode.metrics.prompt_tokens_processed
            adopt0 = decode._migrator.counters["adoptions"]
            on = _leg(router, tk, n_streams, flood_n, d_tokens,
                      long_body)
            delta = REGISTRY.delta(base)
            adoptions = decode._migrator.counters["adoptions"] - adopt0
            # zero re-prefill: the decode engine's prompt counter may
            # only move for the short LOCAL streams, never the flood
            stream_prompt = sum(
                len(tk.encode(f"stream {i:02d}"))
                for i in range(n_streams))
            prompt_moved = (decode.metrics.prompt_tokens_processed
                            - prompt0)
            npg_per = decode._pool.pages_for(
                len(tk.encode("ctx 00 " + long_body)))
            migrated_pages = sum(
                v for k, v in delta.items()
                if k.startswith("engine_kv_migrated_pages_total")
                and 'outcome="migrated"' in k)
            on["migration_ms"] = {
                "p50": round(_pct(mig_ms, 50), 2),
                "p95": round(_pct(mig_ms, 95), 2),
                "n": len(mig_ms),
            }
            on["adoptions"] = adoptions
            on["fallbacks"] = sum(
                v for k, v in delta.items()
                if k.startswith("engine_disagg_requests_total")
                and 'path="fallback"' in k)
            on["decode_prompt_tokens"] = prompt_moved
            on["stream_prompt_tokens"] = stream_prompt
            on["migrated_pages"] = migrated_pages
            on["expected_pages"] = flood_n * npg_per
            report["on"] = on
            time.sleep(0.2)
            decode._pool.leak_check()
            prefill._pool.leak_check()
            assert router.bus.live_blocks() == 0
            report["zero_reprefill"] = (
                adoptions == flood_n
                and prompt_moved <= stream_prompt
                and migrated_pages == flood_n * npg_per)
        finally:
            router.close()

        report["identity"] = identity_leg(spec, params, tk)
        report["itl_p99_improved"] = (report["on"]["itl_p99_ms"]
                                      < report["off"]["itl_p99_ms"])
        report["max_gap_improved"] = (report["on"]["max_gap_ms"]
                                      < report["off"]["max_gap_ms"])
        report["ok"] = (report["itl_p99_improved"]
                        and report["max_gap_improved"]
                        and report["zero_reprefill"]
                        and report["identity"]["identical"])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return report


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=3,
                    help="sustained decode streams")
    ap.add_argument("--flood", type=int, default=0,
                    help="long prompts flooded mid-decode "
                         "(default 12, smoke 4)")
    ap.add_argument("--decode-tokens", type=int, default=0,
                    help="tokens per sustained stream "
                         "(default 192, smoke 64)")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings")
    args = ap.parse_args()
    report = disagg_contrast(args.smoke, args.streams, args.flood,
                             args.decode_tokens)
    print(json.dumps(report, indent=2), flush=True)
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
