"""TTFT decomposition probe for the 8B serving config (round-4 perf work).

Reconstructs bench.py's 8B leg, then instruments:
  1. engine wave: every _run dispatch (kind, wall ms) during a 64-deep burst
  2. HTTP wave: per-request phase timestamps (handler entry -> body -> load
     -> template -> submit -> first token -> first write)

Prints a JSON report. Not part of the test suite; run manually on the chip:
    python tools/profile_ttft.py [--small]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


class WideByteTok:
    """bench.py's WideByteTok (defined inside its main; re-declared here)."""

    def __new__(cls):
        from localai_tfp_tpu.engine.tokenizer import ByteTokenizer

        class _T(ByteTokenizer):
            def decode(self, ids):
                return "".join(
                    chr(32 + (i % 95)) for i in ids
                    if i not in (self.bos_id, *self.eos_ids)
                )

        return _T()


def build_engine(small: bool):
    from bench import _fast_int8_params  # type: ignore

    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.models.llm_spec import LLMSpec, tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tok = WideByteTok()
    if small:
        spec = tiny_spec(vocab_size=258)
        params = init_params(jax.random.PRNGKey(0), spec)
        eng = LLMEngine(spec, params, tok, n_slots=4, max_seq=256,
                        decode_steps=8, cache_dtype=jnp.bfloat16,
                        autostart=False)
        n_req, n_tok = 4, 32
    else:
        spec = LLMSpec(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
            rope_theta=500000.0,
        )
        params = _fast_int8_params(spec)
        eng = LLMEngine(spec, params, tok, n_slots=64, max_seq=1024,
                        decode_steps=16, cache_dtype="int8",
                        autostart=False)
        n_req, n_tok = 64, 256
    eng.start()
    eng.warmup()
    return eng, tok, n_req, n_tok


def wave(eng, tok, n_req, n_tok):
    from bench import _run_wave  # type: ignore

    return _run_wave(eng, tok, n_req, n_tok, "benchmark " * 12)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--no-http", action="store_true")
    args = ap.parse_args()

    jax.config.update("jax_compilation_cache_dir", "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    import sys

    sys.path.insert(0, "/root/repo")

    eng, tok, n_req, n_tok = build_engine(args.small)

    # -------- warmups (compile everything) --------
    for _ in range(2):
        _, _, _, errs = wave(eng, tok, n_req, n_tok)
        if errs:
            raise RuntimeError(errs[0])

    # -------- instrument _run --------
    log = []
    orig_run = eng._run

    def traced_run(kind, payload):
        t0 = time.perf_counter()
        out = orig_run(kind, payload)
        shape = (list(payload["toks"].shape)
                 if kind.startswith("prefill") else payload.get("k"))
        log.append((kind, round((time.perf_counter() - t0) * 1e3, 2),
                    round(t0, 4), shape))
        return out

    rems = []
    orig_assign = eng._assign

    def traced_assign(slot, req, out):
        pre = len(slot.cache_tokens)
        orig_assign(slot, req, out)
        rems.append((slot.idx, pre, slot.n_past,
                     slot.n_prompt - slot.n_past))

    eng._assign = traced_assign
    eng._run = traced_run
    t_wave = time.perf_counter()
    total, wall, ttfts, errs = wave(eng, tok, n_req, n_tok)
    eng._run = orig_run
    eng._assign = orig_assign
    print("ASSIGN (slot, cache_len, n_past, rem):", rems[:10], flush=True)
    if errs:
        print("ENGINE WAVE ERRORS:", errs[:2], flush=True)
    report = {
        "engine_wave": {
            "tok_s": round(total / wall, 1),
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1),
            "ttft_min_ms": round(ttfts[0], 1),
            "ttft_max_ms": round(ttfts[-1], 1),
            "dispatches": [
                {"kind": k, "ms": ms, "at_ms": round((at - t_wave) * 1e3, 1),
                 "shape": sh}
                for k, ms, at, sh in log[:40]
            ],
            "n_dispatches": len(log),
        },
    }
    print(json.dumps(report, indent=1), flush=True)  # engine leg first —
    # the HTTP leg must not be able to lose it
    if args.no_http:
        eng.close()
        return

    # -------- HTTP leg with phase timestamps --------
    import asyncio
    import os
    import tempfile

    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import LoadedModel
    from localai_tfp_tpu.server import openai_routes
    from localai_tfp_tpu.server.app import build_app
    from localai_tfp_tpu.server.state import Application
    from localai_tfp_tpu.workers.llm import JaxLLMBackend

    tmp = tempfile.mkdtemp(prefix="prof-srv-")
    models = os.path.join(tmp, "models")
    os.makedirs(models)
    with open(os.path.join(models, "bench.yaml"), "w") as f:
        f.write(
            "name: bench\nbackend: jax-llm\n"
            "parameters:\n  model: bench\n"
            "template:\n"
            '  chat_message: "{{.RoleName}}: {{.Content}}"\n'
            '  chat: "{{.Input}}\\nassistant:"\n'
        )
    state = Application(ApplicationConfig(
        models_path=models,
        generated_content_dir=os.path.join(tmp, "generated"),
        upload_dir=os.path.join(tmp, "uploads"),
        config_dir=os.path.join(tmp, "configuration"),
    ))
    backend = JaxLLMBackend()
    backend.engine, backend.tokenizer = eng, tok
    backend.spec, backend._state = eng.spec, "READY"
    state.model_loader._models["bench"] = LoadedModel(
        "bench", "jax-llm", backend)
    app = build_app(state)

    # trace engine dispatches during the HTTP waves too
    http_log: list = []
    orig2 = eng._run

    def traced2(kind, payload):
        t0 = time.perf_counter()
        shape = None
        if kind in ("prefill", "prefill_final"):
            shape = list(payload["toks"].shape)
        out = orig2(kind, payload)
        http_log.append((kind, shape,
                         round((time.perf_counter() - t0) * 1e3, 1), t0))
        return out

    eng._run = traced2

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def one(i, t0, ttfts, first_byte):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": "benchmark " * 10 + str(i)}],
                    "max_tokens": n_tok, "stream": True,
                    "temperature": 0.8, "top_k": 40, "top_p": 0.95,
                    "ignore_eos": True,
                }
                total = 0
                t_req = time.perf_counter()
                async with sess.post(url, json=body,
                                     headers={"Extra-Usage": "1"}) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        if first_byte[i] is None:
                            first_byte[i] = (time.perf_counter() - t0) * 1e3
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = json.loads(line[6:])
                        ch = d["choices"][0]
                        if (ch["delta"].get("content")
                                and ttfts[i] is None):
                            ttfts[i] = (time.perf_counter() - t0) * 1e3
                        if ch.get("finish_reason"):
                            if ch["finish_reason"] == "error" and i == 0:
                                print("HTTP STREAM ERROR:", d, flush=True)
                            u = d.get("usage") or {}
                            total = u.get("completion_tokens", 0)
                return total, (time.perf_counter() - t_req) * 1e3

            results = {}
            for run in range(3):  # 2 warmup + 1 measured
                ttfts = [None] * n_req
                first_byte = [None] * n_req
                t0 = time.perf_counter()
                totals = await asyncio.gather(
                    *[one(i, t0, ttfts, first_byte) for i in range(n_req)])
                wall = time.perf_counter() - t0
                if run < 2:
                    continue
                tt = sorted(t for t in ttfts if t is not None) or [0.0]
                fb = sorted(t for t in first_byte if t is not None) or [0.0]
                results = {
                    "tok_s": round(sum(t for t, _ in totals) / wall, 1),
                    "ttft_p50_ms": round(tt[len(tt) // 2], 1),
                    "ttft_min_ms": round(tt[0], 1),
                    "ttft_max_ms": round(tt[-1], 1),
                    "first_byte_p50_ms": round(fb[len(fb) // 2], 1),
                    "n_with_content": len([t for t in ttfts
                                           if t is not None]),
                }
            return results

    loop = asyncio.new_event_loop()
    try:
        t_http0 = time.perf_counter()
        report["http_wave"] = loop.run_until_complete(drive())
    finally:
        loop.close()

    eng.close()
    # last ~120 dispatches of the HTTP leg with timestamps
    report["http_dispatches"] = [
        {"kind": k, "shape": s, "ms": ms,
         "at_s": round(at - t_http0, 2)}
        for k, s, ms, at in http_log[-120:]
    ]
    report["http_n_dispatches"] = len(http_log)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
