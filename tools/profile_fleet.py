"""Fleet profile: the telemetry digest plane measured end to end.

Boots N REAL member servers (subprocesses, tiny CPU checkpoint) behind
an in-process federated balancer, drives mixed streaming traffic
through the balancer, and prints one JSON report with the acceptance
numbers the fleet-telemetry PR tracks:

  percentile cross-check:
    client_ttft_p95_s       — p95 of client-measured time-to-first-
                              content-chunk across every request
    fleet_ttft_p95_bounds_s — the bucket holding p95 in the balancer's
                              merged digest histogram (/fleet/metrics)
    ttft_within_one_bucket  — |client bucket - digest bucket| <= 1:
                              exact bucket merges put the fleet p95
                              within one histogram bucket of what
                              clients actually saw (the contract that
                              forbids averaging per-node percentiles)

  digest plane health:
    digest_bytes_max        — largest /telemetry/digest body observed
                              (contract: <= LOCALAI_DIGEST_MAX_BYTES)
    digest_age_max_s        — staleness across nodes right after the
                              traffic wave (probe-refreshed, so this
                              tracks the probe interval, not the 20 s
                              heartbeat)
    load_skew               — max(requests_served) / mean — least-used
                              routing should keep this near 1.0

  SLO burn-rate monitor:
    slo_flip_latency_s      — kill one member; seconds until the
                              availability objective on /fleet/slo
                              leaves "ok" (fast/slow windows shrunk via
                              env so the flip is observable in a smoke)
    slo_flip_within_2_probes— latency <= 2 probe intervals (+ sched
                              slack): the first failed probe marks the
                              node not-serving, the second confirms
    metrics_served_during_kill — /fleet/metrics kept answering 200
                              while the fleet was degraded

Run:  python tools/profile_fleet.py [--members N] [--requests N]
                                    [--probe-s S] [--json]

CPU smoke (what CI can afford):  python tools/profile_fleet.py --smoke
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import re
import socket
import sys
import tempfile
import time
from bisect import bisect_left

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# SLO windows shrunk so a burn-rate flip is observable inside a smoke
# run; generous TTFT threshold so first-request compiles cannot push
# the latency objective into warning and muddy the availability check
_SMOKE_ENV = {
    "LOCALAI_SLO_FAST_WINDOW_S": "1",
    "LOCALAI_SLO_SLOW_WINDOW_S": "5",
    "LOCALAI_SLO_TTFT_P95_MS": "30000",
    "LOCALAI_SLO_ITL_P99_MS": "30000",
}

_TINY_YAML = """
name: tiny
backend: jax-llm
parameters:
  model: tiny-ckpt
  temperature: 0.0
  max_tokens: 16
context_size: 128
max_batch_slots: 2
dtype: float32
template:
  completion: "{{.Input}}"
  chat_message: "{{.RoleName}}: {{.Content}}"
  chat: "{{.Input}}\\nassistant:"
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_models(models_dir: str, *, hidden: int = 64,
                 inter: int = 128, heads: int = 4, kv_heads: int = 2,
                 ctx: int = 128) -> None:
    """Tiny torch Llama checkpoint + config: real jax-llm members that
    boot (and first-request compile) in seconds on CPU. The routing
    leg widens it (hidden/ctx up, still 2 layers so XLA compile stays
    seconds): prefill compute must be MEASURABLE there, because the
    locality win a hit buys IS the skipped prefill — on the 64-wide
    model a full prefill and a tail prefill differ by ~2 ms, under
    per-request noise."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=300, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=2, num_attention_heads=heads,
        num_key_value_heads=kv_heads,
        max_position_embeddings=max(256, 2 * ctx),
    )).save_pretrained(os.path.join(models_dir, "tiny-ckpt"),
                       safe_serialization=True)
    with open(os.path.join(models_dir, "tiny.yaml"), "w") as f:
        f.write(_TINY_YAML.replace("context_size: 128",
                                   f"context_size: {ctx}"))


def _spawn_member(models_dir: str, cwd: str, port: int, *,
                  balancer_url: str, token: str, name: str):
    """One REAL member: announces itself (digest riding the heartbeat)
    and serves the balancer's /healthz + /telemetry/digest probes."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["LOCALAI_WARMUP"] = "0"  # skip warmup decode: fast boot
    env["LOCALAI_NODE_NAME"] = name
    env.pop("LOCALAI_FAULTS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    return subprocess.Popen(
        [sys.executable, "-m", "localai_tfp_tpu.cli", "run",
         "--models-path", models_dir, "--address", "127.0.0.1",
         "--port", str(port),
         "--p2p-token", token,
         "--federated-server", balancer_url,
         "--advertise-address", f"http://127.0.0.1:{port}"],
        cwd=cwd, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


async def _wait_ready(session, base: str, timeout_s: float = 180.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            async with session.get(base + "/readyz") as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        await asyncio.sleep(0.3)
    raise TimeoutError(f"member {base} never became ready")


async def _chat_ttft(client, prompt: str = "", max_tokens: int = 8,
                     messages=None) -> float:
    """One streaming chat completion through the balancer; returns the
    client-measured time to the first GENERATED event — the first chunk
    after the role preamble (which is written before generation
    starts). A tiny random checkpoint can emit tokens whose bytes decode
    to empty text, so the finish chunk is an accepted (late) fallback —
    at smoke token counts it lands in the same log bucket."""
    t0 = time.perf_counter()
    resp = await client.request(
        "POST", "/v1/chat/completions",
        json={"model": "tiny", "stream": True, "max_tokens": max_tokens,
              "messages": messages
              or [{"role": "user", "content": prompt}]})
    assert resp.status == 200, f"proxy status {resp.status}"
    ttft = None
    async for raw in resp.content:
        line = raw.decode("utf-8", "replace").strip()
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        try:
            ev = json.loads(line[len("data: "):])
        except ValueError:
            continue
        choice = (ev.get("choices") or [{}])[0]
        delta = choice.get("delta") or {}
        finish = choice.get("finish_reason")
        if finish == "error":
            raise RuntimeError(f"stream errored: {ev}")
        if ttft is None and "role" not in delta and (
                delta.get("content") or finish is not None):
            ttft = time.perf_counter() - t0
    resp.release()
    if ttft is None:
        raise RuntimeError("stream produced no generated event")
    return ttft


def _prom_hist(text: str, family: str) -> list[tuple[float, float]]:
    """[(le, cumulative_count)] rows of one un-labelled fleet histogram
    from a Prometheus 0.0.4 page, in exposition order."""
    rows = []
    for m in re.finditer(
            rf'^{family}_bucket\{{le="([^"]+)"\}}\s+(\S+)$', text, re.M):
        le = m.group(1)
        rows.append((float("inf") if le == "+Inf" else float(le),
                     float(m.group(2))))
    return rows


def _cum_p95_index(rows: list[tuple[float, float]], q: float) -> int:
    """Bucket index holding the q-quantile of a cumulative histogram."""
    total = rows[-1][1] if rows else 0.0
    if total <= 0:
        return 0
    rank = max(1.0, math.ceil(q * total))
    for i, (_le, cum) in enumerate(rows):
        if cum >= rank:
            return i
    return len(rows) - 1


async def fleet_leg(n_members: int = 3, probe_s: float = 0.5,
                    n_requests: int = 18) -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )
    from localai_tfp_tpu.telemetry import digest as dg

    saved = {k: os.environ.get(k) for k in _SMOKE_ENV}
    os.environ.update(_SMOKE_ENV)
    out: dict = {"members": n_members, "probe_s": probe_s,
                 "requests": n_requests}
    members: list = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            models = os.path.join(tmp, "models")
            os.makedirs(models)
            _make_models(models)

            tok = generate_token()
            fed = FederatedServer(tok, probe_s=probe_s)
            client = TestClient(TestServer(fed.build_app()))
            await client.start_server()
            balancer_url = (f"http://127.0.0.1:"
                            f"{client.server.port}")
            session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10))
            try:
                ports = []
                for i in range(n_members):
                    port = _free_port()
                    cwd = os.path.join(tmp, f"member{i}")
                    os.makedirs(cwd)
                    members.append(_spawn_member(
                        models, cwd, port, balancer_url=balancer_url,
                        token=tok, name=f"member-{i}"))
                    ports.append(port)
                t_boot = time.monotonic()
                await asyncio.gather(*[
                    _wait_ready(session, f"http://127.0.0.1:{p}")
                    for p in ports])
                out["member_boot_s"] = round(
                    time.monotonic() - t_boot, 1)

                # the startup announce registers each member (digest
                # attached); wait until the registry sees the full fleet
                t0 = time.monotonic()
                while time.monotonic() - t0 < 60:
                    r = await client.get("/federation/nodes")
                    nodes = await r.json()
                    if len(nodes) == n_members:
                        break
                    await asyncio.sleep(0.2)
                assert len(nodes) == n_members, \
                    f"only {len(nodes)}/{n_members} members registered"
                out["announce_digest_nodes"] = sum(
                    1 for n in nodes
                    if (n.get("digest") or {}).get("src") == "announce")

                # ---- mixed traffic wave through the balancer ----
                sem = asyncio.Semaphore(3)
                prompts = ["hi", "tell me a story about a boat",
                           "x " * 20, "why"]

                async def one(i: int) -> float:
                    async with sem:
                        return await _chat_ttft(
                            client, prompts[i % len(prompts)],
                            max_tokens=8 + 8 * (i % 2))

                ttfts = await asyncio.gather(
                    *[one(i) for i in range(n_requests)])
                ttfts = sorted(ttfts)

                # let the next probe round pick up final digests
                await asyncio.sleep(2 * probe_s + 0.2)

                # ---- digest plane health ----
                sizes = []
                for p in ports:
                    async with session.get(
                            f"http://127.0.0.1:{p}/telemetry/digest"
                    ) as r:
                        raw = await r.read()
                    dg.decode(raw)  # must round-trip the wire format
                    sizes.append(len(raw))
                out["digest_bytes_max"] = max(sizes)
                out["digest_within_cap"] = max(sizes) <= dg._max_bytes()

                r = await client.get("/federation/nodes")
                nodes = await r.json()
                out["nodes_cache_control"] = r.headers.get(
                    "Cache-Control")
                ages = [(n.get("digest") or {}).get("age_s")
                        for n in nodes]
                out["digest_age_max_s"] = round(
                    max(a for a in ages if a is not None), 3)
                out["digest_stale_nodes"] = sum(
                    1 for n in nodes
                    if (n.get("digest") or {}).get("stale", True))
                served = [n["requests_served"] for n in nodes]
                mean = sum(served) / max(1, len(served))
                out["requests_served"] = served
                out["load_skew"] = round(max(served) / mean, 3) \
                    if mean else None

                # ---- percentile cross-check: merged digests vs what
                # clients measured ----
                r = await client.get("/fleet/metrics")
                prom = (await r.read()).decode()
                rows = _prom_hist(prom, "fleet_ttft_seconds")
                total = rows[-1][1] if rows else 0
                out["fleet_ttft_count"] = int(total)
                i_fleet = _cum_p95_index(rows, 0.95)
                client_p95 = ttfts[
                    min(len(ttfts) - 1, int(math.ceil(0.95 * len(ttfts))) - 1)]
                bounds = dg.HIST_BOUNDS["ttft"]
                i_client = bisect_left(bounds, client_p95)
                out["client_ttft_p50_s"] = round(
                    ttfts[len(ttfts) // 2], 4)
                out["client_ttft_p95_s"] = round(client_p95, 4)
                out["fleet_ttft_p95_bounds_s"] = [
                    0.0 if i_fleet == 0 else bounds[i_fleet - 1],
                    rows[i_fleet][0] if rows else 0.0]
                out["ttft_within_one_bucket"] = abs(
                    i_fleet - i_client) <= 1
                itl_rows = _prom_hist(prom, "fleet_itl_seconds")
                if itl_rows and itl_rows[-1][1] > 0:
                    i50 = _cum_p95_index(itl_rows, 0.50)
                    i95 = _cum_p95_index(itl_rows, 0.95)
                    out["fleet_itl_p50_le_s"] = itl_rows[i50][0]
                    out["fleet_itl_p95_le_s"] = itl_rows[i95][0]

                # ---- SLO flip: kill one member ----
                r = await client.get("/fleet/slo")
                slo = await r.json()
                out["slo_cache_control"] = r.headers.get("Cache-Control")
                out["slo_state_before_kill"] = \
                    slo["objectives"]["availability"]["state"]
                members[-1].kill()
                t_kill = time.monotonic()
                flip = None
                metrics_ok = True
                while time.monotonic() - t_kill < 15.0:
                    r = await client.get("/fleet/metrics")
                    metrics_ok &= r.status == 200
                    await r.release()
                    r = await client.get("/fleet/slo")
                    slo = await r.json()
                    if slo["objectives"]["availability"]["state"] != "ok":
                        flip = time.monotonic() - t_kill
                        break
                    await asyncio.sleep(0.05)
                out["slo_state_after_kill"] = \
                    slo["objectives"]["availability"]["state"]
                out["slo_flip_latency_s"] = (round(flip, 3)
                                             if flip is not None else None)
                out["slo_flip_within_2_probes"] = (
                    flip is not None and flip <= 2 * probe_s + 0.5)
                out["metrics_served_during_kill"] = metrics_ok
                out["nodes_serving_after_kill"] = slo["nodes"]["serving"]
            finally:
                await session.close()
                await client.close()
    finally:
        for m in members:
            m.terminate()
        for m in members:
            try:
                m.wait(timeout=10)
            except Exception:
                m.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


# --------------------------------------------------------------------
# --routing: prefix-locality routing vs blind least-used, same fleet
# --------------------------------------------------------------------

# knobs for the routing leg: fast prefix-summary refresh so a seed
# request's KV residency reaches the gossiped digest within one probe
_ROUTING_ENV = dict(_SMOKE_ENV, LOCALAI_PREFIX_SUMMARY_S="0.2")


def _group_messages(tag: str, i: int) -> list:
    """Shared-prefix workload: every request in a group opens with the
    same long system message (one fingerprint boundary == one reusable
    KV prefix) and diverges at the user turn. The tag leads the
    preamble so DIFFERENT groups diverge at the first content token —
    a shared opening would make every group's token prefix overlap."""
    # ~300 chars (~310 tokens at this tokenizer's ~1 token/char): the
    # routing leg's widened model has a 384-token context, and the
    # shared prefix must dominate the tail — the hit-vs-miss TTFT gap
    # IS the prefill the hit skips
    preamble = f"{tag} desk. " + "Cite the runbook. " * 16
    tails = ["status?", "next?", "oncall?", "doc?", "retry?", "eta?"]
    return [{"role": "system", "content": preamble},
            {"role": "user", "content": tails[i % len(tails)]}]


async def routing_leg(n_members: int = 3, probe_s: float = 0.5,
                      groups: int = 4, repeats: int = 6) -> dict:
    """A/B inside one run: phase A drives grouped shared-prefix traffic
    with blind ``least-used`` routing, phase B drives fresh groups with
    ``prefix`` (cost-scored) routing. Reports the cross-replica prefix
    hit rate and the repeat-request TTFT p50 of each phase — locality
    must land repeats on the member already holding the group's KV.

    ``groups`` deliberately does NOT equal ``n_members``: least-used
    rotation is deterministic, so with groups == members the blind
    phase's group->member assignment is CONSTANT across rounds and can
    accidentally align every group with its seeded KV holder — a blind
    baseline that routes like a perfect locality router. A group count
    coprime to the member count rotates each group across members, so
    blind hits the holder at the expected ~1/members rate."""
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )

    saved = {k: os.environ.get(k) for k in _ROUTING_ENV}
    os.environ.update(_ROUTING_ENV)
    out: dict = {"members": n_members, "probe_s": probe_s,
                 "groups": groups, "repeats": repeats}
    members: list = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            models = os.path.join(tmp, "models")
            os.makedirs(models)
            # wider model (see _make_models): a skipped 300-token
            # prefill must be worth 10s of ms for the locality TTFT
            # comparison to clear per-request noise
            _make_models(models, hidden=256, inter=512, heads=8,
                         kv_heads=4, ctx=384)
            tok = generate_token()
            fed = FederatedServer(tok, strategy="least-used",
                                  probe_s=probe_s)
            client = TestClient(TestServer(fed.build_app()))
            await client.start_server()
            balancer_url = f"http://127.0.0.1:{client.server.port}"
            session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10))
            try:
                ports = []
                for i in range(n_members):
                    port = _free_port()
                    cwd = os.path.join(tmp, f"member{i}")
                    os.makedirs(cwd)
                    members.append(_spawn_member(
                        models, cwd, port, balancer_url=balancer_url,
                        token=tok, name=f"member-{i}"))
                    ports.append(port)
                await asyncio.gather(*[
                    _wait_ready(session, f"http://127.0.0.1:{p}")
                    for p in ports])
                t0 = time.monotonic()
                while time.monotonic() - t0 < 60:
                    nodes = await (await client.get(
                        "/federation/nodes")).json()
                    if len(nodes) == n_members:
                        break
                    await asyncio.sleep(0.2)

                # compile warm-up so neither phase pays first-request
                # compiles. Round 1 (one request per member,
                # least-used rotation) compiles the full-prefill
                # variant; round 2 repeats each warm GROUP — the same
                # rotation lands repeat i on the member already
                # holding w{i}'s prefix, compiling the prefix-copy +
                # tail-prefill variant the measured prefix phase's
                # HITS dispatch (unwarmed, the first hit per member
                # pays a multi-second XLA compile inside the phase)
                for r in (0, 1):
                    for i in range(n_members):
                        await _chat_ttft(
                            client, max_tokens=4,
                            messages=_group_messages(f"w{i}", r))

                from localai_tfp_tpu.utils import fingerprint as fp

                def _gossiped() -> set:
                    have = set()
                    for n in fed.registry.nodes():
                        for h, _t in ((n.digest or {}).get("prefixes")
                                      or []):
                            have.add(h)
                    return have

                async def phase(strategy: str, tagset: str) -> dict:
                    fed.strategy = strategy
                    # settle: the engine's eviction value is LRU x
                    # length with SECOND-granular recency, so seeding
                    # immediately after the previous phase's traffic
                    # makes a just-touched leftover residue look more
                    # valuable than a sibling seed placed seconds ago
                    # — the last seed then evicts the first instead of
                    # the leftover. A few seconds of decay makes every
                    # leftover the unambiguous victim.
                    await asyncio.sleep(3.0)
                    # seed each group's prefix into some member's KV
                    want = set()
                    for g in range(groups):
                        msgs = _group_messages(f"{tagset}{g}", 0)
                        # the shared (system-message) boundary hash —
                        # what every repeat in the group will match
                        h = fp.chain_from_body(
                            {"model": "tiny", "messages": msgs})[0][0]
                        want.add(h)
                        await _chat_ttft(client, max_tokens=4,
                                         messages=msgs)
                    # wait for the probe loop to gossip every seeded
                    # prefix (both phases, so traffic stays symmetric)
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < 15.0 \
                            and not want <= _gossiped():
                        await asyncio.sleep(0.2)
                    before = dict(fed.route_stats)
                    ttfts = []
                    for r in range(1, repeats + 1):
                        for g in range(groups):
                            ttfts.append(await _chat_ttft(
                                client, max_tokens=4,
                                messages=_group_messages(
                                    f"{tagset}{g}", r)))
                        if r < repeats:
                            # think-time >= one probe round between
                            # rounds (both phases, symmetric): any
                            # residency change a round caused reaches
                            # the gossiped digests before the next
                            # round routes on them — back-to-back
                            # rounds outrun the probe loop and a group
                            # that lost residency would miss every
                            # remaining repeat instead of recovering
                            await asyncio.sleep(probe_s + 0.3)
                    delta = {k: fed.route_stats[k] - before[k]
                             for k in before}
                    ttfts.sort()
                    n = len(ttfts)
                    return {
                        "strategy": strategy,
                        "route_stats": delta,
                        "repeat_requests": n,
                        "ttft_p50_s": round(ttfts[n // 2], 4),
                        "ttft_p95_s": round(
                            ttfts[min(n - 1,
                                      math.ceil(0.95 * n) - 1)], 4),
                    }

                blind = await phase("least-used", "a")
                prefix = await phase("prefix", "b")
                out["blind"] = blind
                out["prefix"] = prefix
                routed = sum(prefix["route_stats"].values())
                hits = prefix["route_stats"]["hit"]
                out["prefix_hit_rate"] = round(
                    hits / max(1, routed), 3)
                out["prefix_hit_rate_gt_half"] = \
                    hits / max(1, routed) > 0.5
                out["locality_ttft_gain_s"] = round(
                    blind["ttft_p50_s"] - prefix["ttft_p50_s"], 4)
                out["locality_beats_blind"] = \
                    prefix["ttft_p50_s"] < blind["ttft_p50_s"]
                # blind phase must stay locality-blind end to end
                out["blind_phase_scored"] = \
                    blind["route_stats"]["hit"] \
                    + blind["route_stats"]["stale"]
            finally:
                await session.close()
                await client.close()
    finally:
        for m in members:
            m.terminate()
        for m in members:
            try:
                m.wait(timeout=10)
            except Exception:
                m.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


# --------------------------------------------------------------------
# --autoscale: burst -> warmup-reuse replica boot -> drain -> kill
# --------------------------------------------------------------------

_AUTOSCALE_ENV = dict(
    _SMOKE_ENV,
    LOCALAI_SCALE_UP_QW_MS="80",
    LOCALAI_SCALE_HYSTERESIS="1",
    LOCALAI_SCALE_COOLDOWN_S="4",
    LOCALAI_SCALE_MIN="1",
    LOCALAI_SCALE_MAX="2",
    # generous floors so an idle tiny-model fleet qualifies for
    # scale-down the moment the burst drains
    LOCALAI_SCALE_DOWN_OCC="0.9",
    LOCALAI_SCALE_DOWN_MFU="0.9",
    LOCALAI_SCALE_DRAIN_TIMEOUT_S="30",
)


def _make_subprocess_driver(models_dir: str, tmp: str, token: str):
    """A real ScaleDriver: scale-up boots another member subprocess
    (same warmup-reuse fast-boot env as _spawn_member), scale-down
    terminates the victim's process. Records timings + the victim's
    in-flight count at kill time for the drain-before-kill check."""
    from localai_tfp_tpu.parallel.autoscale import ScaleDriver

    class SubprocessScaleDriver(ScaleDriver):
        mutates = True

        def __init__(self):
            self.balancer_url = None  # set once the app is listening
            self.procs: dict = {}  # advertise url -> Popen
            self.up_times: list = []
            self.down_times: list = []
            self.down_inflight: list = []
            self._n = 0

        def adopt(self, url: str, proc) -> None:
            self.procs[url] = proc

        def scale_up(self, count: int) -> None:
            for _ in range(count):
                self._n += 1
                port = _free_port()
                cwd = os.path.join(tmp, f"scale{self._n}")
                os.makedirs(cwd, exist_ok=True)
                proc = _spawn_member(
                    models_dir, cwd, port,
                    balancer_url=self.balancer_url, token=token,
                    name=f"scale-{self._n}")
                self.procs[f"http://127.0.0.1:{port}"] = proc
                self.up_times.append(time.monotonic())

        def scale_down(self, node) -> None:
            self.down_times.append(time.monotonic())
            self.down_inflight.append(node.in_flight)
            proc = self.procs.pop(node.address, None)
            if proc is not None:
                proc.terminate()

    return SubprocessScaleDriver()


async def autoscale_leg(probe_s: float = 2.0,
                        burst: int = 10) -> dict:
    """One member + the subprocess ScaleDriver: a queue burst must boot
    a second replica within ~2 probe intervals of the signal landing,
    and the post-burst idle fleet must drain (victim out of rotation,
    zero in-flight at kill) before the process dies."""
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )

    saved = {k: os.environ.get(k) for k in _AUTOSCALE_ENV}
    os.environ.update(_AUTOSCALE_ENV)
    out: dict = {"probe_s": probe_s, "burst": burst}
    driver = None
    try:
        with tempfile.TemporaryDirectory() as tmp:
            models = os.path.join(tmp, "models")
            os.makedirs(models)
            _make_models(models)
            tok = generate_token()
            driver = _make_subprocess_driver(models, tmp, tok)
            fed = FederatedServer(tok, probe_s=probe_s,
                                  scale_driver=driver)
            client = TestClient(TestServer(fed.build_app()))
            await client.start_server()
            driver.balancer_url = \
                f"http://127.0.0.1:{client.server.port}"
            session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10))
            try:
                port = _free_port()
                cwd = os.path.join(tmp, "member0")
                os.makedirs(cwd)
                base = _spawn_member(
                    models, cwd, port,
                    balancer_url=driver.balancer_url, token=tok,
                    name="base-0")
                driver.adopt(f"http://127.0.0.1:{port}", base)
                await _wait_ready(session, f"http://127.0.0.1:{port}")
                t0 = time.monotonic()
                while time.monotonic() - t0 < 60:
                    nodes = await (await client.get(
                        "/federation/nodes")).json()
                    if len(nodes) == 1:
                        break
                    await asyncio.sleep(0.2)
                # warm BOTH slots concurrently: members boot with
                # LOCALAI_WARMUP=0, so the batch=2 decode shape
                # compiles on first use — unwarmed, that multi-second
                # compile pins both slots through the burst's first
                # wave and the reaction clock measures XLA, not the
                # autoscaler. Disarm the scale-up threshold while
                # warming (knobs read env live; the balancer is
                # in-process): if the warms arrive staggered, the
                # second's admission waits out the first's compile and
                # that wait would trip the threshold pre-burst. Two
                # probe rounds of idle after the warms fold their
                # queue-wait samples into the windowed diff's baseline
                # before re-arming.
                os.environ["LOCALAI_SCALE_UP_QW_MS"] = "0"
                await asyncio.gather(*[
                    _chat_ttft(client, f"warm {i}", max_tokens=4)
                    for i in range(2)])
                await asyncio.sleep(2 * probe_s + 0.3)
                os.environ["LOCALAI_SCALE_UP_QW_MS"] = \
                    _AUTOSCALE_ENV["LOCALAI_SCALE_UP_QW_MS"]

                # ---- burst: overflow the 2 decode slots ----
                # short decodes so slots RELEASE quickly: queue-wait
                # samples are recorded at admission, so the scale-up
                # signal can only appear in a digest once the first
                # burst requests have been admitted off the queue
                t_burst = time.monotonic()
                await asyncio.gather(*[
                    _chat_ttft(client, f"burst {i}", max_tokens=4)
                    for i in range(burst)])
                while (not driver.up_times
                       and time.monotonic() - t_burst < 30):
                    await asyncio.sleep(0.1)
                assert driver.up_times, \
                    "burst never triggered a scale-up"
                reaction = driver.up_times[0] - t_burst
                out["boot_reaction_s"] = round(reaction, 3)
                out["reaction_within_2_probes"] = \
                    reaction <= 2 * probe_s + 0.5
                out["replicas_desired_peak"] = fed.autoscaler.desired

                # the booted replica must register and serve
                t0 = time.monotonic()
                nodes = []
                while time.monotonic() - t0 < 180:
                    nodes = await (await client.get(
                        "/federation/nodes")).json()
                    if len(nodes) == 2:
                        break
                    await asyncio.sleep(0.3)
                out["replicas_after_boot"] = len(nodes)
                out["boot_to_serving_s"] = round(
                    time.monotonic() - driver.up_times[0], 1)

                # ---- idle: drain-before-kill scale-down ----
                saw_draining = False
                t0 = time.monotonic()
                while time.monotonic() - t0 < 90:
                    nodes = await (await client.get(
                        "/federation/nodes")).json()
                    saw_draining |= any(
                        n.get("draining") for n in nodes)
                    if len(nodes) == 1 and driver.down_times:
                        break
                    await asyncio.sleep(0.2)
                out["replicas_after_drain"] = len(nodes)
                out["victim_seen_draining"] = saw_draining
                out["victim_in_flight_at_kill"] = \
                    driver.down_inflight
                out["scale_down_after_drain"] = bool(
                    driver.down_times) and all(
                    n == 0 for n in driver.down_inflight)
                out["scale_events"] = {
                    f"{d}/{o}": n for (d, o), n in sorted(
                        fed.autoscaler.snapshot()["events"].items())}
                page = await (await client.get(
                    "/fleet/metrics")).text()
                out["desired_gauge_exported"] = \
                    "fleet_replicas_desired_count" in page
            finally:
                await session.close()
                await client.close()
    finally:
        if driver is not None:
            for proc in driver.procs.values():
                proc.terminate()
            for proc in driver.procs.values():
                try:
                    proc.wait(timeout=10)
                except Exception:
                    proc.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--members", type=int, default=3)
    ap.add_argument("--requests", type=int, default=18)
    ap.add_argument("--probe-s", type=float, default=0.5)
    ap.add_argument("--routing", action="store_true",
                    help="run the prefix-locality routing A/B leg "
                         "instead of the digest-plane leg")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the elastic-autoscaling leg instead of "
                         "the digest-plane leg")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings (3 members, "
                         "12 requests; routing: 3 groups x 4 repeats)")
    ap.add_argument("--json", action="store_true",
                    help="compact one-line JSON output")
    args = ap.parse_args()
    if args.smoke:
        args.members, args.requests = 3, 12

    if args.routing or args.autoscale:
        report = {}
        if args.routing:
            report["routing"] = asyncio.run(routing_leg(
                n_members=args.members, probe_s=args.probe_s,
                repeats=3 if args.smoke else 6))
        if args.autoscale:
            report["autoscale"] = asyncio.run(autoscale_leg())
    else:
        report = asyncio.run(fleet_leg(
            n_members=args.members, probe_s=args.probe_s,
            n_requests=args.requests))
    print(json.dumps(report) if args.json
          else json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
