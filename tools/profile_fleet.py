"""Fleet profile: the telemetry digest plane measured end to end.

Boots N REAL member servers (subprocesses, tiny CPU checkpoint) behind
an in-process federated balancer, drives mixed streaming traffic
through the balancer, and prints one JSON report with the acceptance
numbers the fleet-telemetry PR tracks:

  percentile cross-check:
    client_ttft_p95_s       — p95 of client-measured time-to-first-
                              content-chunk across every request
    fleet_ttft_p95_bounds_s — the bucket holding p95 in the balancer's
                              merged digest histogram (/fleet/metrics)
    ttft_within_one_bucket  — |client bucket - digest bucket| <= 1:
                              exact bucket merges put the fleet p95
                              within one histogram bucket of what
                              clients actually saw (the contract that
                              forbids averaging per-node percentiles)

  digest plane health:
    digest_bytes_max        — largest /telemetry/digest body observed
                              (contract: <= LOCALAI_DIGEST_MAX_BYTES)
    digest_age_max_s        — staleness across nodes right after the
                              traffic wave (probe-refreshed, so this
                              tracks the probe interval, not the 20 s
                              heartbeat)
    load_skew               — max(requests_served) / mean — least-used
                              routing should keep this near 1.0

  SLO burn-rate monitor:
    slo_flip_latency_s      — kill one member; seconds until the
                              availability objective on /fleet/slo
                              leaves "ok" (fast/slow windows shrunk via
                              env so the flip is observable in a smoke)
    slo_flip_within_2_probes— latency <= 2 probe intervals (+ sched
                              slack): the first failed probe marks the
                              node not-serving, the second confirms
    metrics_served_during_kill — /fleet/metrics kept answering 200
                              while the fleet was degraded

Run:  python tools/profile_fleet.py [--members N] [--requests N]
                                    [--probe-s S] [--json]

CPU smoke (what CI can afford):  python tools/profile_fleet.py --smoke
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import re
import socket
import sys
import tempfile
import time
from bisect import bisect_left

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# SLO windows shrunk so a burn-rate flip is observable inside a smoke
# run; generous TTFT threshold so first-request compiles cannot push
# the latency objective into warning and muddy the availability check
_SMOKE_ENV = {
    "LOCALAI_SLO_FAST_WINDOW_S": "1",
    "LOCALAI_SLO_SLOW_WINDOW_S": "5",
    "LOCALAI_SLO_TTFT_P95_MS": "30000",
    "LOCALAI_SLO_ITL_P99_MS": "30000",
}

_TINY_YAML = """
name: tiny
backend: jax-llm
parameters:
  model: tiny-ckpt
  temperature: 0.0
  max_tokens: 16
context_size: 128
max_batch_slots: 2
dtype: float32
template:
  completion: "{{.Input}}"
  chat_message: "{{.RoleName}}: {{.Content}}"
  chat: "{{.Input}}\\nassistant:"
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_models(models_dir: str) -> None:
    """Tiny torch Llama checkpoint + config: real jax-llm members that
    boot (and first-request compile) in seconds on CPU."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=300, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256,
    )).save_pretrained(os.path.join(models_dir, "tiny-ckpt"),
                       safe_serialization=True)
    with open(os.path.join(models_dir, "tiny.yaml"), "w") as f:
        f.write(_TINY_YAML)


def _spawn_member(models_dir: str, cwd: str, port: int, *,
                  balancer_url: str, token: str, name: str):
    """One REAL member: announces itself (digest riding the heartbeat)
    and serves the balancer's /healthz + /telemetry/digest probes."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["LOCALAI_WARMUP"] = "0"  # skip warmup decode: fast boot
    env["LOCALAI_NODE_NAME"] = name
    env.pop("LOCALAI_FAULTS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    return subprocess.Popen(
        [sys.executable, "-m", "localai_tfp_tpu.cli", "run",
         "--models-path", models_dir, "--address", "127.0.0.1",
         "--port", str(port),
         "--p2p-token", token,
         "--federated-server", balancer_url,
         "--advertise-address", f"http://127.0.0.1:{port}"],
        cwd=cwd, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)


async def _wait_ready(session, base: str, timeout_s: float = 180.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            async with session.get(base + "/readyz") as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        await asyncio.sleep(0.3)
    raise TimeoutError(f"member {base} never became ready")


async def _chat_ttft(client, prompt: str, max_tokens: int) -> float:
    """One streaming chat completion through the balancer; returns the
    client-measured time to the first GENERATED event — the first chunk
    after the role preamble (which is written before generation
    starts). A tiny random checkpoint can emit tokens whose bytes decode
    to empty text, so the finish chunk is an accepted (late) fallback —
    at smoke token counts it lands in the same log bucket."""
    t0 = time.perf_counter()
    resp = await client.request(
        "POST", "/v1/chat/completions",
        json={"model": "tiny", "stream": True, "max_tokens": max_tokens,
              "messages": [{"role": "user", "content": prompt}]})
    assert resp.status == 200, f"proxy status {resp.status}"
    ttft = None
    async for raw in resp.content:
        line = raw.decode("utf-8", "replace").strip()
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        try:
            ev = json.loads(line[len("data: "):])
        except ValueError:
            continue
        choice = (ev.get("choices") or [{}])[0]
        delta = choice.get("delta") or {}
        finish = choice.get("finish_reason")
        if finish == "error":
            raise RuntimeError(f"stream errored: {ev}")
        if ttft is None and "role" not in delta and (
                delta.get("content") or finish is not None):
            ttft = time.perf_counter() - t0
    resp.release()
    if ttft is None:
        raise RuntimeError("stream produced no generated event")
    return ttft


def _prom_hist(text: str, family: str) -> list[tuple[float, float]]:
    """[(le, cumulative_count)] rows of one un-labelled fleet histogram
    from a Prometheus 0.0.4 page, in exposition order."""
    rows = []
    for m in re.finditer(
            rf'^{family}_bucket\{{le="([^"]+)"\}}\s+(\S+)$', text, re.M):
        le = m.group(1)
        rows.append((float("inf") if le == "+Inf" else float(le),
                     float(m.group(2))))
    return rows


def _cum_p95_index(rows: list[tuple[float, float]], q: float) -> int:
    """Bucket index holding the q-quantile of a cumulative histogram."""
    total = rows[-1][1] if rows else 0.0
    if total <= 0:
        return 0
    rank = max(1.0, math.ceil(q * total))
    for i, (_le, cum) in enumerate(rows):
        if cum >= rank:
            return i
    return len(rows) - 1


async def fleet_leg(n_members: int = 3, probe_s: float = 0.5,
                    n_requests: int = 18) -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from localai_tfp_tpu.parallel.federated import (
        FederatedServer, generate_token,
    )
    from localai_tfp_tpu.telemetry import digest as dg

    saved = {k: os.environ.get(k) for k in _SMOKE_ENV}
    os.environ.update(_SMOKE_ENV)
    out: dict = {"members": n_members, "probe_s": probe_s,
                 "requests": n_requests}
    members: list = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            models = os.path.join(tmp, "models")
            os.makedirs(models)
            _make_models(models)

            tok = generate_token()
            fed = FederatedServer(tok, probe_s=probe_s)
            client = TestClient(TestServer(fed.build_app()))
            await client.start_server()
            balancer_url = (f"http://127.0.0.1:"
                            f"{client.server.port}")
            session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10))
            try:
                ports = []
                for i in range(n_members):
                    port = _free_port()
                    cwd = os.path.join(tmp, f"member{i}")
                    os.makedirs(cwd)
                    members.append(_spawn_member(
                        models, cwd, port, balancer_url=balancer_url,
                        token=tok, name=f"member-{i}"))
                    ports.append(port)
                t_boot = time.monotonic()
                await asyncio.gather(*[
                    _wait_ready(session, f"http://127.0.0.1:{p}")
                    for p in ports])
                out["member_boot_s"] = round(
                    time.monotonic() - t_boot, 1)

                # the startup announce registers each member (digest
                # attached); wait until the registry sees the full fleet
                t0 = time.monotonic()
                while time.monotonic() - t0 < 60:
                    r = await client.get("/federation/nodes")
                    nodes = await r.json()
                    if len(nodes) == n_members:
                        break
                    await asyncio.sleep(0.2)
                assert len(nodes) == n_members, \
                    f"only {len(nodes)}/{n_members} members registered"
                out["announce_digest_nodes"] = sum(
                    1 for n in nodes
                    if (n.get("digest") or {}).get("src") == "announce")

                # ---- mixed traffic wave through the balancer ----
                sem = asyncio.Semaphore(3)
                prompts = ["hi", "tell me a story about a boat",
                           "x " * 20, "why"]

                async def one(i: int) -> float:
                    async with sem:
                        return await _chat_ttft(
                            client, prompts[i % len(prompts)],
                            max_tokens=8 + 8 * (i % 2))

                ttfts = await asyncio.gather(
                    *[one(i) for i in range(n_requests)])
                ttfts = sorted(ttfts)

                # let the next probe round pick up final digests
                await asyncio.sleep(2 * probe_s + 0.2)

                # ---- digest plane health ----
                sizes = []
                for p in ports:
                    async with session.get(
                            f"http://127.0.0.1:{p}/telemetry/digest"
                    ) as r:
                        raw = await r.read()
                    dg.decode(raw)  # must round-trip the wire format
                    sizes.append(len(raw))
                out["digest_bytes_max"] = max(sizes)
                out["digest_within_cap"] = max(sizes) <= dg._max_bytes()

                r = await client.get("/federation/nodes")
                nodes = await r.json()
                out["nodes_cache_control"] = r.headers.get(
                    "Cache-Control")
                ages = [(n.get("digest") or {}).get("age_s")
                        for n in nodes]
                out["digest_age_max_s"] = round(
                    max(a for a in ages if a is not None), 3)
                out["digest_stale_nodes"] = sum(
                    1 for n in nodes
                    if (n.get("digest") or {}).get("stale", True))
                served = [n["requests_served"] for n in nodes]
                mean = sum(served) / max(1, len(served))
                out["requests_served"] = served
                out["load_skew"] = round(max(served) / mean, 3) \
                    if mean else None

                # ---- percentile cross-check: merged digests vs what
                # clients measured ----
                r = await client.get("/fleet/metrics")
                prom = (await r.read()).decode()
                rows = _prom_hist(prom, "fleet_ttft_seconds")
                total = rows[-1][1] if rows else 0
                out["fleet_ttft_count"] = int(total)
                i_fleet = _cum_p95_index(rows, 0.95)
                client_p95 = ttfts[
                    min(len(ttfts) - 1, int(math.ceil(0.95 * len(ttfts))) - 1)]
                bounds = dg.HIST_BOUNDS["ttft"]
                i_client = bisect_left(bounds, client_p95)
                out["client_ttft_p50_s"] = round(
                    ttfts[len(ttfts) // 2], 4)
                out["client_ttft_p95_s"] = round(client_p95, 4)
                out["fleet_ttft_p95_bounds_s"] = [
                    0.0 if i_fleet == 0 else bounds[i_fleet - 1],
                    rows[i_fleet][0] if rows else 0.0]
                out["ttft_within_one_bucket"] = abs(
                    i_fleet - i_client) <= 1
                itl_rows = _prom_hist(prom, "fleet_itl_seconds")
                if itl_rows and itl_rows[-1][1] > 0:
                    i50 = _cum_p95_index(itl_rows, 0.50)
                    i95 = _cum_p95_index(itl_rows, 0.95)
                    out["fleet_itl_p50_le_s"] = itl_rows[i50][0]
                    out["fleet_itl_p95_le_s"] = itl_rows[i95][0]

                # ---- SLO flip: kill one member ----
                r = await client.get("/fleet/slo")
                slo = await r.json()
                out["slo_cache_control"] = r.headers.get("Cache-Control")
                out["slo_state_before_kill"] = \
                    slo["objectives"]["availability"]["state"]
                members[-1].kill()
                t_kill = time.monotonic()
                flip = None
                metrics_ok = True
                while time.monotonic() - t_kill < 15.0:
                    r = await client.get("/fleet/metrics")
                    metrics_ok &= r.status == 200
                    await r.release()
                    r = await client.get("/fleet/slo")
                    slo = await r.json()
                    if slo["objectives"]["availability"]["state"] != "ok":
                        flip = time.monotonic() - t_kill
                        break
                    await asyncio.sleep(0.05)
                out["slo_state_after_kill"] = \
                    slo["objectives"]["availability"]["state"]
                out["slo_flip_latency_s"] = (round(flip, 3)
                                             if flip is not None else None)
                out["slo_flip_within_2_probes"] = (
                    flip is not None and flip <= 2 * probe_s + 0.5)
                out["metrics_served_during_kill"] = metrics_ok
                out["nodes_serving_after_kill"] = slo["nodes"]["serving"]
            finally:
                await session.close()
                await client.close()
    finally:
        for m in members:
            m.terminate()
        for m in members:
            try:
                m.wait(timeout=10)
            except Exception:
                m.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--members", type=int, default=3)
    ap.add_argument("--requests", type=int, default=18)
    ap.add_argument("--probe-s", type=float, default=0.5)
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings (3 members, "
                         "12 requests)")
    ap.add_argument("--json", action="store_true",
                    help="compact one-line JSON output")
    args = ap.parse_args()
    if args.smoke:
        args.members, args.requests = 3, 12

    report = asyncio.run(fleet_leg(
        n_members=args.members, probe_s=args.probe_s,
        n_requests=args.requests))
    print(json.dumps(report) if args.json
          else json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
