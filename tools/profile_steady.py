"""Steady-state TTFT decomposition for the 8B serving config.

The scenario BASELINE.md's <200 ms p50 target describes: the engine is
saturated (63/64 slots decoding) and ONE new request arrives. Where do
its ~400 ms go?  This traces, per arrival:

  submit -> assign (scheduler pickup)
  assign -> prefill dispatch enqueue
  dispatch -> flight harvested (device queue ahead + prefill itself)
  harvest -> StreamEvent first token on the client queue

plus the dispatch log (kind, k, host-enqueue wall) between submit and
first token, which shows how much scan work was queued ahead.

Run manually on the chip:  python tools/profile_steady.py [--arrivals N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arrivals", type=int, default=10)
    ap.add_argument("--gap", type=float, default=0.5)
    args = ap.parse_args()

    jax.config.update("jax_compilation_cache_dir", "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from bench import _fast_int8_params  # type: ignore
    from tools.profile_ttft import WideByteTok

    from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
    from localai_tfp_tpu.models.llm_spec import LLMSpec

    spec = LLMSpec(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
        rope_theta=500000.0)
    tok = WideByteTok()
    params = _fast_int8_params(spec)
    import jax.numpy as jnp

    eng = LLMEngine(spec, params, tok, n_slots=64, max_seq=1024,
                    decode_steps=16, cache_dtype=jnp.int8,
                    latency_target_ms=70.0,  # matches bench8b.yaml
                    autostart=True)
    eng.warmup()

    # ~22 byte-tokens -> the same 32 bucket the bench's real-BPE prompt
    # ("benchmark " * 12 -> ~25 BPE ids) hits, so every prefill variant
    # below is warm in the persistent compile cache
    prompt = tok.encode("benchmark " * 2)

    def req(i: int, n: int) -> GenRequest:
        return GenRequest(
            prompt_ids=prompt + [i % 200], max_tokens=n,
            temperature=0.8, top_k=40, top_p=0.95, ignore_eos=True)

    # same two compile-warmup waves _bench_config runs (cold-prompt,
    # then prefix-reuse variants) so the steady phase measures serving,
    # not compiles
    def warm_wave() -> None:
        qs = eng.submit_many([req(i, 16) for i in range(64)])
        for q in qs:
            while True:
                ev = q.get(timeout=1800)
                if ev.error:
                    raise RuntimeError(ev.error)
                if ev.done:
                    break

    for n in range(2):
        t0 = time.perf_counter()
        warm_wave()
        print(f"warm wave {n}: {time.perf_counter() - t0:.1f}s",
              flush=True)

    # -------- background load: 63 long streams --------
    bg_qs = eng.submit_many([req(i, 900) for i in range(63)])
    bg_stop = threading.Event()

    def drain_bg() -> None:
        done = 0
        while not bg_stop.is_set() and done < len(bg_qs):
            for q in bg_qs:
                try:
                    ev = q.get(timeout=0.05)
                    if ev.done:
                        done += 1
                except Exception:
                    pass

    bg_t = threading.Thread(target=drain_bg, daemon=True)
    bg_t.start()
    # let the wave prefill and settle into pure decode
    time.sleep(6.0)

    # -------- instrumented arrivals --------
    log: list = []
    orig_run = eng._run

    def traced_run(kind, payload):
        t0 = time.perf_counter()
        out = orig_run(kind, payload)
        t1 = time.perf_counter()
        sh = (list(payload["toks"].shape)
              if kind.startswith("prefill") else payload.get("k"))
        log.append((kind, sh, t0, round((t1 - t0) * 1e3, 1)))
        return out

    eng._run = traced_run
    arrivals = []
    for i in range(args.arrivals):
        time.sleep(args.gap)
        mark = len(log)
        t0 = time.perf_counter()
        q = eng.submit(req(1000 + i, 4))
        ttft = None
        while True:
            try:
                # generous: a first-of-shape arrival may sit behind a
                # cold jit (minutes through the remote AOT helper);
                # later arrivals of the same shape measure serving
                ev = q.get(timeout=900)
            except Exception:
                states = {}
                for s in eng.slots:
                    states[str(s.state)] = states.get(str(s.state), 0) + 1
                print(json.dumps({
                    "STARVED": i, "slot_states": states,
                    "pending": len(eng._pending),
                    "flights": len(eng._flights),
                    "recent_dispatches": [
                        (k, sh, round((time.perf_counter() - at), 1))
                        for k, sh, at, _ in log[-6:]],
                }), flush=True)
                raise
            if ev.error:
                raise RuntimeError(f"arrival errored: {ev.error}")
            if ev.token_id is not None and ttft is None:
                ttft = (time.perf_counter() - t0) * 1e3
            if ev.done:
                break
        window = [
            {"kind": k, "shape": sh,
             "at_ms": round((at - t0) * 1e3, 1), "host_ms": ms}
            for k, sh, at, ms in log[max(0, mark - 4):]
            if at - t0 < (ttft or 1e9) / 1e3
        ]
        arrivals.append({"ttft_ms": round(ttft, 1), "dispatches": window})
    eng._run = orig_run
    bg_stop.set()

    tt = sorted(a["ttft_ms"] for a in arrivals)
    print(json.dumps({
        "steady_ttft_p50_ms": tt[len(tt) // 2],
        "steady_ttft_min_ms": tt[0],
        "steady_ttft_max_ms": tt[-1],
        "arrivals": arrivals,
    }, indent=1), flush=True)
    eng.close()


if __name__ == "__main__":
    main()
