"""Cold-start regression check: load the bench 8B artifact once and
print the phase-timing breakdown table.

The r5 bench reported `checkpoint_load_s = 256.9` in artifact mode
against a ~90 s annotation — 167 unattributed seconds. The loader now
bills every load into phases (models/load_timing.py:
read/dequant/transfer/compile/warmup + other); this tool makes the
breakdown a one-command check so a regression in any single phase is
visible the day it lands, not at the end-of-round bench.

Runs the SAME path bench.py's 8B leg takes: real-format HF checkpoint
(cached across runs) -> Application -> ModelLoader -> JaxLLMBackend
(artifact cache on, so the second run measures the artifact-mode load).
On CPU hosts a tiny geometry is substituted so the tool runs anywhere.

Usage:
  python tools/profile_coldstart.py            # geometry by backend
  python tools/profile_coldstart.py --tiny     # force tiny (CPU smoke)
  python tools/profile_coldstart.py --cold     # drop the quant artifact
                                               # first: measure the full
                                               # (streamed) load
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="force the tiny CPU geometry")
    ap.add_argument("--cold", action="store_true",
                    help="remove the quant artifact first (full load)")
    ap.add_argument("--no-warmup-reuse", action="store_true",
                    help="ignore persistent-cache warmup markers")
    args = ap.parse_args()

    if args.no_warmup_reuse:
        os.environ["LOCALAI_WARMUP_REUSE"] = "off"

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    import shutil
    import tempfile
    import time

    from bench import _write_hf_checkpoint
    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import register_default_backends
    from localai_tfp_tpu.models.llm_spec import LLMSpec
    from localai_tfp_tpu.server.state import Application

    on_tpu = jax.default_backend() == "tpu" and not args.tiny
    if on_tpu:
        spec = LLMSpec(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
            rope_theta=500000.0,
        )
        slots, ctx = 64, 1024
    else:
        spec = LLMSpec(
            vocab_size=512, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, d_head=16, d_ff=128, max_position=256,
        )
        slots, ctx = 2, 128

    import hashlib

    key = hashlib.sha256(
        (repr(spec) + "|writer-v2").encode()).hexdigest()[:16]
    cache_root = os.environ.get(
        "XDG_CACHE_HOME", os.path.expanduser("~/.cache"))
    ckpt = os.path.join(cache_root, f"localai_bench_ckpt_{key}")
    if not os.path.exists(os.path.join(ckpt, ".complete")):
        shutil.rmtree(ckpt, ignore_errors=True)
        print(f"writing checkpoint {ckpt} ...", flush=True)
        _write_hf_checkpoint(ckpt, spec)
        with open(os.path.join(ckpt, ".complete"), "w") as f:
            f.write("ok")

    if args.cold:
        from localai_tfp_tpu.models.artifact_cache import artifact_path

        p = artifact_path(ckpt, "int8_full", "bfloat16")
        if os.path.exists(p):
            os.unlink(p)
            print(f"dropped artifact {p} (cold full load)", flush=True)

    tmp = tempfile.mkdtemp(prefix="coldstart-")
    try:
        models = os.path.join(tmp, "models")
        os.makedirs(models)
        os.symlink(ckpt, os.path.join(models, "ckpt"))
        with open(os.path.join(models, "prof.yaml"), "w") as f:
            f.write(
                "name: prof\n"
                "backend: jax-llm\n"
                "parameters:\n  model: ckpt\n"
                f"context_size: {ctx}\n"
                f"max_batch_slots: {slots}\n"
                "quantization: int8_full\n"
                "kv_cache_dtype: int8\n"
                "decode_steps: 16\n"
                "latency_target_ms: 70\n"
            )
        state = Application(ApplicationConfig(
            models_path=models,
            generated_content_dir=os.path.join(tmp, "generated"),
            upload_dir=os.path.join(tmp, "uploads"),
            config_dir=os.path.join(tmp, "configuration"),
        ))
        register_default_backends()
        state.config_loader.load_configs_from_path()
        t0 = time.perf_counter()
        backend = state.model_loader.load(state.config_loader.get("prof"))
        total = time.perf_counter() - t0
        bd = dict(getattr(backend, "load_breakdown", {}) or {})
        mode = bd.pop("load_mode", getattr(backend, "load_mode", "?"))
        reused = bd.pop("warmup_reused", False)

        print(f"\ncold-start load: {total:.1f}s  mode={mode}  "
              f"warmup_reused={reused}")
        print(f"{'phase':<12}{'seconds':>9}   share")
        tot = bd.get("total_s") or total
        for p in ("read_s", "dequant_s", "transfer_s", "compile_s",
                  "warmup_s", "other_s"):
            v = float(bd.get(p, 0.0))
            bar = "#" * int(40 * v / tot) if tot else ""
            print(f"{p:<12}{v:>9.2f}   {bar}")
        print(f"{'total_s':<12}{float(bd.get('total_s', total)):>9.2f}")
        print("\nJSON: " + json.dumps(
            {**bd, "load_mode": mode, "warmup_reused": reused}))
        # leave the artifact behind so the NEXT run measures artifact
        # mode: the deferred write is abandoned by shutdown(), so wait
        # for it here (idle engine -> starts immediately)
        t = getattr(backend, "_artifact_thread", None)
        if t is not None:
            print("waiting for quant artifact write ...", flush=True)
            t.join(timeout=600)
        backend.shutdown()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
