"""Cold-start regression check: load the bench 8B artifact once and
print the phase-timing breakdown table.

The r5 bench reported `checkpoint_load_s = 256.9` in artifact mode
against a ~90 s annotation — 167 unattributed seconds. The loader now
bills every load into phases (models/load_timing.py:
read/dequant/transfer/compile/warmup + other); this tool makes the
breakdown a one-command check so a regression in any single phase is
visible the day it lands, not at the end-of-round bench.

Runs the SAME path bench.py's 8B leg takes: real-format HF checkpoint
(cached across runs) -> Application -> ModelLoader -> JaxLLMBackend
(artifact cache on, so the second run measures the artifact-mode load).
On CPU hosts a tiny geometry is substituted so the tool runs anywhere.

The --gallery mode measures the weight-paging story instead
(engine/weight_pager.py): N models round-robin on one chip with the
HBM weight budget sized for ~2 of them, so every visit to a paged-out
model pays a warm PROMOTION (layer-streamed H2D from the host mirror)
rather than a cold load. Reports cold vs warm vs hot first-token
latency, the HBM high-water mark against the budget, and LRU thrash
(coordinator pressure demotions).

Usage:
  python tools/profile_coldstart.py            # geometry by backend
  python tools/profile_coldstart.py --tiny     # force tiny (CPU smoke)
  python tools/profile_coldstart.py --cold     # drop the quant artifact
                                               # first: measure the full
                                               # (streamed) load
  python tools/profile_coldstart.py --gallery  # N-model paging smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _pctl(xs: list, q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def gallery_shape(n_models: int = 4, rounds: int = 3) -> dict:
    """The gallery contention story on small engines: N models share
    one chip, the weight-HBM budget fits ~2, a round-robin client
    visits them all. First-token latency is bucketed by the pager
    state the visit found (cold = engine build + transfer + first
    step; warm = layer-streamed promotion; hot = resident). Returns
    the JSON-able shape bench.py embeds as ``extra.weight_paging``."""
    import jax
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.engine.weight_pager import COORD
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tok = ByteTokenizer()
    spec = tiny_spec(vocab_size=tok.vocab_size, max_position=256)
    saved = {k: os.environ.get(k)
             for k in ("LOCALAI_WEIGHT_PAGING", "LOCALAI_WEIGHT_HBM_MB")}
    os.environ["LOCALAI_WEIGHT_PAGING"] = "on"
    os.environ["LOCALAI_WEIGHT_HBM_MB"] = "0"
    engines: list = []
    high_water = 0
    thrash0 = COORD.counters["pressure_demotes"]

    def first_token_s(eng, prompt: str) -> float:
        t0 = time.perf_counter()
        q = eng.submit(GenRequest(prompt_ids=eng.tokenize(prompt),
                                  max_tokens=4, temperature=0.0,
                                  ignore_eos=True))
        t1 = None
        while True:
            ev = q.get(timeout=300)
            if t1 is None and ev.token_id is not None:
                t1 = time.perf_counter()
            if ev.done:
                break
        return (t1 or time.perf_counter()) - t0

    try:
        cold, warm, hot = [], [], []
        budget_mb = 0.0
        for i in range(n_models):
            params = init_params(jax.random.PRNGKey(i), spec,
                                 dtype=jnp.float32)
            t0 = time.perf_counter()
            eng = LLMEngine(spec, params, tok, n_slots=2, max_seq=128,
                            prefill_buckets=(8, 32))
            cold.append(time.perf_counter() - t0
                        + first_token_s(eng, f"gallery model {i}"))
            engines.append(eng)
            if i == 0:
                # budget fits ~2 trees: from the third model on, every
                # arrival pressures the LRU resident out
                budget_mb = (eng._pager.tree_bytes() * 2.5) / (1 << 20)
                os.environ["LOCALAI_WEIGHT_HBM_MB"] = \
                    f"{budget_mb:.6f}"
            high_water = max(high_water, sum(
                e._pager.device_bytes() for e in engines))
        for r in range(rounds):
            for i, eng in enumerate(engines):
                state = eng._pager.state
                dt = first_token_s(eng, f"round {r} model {i}")
                (hot if state == "hot" else warm).append(dt)
                high_water = max(high_water, sum(
                    e._pager.device_bytes() for e in engines))
        # let in-flight pressure demotions land before reading state
        for eng in engines:
            eng._pager.settle(30)
        residency = COORD.residency()
        for eng in engines:
            eng._pager.leak_check()
        cold_p50, warm_p50 = _pctl(cold, 0.5), _pctl(warm, 0.5)
        return {
            "n_models": n_models,
            "rounds": rounds,
            "tree_mb": round(
                engines[0]._pager.tree_bytes() / (1 << 20), 3),
            "hbm_budget_mb": round(budget_mb, 3),
            "cold_first_token_s": {
                "p50": round(cold_p50, 4), "max": round(max(cold), 4),
                "n": len(cold)},
            "warm_first_token_s": {
                "p50": round(warm_p50, 4),
                "max": round(max(warm), 4) if warm else 0.0,
                "n": len(warm)},
            "hot_first_token_s": {
                "p50": round(_pctl(hot, 0.5), 4), "n": len(hot)},
            "warm_vs_cold_speedup": round(
                cold_p50 / max(warm_p50, 1e-9), 2) if warm else None,
            "hbm_high_water_mb": round(high_water / (1 << 20), 3),
            "lru_thrash_demotes":
                COORD.counters["pressure_demotes"] - thrash0,
            "residency": residency,
        }
    finally:
        for eng in engines:
            eng.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="force the tiny CPU geometry")
    ap.add_argument("--cold", action="store_true",
                    help="remove the quant artifact first (full load)")
    ap.add_argument("--no-warmup-reuse", action="store_true",
                    help="ignore persistent-cache warmup markers")
    ap.add_argument("--gallery", action="store_true",
                    help="N-model round-robin weight-paging smoke")
    ap.add_argument("--models", type=int, default=4,
                    help="gallery size (with --gallery)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="round-robin passes (with --gallery)")
    args = ap.parse_args()

    if args.no_warmup_reuse:
        os.environ["LOCALAI_WARMUP_REUSE"] = "off"

    if args.gallery:
        g = gallery_shape(n_models=args.models, rounds=args.rounds)
        print(f"\ngallery: {g['n_models']} models x {g['rounds']} "
              f"rounds, {g['tree_mb']:.1f} MB trees under a "
              f"{g['hbm_budget_mb']:.1f} MB weight budget")
        for k in ("cold", "warm", "hot"):
            row = g[f"{k}_first_token_s"]
            print(f"  {k:<5} first token p50 {row['p50'] * 1e3:8.1f} ms"
                  f"   (n={row['n']})")
        print(f"  warm vs cold speedup : {g['warm_vs_cold_speedup']}x")
        print(f"  HBM high water       : {g['hbm_high_water_mb']:.1f} "
              f"MB (budget {g['hbm_budget_mb']:.1f} MB)")
        print(f"  LRU pressure demotes : {g['lru_thrash_demotes']}")
        print(f"  residency at rest    : {g['residency']}")
        print("\nJSON: " + json.dumps(g))
        return

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    import shutil
    import tempfile
    import time

    from bench import _write_hf_checkpoint
    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import register_default_backends
    from localai_tfp_tpu.models.llm_spec import LLMSpec
    from localai_tfp_tpu.server.state import Application

    on_tpu = jax.default_backend() == "tpu" and not args.tiny
    if on_tpu:
        spec = LLMSpec(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_head=128, d_ff=14336, max_position=4096,
            rope_theta=500000.0,
        )
        slots, ctx = 64, 1024
    else:
        spec = LLMSpec(
            vocab_size=512, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, d_head=16, d_ff=128, max_position=256,
        )
        slots, ctx = 2, 128

    import hashlib

    key = hashlib.sha256(
        (repr(spec) + "|writer-v2").encode()).hexdigest()[:16]
    cache_root = os.environ.get(
        "XDG_CACHE_HOME", os.path.expanduser("~/.cache"))
    ckpt = os.path.join(cache_root, f"localai_bench_ckpt_{key}")
    if not os.path.exists(os.path.join(ckpt, ".complete")):
        shutil.rmtree(ckpt, ignore_errors=True)
        print(f"writing checkpoint {ckpt} ...", flush=True)
        _write_hf_checkpoint(ckpt, spec)
        with open(os.path.join(ckpt, ".complete"), "w") as f:
            f.write("ok")

    if args.cold:
        from localai_tfp_tpu.models.artifact_cache import artifact_path

        p = artifact_path(ckpt, "int8_full", "bfloat16")
        if os.path.exists(p):
            os.unlink(p)
            print(f"dropped artifact {p} (cold full load)", flush=True)

    tmp = tempfile.mkdtemp(prefix="coldstart-")
    try:
        models = os.path.join(tmp, "models")
        os.makedirs(models)
        os.symlink(ckpt, os.path.join(models, "ckpt"))
        with open(os.path.join(models, "prof.yaml"), "w") as f:
            f.write(
                "name: prof\n"
                "backend: jax-llm\n"
                "parameters:\n  model: ckpt\n"
                f"context_size: {ctx}\n"
                f"max_batch_slots: {slots}\n"
                "quantization: int8_full\n"
                "kv_cache_dtype: int8\n"
                "decode_steps: 16\n"
                "latency_target_ms: 70\n"
            )
        state = Application(ApplicationConfig(
            models_path=models,
            generated_content_dir=os.path.join(tmp, "generated"),
            upload_dir=os.path.join(tmp, "uploads"),
            config_dir=os.path.join(tmp, "configuration"),
        ))
        register_default_backends()
        state.config_loader.load_configs_from_path()
        t0 = time.perf_counter()
        backend = state.model_loader.load(state.config_loader.get("prof"))
        total = time.perf_counter() - t0
        bd = dict(getattr(backend, "load_breakdown", {}) or {})
        mode = bd.pop("load_mode", getattr(backend, "load_mode", "?"))
        reused = bd.pop("warmup_reused", False)

        print(f"\ncold-start load: {total:.1f}s  mode={mode}  "
              f"warmup_reused={reused}")
        print(f"{'phase':<12}{'seconds':>9}   share")
        tot = bd.get("total_s") or total
        for p in ("read_s", "dequant_s", "transfer_s", "compile_s",
                  "warmup_s", "other_s"):
            v = float(bd.get(p, 0.0))
            bar = "#" * int(40 * v / tot) if tot else ""
            print(f"{p:<12}{v:>9.2f}   {bar}")
        print(f"{'total_s':<12}{float(bd.get('total_s', total)):>9.2f}")
        print("\nJSON: " + json.dumps(
            {**bd, "load_mode": mode, "warmup_reused": reused}))
        # leave the artifact behind so the NEXT run measures artifact
        # mode: the deferred write is abandoned by shutdown(), so wait
        # for it here (idle engine -> starts immediately)
        t = getattr(backend, "_artifact_thread", None)
        if t is not None:
            print("waiting for quant artifact write ...", flush=True)
            t.join(timeout=600)
        backend.shutdown()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
