#!/usr/bin/env python3
"""Pretty-print request-lifecycle traces from a running server.

Fetches ``GET /debug/traces`` (telemetry/tracing.py) and renders each
request as a span timeline:

    $ python tools/trace_report.py --url http://localhost:8080 --model tiny
    a3f9…  tiny  stop  total 412.7 ms  (corr 9bc2…)
      queue          0.0 ms ▕█▏                 3.1 ms
      prefill        3.1 ms ▕██████▏           61.0 ms
      first_token   64.1 ms ▕█████████▏        96.4 ms
      decode       160.5 ms ▕███████████████▏ 252.2 ms

Options: --model filters server-side, --limit caps the count,
--id looks up one distributed trace (trace id / request id /
correlation id / full traceparent header — joins every hop's entry on
this node), --api-key sends a Bearer token, --json emits the raw JSON
payload instead of span bars, --from-file reads a saved payload
instead of a URL (offline triage of a pasted /debug/traces body).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request

BAR_COLS = 34


def fetch(url: str, model: str, limit: int, api_key: str,
          ident: str = "") -> dict:
    q = {"limit": str(limit)}
    if ident:
        q["id"] = ident
    elif model:
        q["model"] = model
    full = f"{url.rstrip('/')}/debug/traces?{urllib.parse.urlencode(q)}"
    req = urllib.request.Request(full)
    if api_key:
        req.add_header("Authorization", f"Bearer {api_key}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def render(trace: dict, out) -> None:
    rid = trace.get("request_id", "")[:12]
    corr = trace.get("correlation_id", "")
    head = (f"{rid}  {trace.get('model') or '-'}  "
            f"{trace.get('status')}  total {trace.get('total_ms')} ms")
    if corr:
        head += f"  (corr {corr[:12]})"
    tid = trace.get("trace_id", "")
    if tid:
        head += f"  trace {tid[:16]}"
    print(head, file=out)
    spans = trace.get("spans") or []
    total = max(float(trace.get("total_ms") or 0.0), 1e-9)
    width = max((len(s["name"]) for s in spans), default=4)
    for s in spans:
        frac = max(float(s["dur_ms"]), 0.0) / total
        bar = "█" * max(1, round(frac * BAR_COLS))
        print(f"  {s['name']:<{width}} {s['start_ms']:>9.1f} ms "
              f"▕{bar:<{BAR_COLS}}▏ {s['dur_ms']:>9.1f} ms", file=out)
    if not spans:
        events = trace.get("events") or []
        for e in events:
            print(f"  {e['phase']:<16} {e['t_ms']:>9.1f} ms", file=out)
    for n in trace.get("span_events") or []:
        attrs = {k: v for k, v in n.items() if k not in ("name", "t_ms")}
        kv = " ".join(f"{k}={v}" for k, v in attrs.items())
        print(f"  * {n['name']:<14} {n['t_ms']:>9.1f} ms  {kv}",
              file=out)
    print(file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pretty-print /debug/traces timelines")
    ap.add_argument("--url", default="http://localhost:8080",
                    help="server base URL")
    ap.add_argument("--model", default="", help="filter by model name")
    ap.add_argument("--id", default="", dest="ident",
                    help="look up one distributed trace: trace id, "
                         "request id, correlation id, or a full "
                         "traceparent header value")
    ap.add_argument("--limit", type=int, default=10)
    ap.add_argument("--api-key", default="", help="Bearer token")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw JSON payload instead of bars")
    ap.add_argument("--from-file", default="",
                    help="read a saved /debug/traces JSON file instead")
    args = ap.parse_args(argv)

    if args.from_file:
        with open(args.from_file, encoding="utf-8") as f:
            payload = json.load(f)
        if args.ident:  # offline --id: client-side join
            traces = [t for t in payload.get("traces") or []
                      if args.ident in (t.get("trace_id"),
                                        t.get("request_id"),
                                        t.get("correlation_id"))]
            payload = {"traces": traces}
    else:
        try:
            payload = fetch(args.url, args.model, args.limit,
                            args.api_key, ident=args.ident)
        except OSError as e:
            print(f"trace_report: cannot reach {args.url}: {e}",
                  file=sys.stderr)
            return 1
    if args.json:
        json.dump(payload, sys.stdout, indent=2)
        print()
        return 0
    traces = payload.get("traces") or []
    if not traces:
        print("no traces recorded (is the server serving requests?)")
        return 0
    for tr in traces:
        render(tr, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
