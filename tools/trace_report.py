#!/usr/bin/env python3
"""Pretty-print request-lifecycle traces from a running server.

Fetches ``GET /debug/traces`` (telemetry/tracing.py) and renders each
request as a span timeline:

    $ python tools/trace_report.py --url http://localhost:8080 --model tiny
    a3f9…  tiny  stop  total 412.7 ms  (corr 9bc2…)
      queue          0.0 ms ▕█▏                 3.1 ms
      prefill        3.1 ms ▕██████▏           61.0 ms
      first_token   64.1 ms ▕█████████▏        96.4 ms
      decode       160.5 ms ▕███████████████▏ 252.2 ms

Options: --model filters server-side, --limit caps the count,
--api-key sends a Bearer token, --json reads a saved payload instead
of a URL (offline triage of a pasted /debug/traces body).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request

BAR_COLS = 34


def fetch(url: str, model: str, limit: int, api_key: str) -> dict:
    q = {"limit": str(limit)}
    if model:
        q["model"] = model
    full = f"{url.rstrip('/')}/debug/traces?{urllib.parse.urlencode(q)}"
    req = urllib.request.Request(full)
    if api_key:
        req.add_header("Authorization", f"Bearer {api_key}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def render(trace: dict, out) -> None:
    rid = trace.get("request_id", "")[:12]
    corr = trace.get("correlation_id", "")
    head = (f"{rid}  {trace.get('model') or '-'}  "
            f"{trace.get('status')}  total {trace.get('total_ms')} ms")
    if corr:
        head += f"  (corr {corr[:12]})"
    print(head, file=out)
    spans = trace.get("spans") or []
    total = max(float(trace.get("total_ms") or 0.0), 1e-9)
    width = max((len(s["name"]) for s in spans), default=4)
    for s in spans:
        frac = max(float(s["dur_ms"]), 0.0) / total
        bar = "█" * max(1, round(frac * BAR_COLS))
        print(f"  {s['name']:<{width}} {s['start_ms']:>9.1f} ms "
              f"▕{bar:<{BAR_COLS}}▏ {s['dur_ms']:>9.1f} ms", file=out)
    if not spans:
        events = trace.get("events") or []
        for e in events:
            print(f"  {e['phase']:<16} {e['t_ms']:>9.1f} ms", file=out)
    print(file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pretty-print /debug/traces timelines")
    ap.add_argument("--url", default="http://localhost:8080",
                    help="server base URL")
    ap.add_argument("--model", default="", help="filter by model name")
    ap.add_argument("--limit", type=int, default=10)
    ap.add_argument("--api-key", default="", help="Bearer token")
    ap.add_argument("--json", default="",
                    help="read a saved /debug/traces JSON file instead")
    args = ap.parse_args(argv)

    if args.json:
        with open(args.json, encoding="utf-8") as f:
            payload = json.load(f)
    else:
        try:
            payload = fetch(args.url, args.model, args.limit,
                            args.api_key)
        except OSError as e:
            print(f"trace_report: cannot reach {args.url}: {e}",
                  file=sys.stderr)
            return 1
    traces = payload.get("traces") or []
    if not traces:
        print("no traces recorded (is the server serving requests?)")
        return 0
    for tr in traces:
        render(tr, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
