"""HTTP 64-burst TTFT phase timeline for the 8B serving config.

BENCH r5 gap: engine-side burst p50 ~237 ms, HTTP-side ~818 ms. This
stamps every stage each request passes through, aggregated across the
wave (all times ms relative to the wave's t0):

  recv    — handler reached (_body awaited): aiohttp accept+parse+route
  built   — PredictOptions ready in the producer thread (template
            render + tokenize done)
  submit  — engine.submit returned (admission queue)
  prefill — the engine dispatched the wave's prefill_final group(s)
  harvest — first tokens harvested (bridge put)
  write   — client saw the first CONTENT SSE event (TTFT)

Run manually on the chip:  python tools/profile_http.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402


from tools.profile_r5 import pct as _pct  # noqa: E402  (shared helper)


def pct(xs, q):
    return round(_pct(xs, q), 1) if xs else None


def main() -> None:
    jax.config.update("jax_compilation_cache_dir", "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from tools.profile_ttft import build_engine

    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import LoadedModel
    from localai_tfp_tpu.server import openai_routes
    from localai_tfp_tpu.server.app import build_app
    from localai_tfp_tpu.server.state import Application
    from localai_tfp_tpu.workers.llm import JaxLLMBackend

    eng, tok, n_req, n_tok = build_engine(False)
    eng.latency_target_ms = 70.0  # bench8b.yaml parity

    tmp = tempfile.mkdtemp(prefix="prof-http-")
    models = os.path.join(tmp, "models")
    os.makedirs(models)
    with open(os.path.join(models, "bench.yaml"), "w") as f:
        f.write(
            "name: bench\nbackend: jax-llm\n"
            "parameters:\n  model: bench\n"
            "template:\n"
            '  chat_message: "{{.RoleName}}: {{.Content}}"\n'
            '  chat: "{{.Input}}\\nassistant:"\n'
        )
    state = Application(ApplicationConfig(
        models_path=models,
        generated_content_dir=os.path.join(tmp, "generated"),
        upload_dir=os.path.join(tmp, "uploads"),
        config_dir=os.path.join(tmp, "configuration"),
    ))
    backend = JaxLLMBackend()
    backend.engine, backend.tokenizer = eng, tok
    backend.spec, backend._state = eng.spec, "READY"
    state.model_loader._models["bench"] = LoadedModel(
        "bench", "jax-llm", backend)
    app = build_app(state)

    # ---- stage stamps ----
    stamps: dict[str, list[float]] = {
        k: [] for k in ("recv", "built", "submit", "prefill", "harvest")}
    t0_box = [0.0]

    orig_body = openai_routes._body

    async def stamped_body(request):
        stamps["recv"].append(time.perf_counter() - t0_box[0])
        return await orig_body(request)

    openai_routes._body = stamped_body

    orig_to_request = backend._to_request

    def stamped_to_request(opts):
        r = orig_to_request(opts)
        stamps["built"].append(time.perf_counter() - t0_box[0])
        return r

    backend._to_request = stamped_to_request

    orig_submit = eng.submit

    def stamped_submit(req):
        q = orig_submit(req)
        stamps["submit"].append(time.perf_counter() - t0_box[0])
        return q

    eng.submit = stamped_submit

    orig_run = eng._run

    def stamped_run(kind, payload):
        if kind == "prefill_final":
            stamps["prefill"].append(time.perf_counter() - t0_box[0])
        return orig_run(kind, payload)

    eng._run = stamped_run

    orig_complete = eng._complete_prefill_final

    def stamped_complete(fl):
        stamps["harvest"].append(time.perf_counter() - t0_box[0])
        return orig_complete(fl)

    eng._complete_prefill_final = stamped_complete

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def one(i, ttfts, first_byte, sent):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": "benchmark " * 2 + str(i)}],
                    "max_tokens": n_tok, "stream": True,
                    "temperature": 0.8, "top_k": 40, "top_p": 0.95,
                    "ignore_eos": True,
                }
                sent[i] = time.perf_counter() - t0_box[0]
                async with sess.post(url, json=body) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        now = time.perf_counter() - t0_box[0]
                        if first_byte[i] is None:
                            first_byte[i] = now
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = json.loads(line[6:])
                        ch = d["choices"][0]
                        if (ch["delta"].get("content")
                                and ttfts[i] is None):
                            ttfts[i] = now
                        if ch.get("finish_reason"):
                            break

            out = {}
            for run in range(4):  # 3 warmup (compile + settle), 1 measured
                for v in stamps.values():
                    v.clear()
                ttfts = [None] * 64
                first_byte = [None] * 64
                sent = [None] * 64
                t0_box[0] = time.perf_counter()
                await asyncio.gather(
                    *[one(i, ttfts, first_byte, sent) for i in range(64)])
                if run < 3:
                    continue
                s = {k: [x * 1e3 for x in v] for k, v in stamps.items()}
                out = {
                    "sent": {"p50": pct([x * 1e3 for x in sent], .5),
                             "max": pct([x * 1e3 for x in sent], 1.0)},
                    **{k: {"min": pct(v, 0.0), "p50": pct(v, .5),
                           "max": pct(v, 1.0), "n": len(v)}
                       for k, v in s.items()},
                    "ttft": {"min": pct([x * 1e3 for x in ttfts if x], 0.0),
                             "p50": pct([x * 1e3 for x in ttfts if x], .5),
                             "p95": pct([x * 1e3 for x in ttfts if x], .95)},
                    "first_byte_p50": pct(
                        [x * 1e3 for x in first_byte if x], .5),
                }
            return out

    loop = asyncio.new_event_loop()
    try:
        report = loop.run_until_complete(drive())
    finally:
        loop.close()
    print(json.dumps(report, indent=1), flush=True)
    eng.close()


if __name__ == "__main__":
    main()
