"""HTTP 64-burst TTFT phase timeline for the 8B serving config.

BENCH r5 gap: engine-side burst p50 ~237 ms, HTTP-side ~818 ms. This
stamps every stage each request passes through, aggregated across the
wave (all times ms relative to the wave's t0):

  recv    — handler reached (_body awaited): aiohttp accept+parse+route
  built   — PredictOptions ready in the producer thread (template
            render + tokenize done)
  submit  — engine.submit returned (admission queue)
  prefill — the engine dispatched the wave's prefill_final group(s)
  harvest — first tokens harvested (bridge put)
  write   — client saw the first CONTENT SSE event (TTFT)

Run manually on the chip:  python tools/profile_http.py

Shared-system-prompt burst scenario (cross-slot prefix cache):

  python tools/profile_http.py --shared-prefix [--small] \
      [--requests N] [--prefix-tokens P]

drives two bursts through the stock endpoint — N requests sharing a
P-token prefix, and N fully distinct requests — each with the prefix
cache ON and OFF, reporting client TTFT, prefill tokens actually
dispatched (counted at the dispatch layer), kvcopy count, and the
telemetry counters cross-checked against the dispatch-level ground
truth. ``--small`` runs the tiny CPU config (smoke).

Mixed-dispatch scenario (stall-free prefill+decode fusion):

  python tools/profile_http.py --mixed [--small] \
      [--streams N] [--bursts K] [--burst-size B]

drives N sustained decode streams and injects K admission bursts of B
requests mid-stream, with the fused mixed dispatcher ON and OFF
(LOCALAI_MIXED_DISPATCH) — the headline numbers for the scheduler's
prefill/decode de-serialization: per-stream ITL p50/p95, the **max
inter-token gap** any live stream saw while a burst was admitting
(the legacy hold loops spike it to the prefill-group round trip), and
burst TTFT p50 (must hold — the fused path keeps wave coalescing at
dispatch granularity).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402


from tools.profile_r5 import pct as _pct  # noqa: E402  (shared helper)


def pct(xs, q):
    return round(_pct(xs, q), 1) if xs else None


def _mk_state(eng, tok):
    """Minimal Application with the in-memory engine registered as
    model "bench" (the scenario measures serving, not the loader)."""
    from localai_tfp_tpu.config.app_config import ApplicationConfig
    from localai_tfp_tpu.engine.loader import LoadedModel
    from localai_tfp_tpu.server.state import Application
    from localai_tfp_tpu.workers.llm import JaxLLMBackend

    tmp = tempfile.mkdtemp(prefix="prof-http-")
    models = os.path.join(tmp, "models")
    os.makedirs(models)
    with open(os.path.join(models, "bench.yaml"), "w") as f:
        f.write(
            "name: bench\nbackend: jax-llm\n"
            "parameters:\n  model: bench\n"
            "template:\n"
            '  chat_message: "{{.RoleName}}: {{.Content}}"\n'
            '  chat: "{{.Input}}\\nassistant:"\n'
        )
    state = Application(ApplicationConfig(
        models_path=models,
        generated_content_dir=os.path.join(tmp, "generated"),
        upload_dir=os.path.join(tmp, "uploads"),
        config_dir=os.path.join(tmp, "configuration"),
    ))
    backend = JaxLLMBackend()
    backend.engine, backend.tokenizer = eng, tok
    backend.spec, backend._state = eng.spec, "READY"
    state.model_loader._models["bench"] = LoadedModel(
        "bench", "jax-llm", backend)
    return state


class _DispatchSpy:
    """Count REAL prefill tokens (pad rows excluded) and kvcopy
    dispatches at the engine._run layer — ground truth for the
    telemetry cross-check."""

    def __init__(self, eng):
        self.eng = eng
        self.prefill_tokens = 0
        self.copies = 0
        self._orig = eng._run
        eng._run = self._run

    def reset(self):
        self.prefill_tokens = 0
        self.copies = 0

    def _run(self, kind, payload):
        if kind == "prefill_final":
            self.prefill_tokens += int(sum(
                int(c) for sid, c in zip(payload["slot_ids"],
                                         payload["n_chunk"])
                if int(sid) < self.eng.n_slots))
        elif kind == "prefill":
            self.prefill_tokens += payload["toks"].shape[1]
        elif kind == "kvcopy":
            self.copies += 1
        return self._orig(kind, payload)


def shared_prefix_scenario(small: bool, n_req: int,
                           prefix_tokens: int) -> None:
    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.engine.prefix_index import PrefixIndex
    from localai_tfp_tpu.server.app import build_app
    from localai_tfp_tpu.telemetry.registry import REGISTRY

    from tools.profile_ttft import build_engine

    eng, tok, _, _ = build_engine(small)
    if small:
        n_req = min(n_req, eng.n_slots)
        prefix_tokens = min(prefix_tokens, eng.max_seq // 2)
    n_tok = 16 if small else 64
    app = build_app(_mk_state(eng, tok))
    spy = _DispatchSpy(eng)
    # byte-level bench tokenizers: 1 char ~ 1 token
    shared = "S" * prefix_tokens
    scenarios = {
        "shared": [shared + f" req {i:03d}" for i in range(n_req)],
        "distinct": [f"{i:03d} " + os.urandom(8).hex() + " distinct"
                     for i in range(n_req)],
    }

    def reset_engine():
        # drop all resident prefixes so each mode starts cold
        for s in eng.slots:
            s.cache_tokens = []
            s.n_past = 0
        eng._prefix_index = PrefixIndex()
        eng._deferred.clear()
        spy.reset()

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        out: dict = {}
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def one(content, ttfts, i, t0):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user", "content": content}],
                    "max_tokens": n_tok, "stream": True,
                    "temperature": 0.0, "ignore_eos": True,
                }
                async with sess.post(url, json=body) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = json.loads(line[6:])
                        ch = d["choices"][0]
                        if (ch["delta"].get("content")
                                and ttfts[i] is None):
                            ttfts[i] = time.perf_counter() - t0
                        if ch.get("finish_reason"):
                            break

            async def wave(contents):
                ttfts = [None] * len(contents)
                t0 = time.perf_counter()
                await asyncio.gather(
                    *[one(c, ttfts, i, t0)
                      for i, c in enumerate(contents)])
                return [x * 1e3 for x in ttfts if x is not None]

            # untimed warm waves in BOTH modes: each mode takes
            # different dispatch shapes (full prefill vs copy + tail)
            # and a first-wave compile would be charged to whichever
            # mode ran first
            for warm_mode in ("off", "on"):
                eng._prefix_enabled = (warm_mode == "on")
                reset_engine()
                await wave(scenarios["shared"])
            for name, contents in scenarios.items():
                out[name] = {}
                for mode in ("off", "on"):
                    eng._prefix_enabled = (mode == "on")
                    reset_engine()
                    snap = REGISTRY.snapshot()
                    ttfts = await wave(contents)
                    delta = REGISTRY.delta(snap)
                    reused = sum(
                        v for k, v in delta.items()
                        if k.startswith("engine_prefix_reused_tokens"))
                    prefilled = sum(
                        v for k, v in delta.items()
                        if k.startswith("engine_prompt_tokens_total"))
                    out[name][mode] = {
                        "ttft_p50_ms": pct(ttfts, .5),
                        "ttft_p95_ms": pct(ttfts, .95),
                        "prefill_tokens_dispatched": spy.prefill_tokens,
                        "kv_copies": spy.copies,
                        "telemetry_reused_tokens": int(reused),
                        "telemetry_prefilled_tokens": int(prefilled),
                        "telemetry_matches_dispatch":
                            int(prefilled) == spy.prefill_tokens,
                    }
        s = out["shared"]
        s["prefill_tokens_saved"] = (
            s["off"]["prefill_tokens_dispatched"]
            - s["on"]["prefill_tokens_dispatched"])
        return out

    loop = asyncio.new_event_loop()
    try:
        report = loop.run_until_complete(drive())
    finally:
        loop.close()
    print(json.dumps(report, indent=1), flush=True)
    eng.close()


def mixed_scenario(small: bool, n_streams: int, n_bursts: int,
                   burst_size: int) -> None:
    """Sustained decode streams + admission bursts injected mid-stream,
    fused mixed dispatch ON vs OFF. Reports per-stream inter-token
    gaps (client-observed SSE event spacing — exactly the stall the
    legacy prefill/decode mutual exclusion produced) and burst TTFT."""
    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.server.app import build_app
    from localai_tfp_tpu.telemetry.registry import REGISTRY

    from tools.profile_ttft import build_engine

    eng, tok, _, _ = build_engine(small)
    if small:
        n_streams = min(n_streams, max(1, eng.n_slots // 2))
        burst_size = max(1, min(burst_size, eng.n_slots - n_streams))
    stream_tokens = 150 if small else 192
    burst_prompt_chars = 110 if small else 600
    burst_gap_s = 0.25 if small else 0.5
    app = build_app(_mk_state(eng, tok))
    eng._prefix_enabled = False  # isolate scheduling from prefix reuse

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        out: dict = {}
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def sse_events(body, on_content):
                async with sess.post(url, json=body) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = json.loads(line[6:])
                        ch = d["choices"][0]
                        if ch["delta"].get("content"):
                            on_content()
                        if ch.get("finish_reason"):
                            break

            async def stream_one(i, tag, times, started):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": f"sustained stream {tag} "
                                             f"{i:02d}"}],
                    "max_tokens": stream_tokens, "stream": True,
                    "temperature": 0.0, "ignore_eos": True,
                }

                def on_content():
                    times[i].append(time.perf_counter())
                    started[i].set()

                await sse_events(body, on_content)

            async def burst_one(tag, j, ttfts, t0):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": "B" * burst_prompt_chars
                                             + f" {tag} {j:02d}"}],
                    "max_tokens": 8, "stream": True,
                    "temperature": 0.0, "ignore_eos": True,
                }
                got = []

                def on_content():
                    if not got:
                        got.append(time.perf_counter() - t0)
                        ttfts.append(got[0] * 1e3)

                await sse_events(body, on_content)

            async def run_once(tag):
                times = [[] for _ in range(n_streams)]
                started = [asyncio.Event() for _ in range(n_streams)]
                burst_ttfts: list[float] = []
                streams = [asyncio.ensure_future(
                    stream_one(i, tag, times, started))
                    for i in range(n_streams)]
                await asyncio.gather(*[e.wait() for e in started])
                burst_tasks = []
                for k in range(n_bursts):
                    t0 = time.perf_counter()
                    burst_tasks += [asyncio.ensure_future(
                        burst_one(f"{tag}-{k}", j, burst_ttfts, t0))
                        for j in range(burst_size)]
                    await asyncio.sleep(burst_gap_s)
                await asyncio.gather(*streams, *burst_tasks)
                return times, burst_ttfts

            for mode in ("off", "on"):
                eng._mixed = (mode == "on")
                await run_once(f"warm-{mode}")  # untimed: compiles
                snap = REGISTRY.snapshot()
                times, burst_ttfts = await run_once(f"run-{mode}")
                delta = REGISTRY.delta(snap)
                gaps, max_gaps = [], []
                for ts in times:
                    g = [(b - a) * 1e3 for a, b in zip(ts, ts[1:])]
                    if g:
                        gaps += g
                        max_gaps.append(max(g))
                out[mode] = {
                    "itl_p50_ms": pct(gaps, .5),
                    "itl_p95_ms": pct(gaps, .95),
                    "max_gap_p50_ms": pct(max_gaps, .5),
                    "max_gap_max_ms": pct(max_gaps, 1.0),
                    "burst_ttft_p50_ms": pct(burst_ttfts, .5),
                    "burst_ttft_p95_ms": pct(burst_ttfts, .95),
                    "mixed_dispatches": int(sum(
                        v for k, v in delta.items()
                        if k.startswith("engine_mixed_dispatch_total")
                        and 'composition="mixed"' in k)),
                }
        on, off = out["on"], out["off"]
        out["summary"] = {
            "streams": n_streams, "bursts": n_bursts,
            "burst_size": burst_size,
            "max_gap_reduction_ms": round(
                off["max_gap_max_ms"] - on["max_gap_max_ms"], 1),
            "itl_p95_reduction_ms": round(
                off["itl_p95_ms"] - on["itl_p95_ms"], 1),
            "burst_ttft_ratio_on_vs_off": round(
                on["burst_ttft_p50_ms"] / off["burst_ttft_p50_ms"], 3)
            if off["burst_ttft_p50_ms"] else None,
        }
        return out

    loop = asyncio.new_event_loop()
    try:
        report = loop.run_until_complete(drive())
    finally:
        loop.close()
    # ragged paged attention: jit-cache variant counts + warmup wall
    # time, on vs off (the compile-variant collapse riding the same
    # mixed-traffic scheduler this scenario stresses)
    from bench import ragged_variant_report

    report["ragged_attn"] = ragged_variant_report()
    print(json.dumps(report, indent=1), flush=True)
    eng.close()


def main() -> None:
    from tools.profile_ttft import build_engine

    from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

    from localai_tfp_tpu.server import openai_routes
    from localai_tfp_tpu.server.app import build_app

    eng, tok, n_req, n_tok = build_engine(False)
    eng.latency_target_ms = 70.0  # bench8b.yaml parity

    state = _mk_state(eng, tok)
    backend = state.model_loader._models["bench"].backend
    app = build_app(state)

    # ---- stage stamps ----
    stamps: dict[str, list[float]] = {
        k: [] for k in ("recv", "built", "submit", "prefill", "harvest")}
    t0_box = [0.0]

    orig_body = openai_routes._body

    async def stamped_body(request):
        stamps["recv"].append(time.perf_counter() - t0_box[0])
        return await orig_body(request)

    openai_routes._body = stamped_body

    orig_to_request = backend._to_request

    def stamped_to_request(opts):
        r = orig_to_request(opts)
        stamps["built"].append(time.perf_counter() - t0_box[0])
        return r

    backend._to_request = stamped_to_request

    orig_submit = eng.submit

    def stamped_submit(req):
        q = orig_submit(req)
        stamps["submit"].append(time.perf_counter() - t0_box[0])
        return q

    eng.submit = stamped_submit

    orig_run = eng._run

    def stamped_run(kind, payload):
        if kind == "prefill_final":
            stamps["prefill"].append(time.perf_counter() - t0_box[0])
        return orig_run(kind, payload)

    eng._run = stamped_run

    orig_complete = eng._complete_prefill_final

    def stamped_complete(fl):
        stamps["harvest"].append(time.perf_counter() - t0_box[0])
        return orig_complete(fl)

    eng._complete_prefill_final = stamped_complete

    async def drive():
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/v1/chat/completions"
        async with ClientSession(
            connector=TCPConnector(limit=0),
            timeout=ClientTimeout(total=3600),
        ) as sess:

            async def one(i, ttfts, first_byte, sent):
                body = {
                    "model": "bench",
                    "messages": [{"role": "user",
                                  "content": "benchmark " * 2 + str(i)}],
                    "max_tokens": n_tok, "stream": True,
                    "temperature": 0.8, "top_k": 40, "top_p": 0.95,
                    "ignore_eos": True,
                }
                sent[i] = time.perf_counter() - t0_box[0]
                async with sess.post(url, json=body) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        now = time.perf_counter() - t0_box[0]
                        if first_byte[i] is None:
                            first_byte[i] = now
                        if not line.startswith(b"data: "):
                            continue
                        if line.strip() == b"data: [DONE]":
                            break
                        d = json.loads(line[6:])
                        ch = d["choices"][0]
                        if (ch["delta"].get("content")
                                and ttfts[i] is None):
                            ttfts[i] = now
                        if ch.get("finish_reason"):
                            break

            out = {}
            for run in range(4):  # 3 warmup (compile + settle), 1 measured
                for v in stamps.values():
                    v.clear()
                ttfts = [None] * 64
                first_byte = [None] * 64
                sent = [None] * 64
                t0_box[0] = time.perf_counter()
                await asyncio.gather(
                    *[one(i, ttfts, first_byte, sent) for i in range(64)])
                if run < 3:
                    continue
                s = {k: [x * 1e3 for x in v] for k, v in stamps.items()}
                out = {
                    "sent": {"p50": pct([x * 1e3 for x in sent], .5),
                             "max": pct([x * 1e3 for x in sent], 1.0)},
                    **{k: {"min": pct(v, 0.0), "p50": pct(v, .5),
                           "max": pct(v, 1.0), "n": len(v)}
                       for k, v in s.items()},
                    "ttft": {"min": pct([x * 1e3 for x in ttfts if x], 0.0),
                             "p50": pct([x * 1e3 for x in ttfts if x], .5),
                             "p95": pct([x * 1e3 for x in ttfts if x], .95)},
                    "first_byte_p50": pct(
                        [x * 1e3 for x in first_byte if x], .5),
                }
            return out

    loop = asyncio.new_event_loop()
    try:
        report = loop.run_until_complete(drive())
    finally:
        loop.close()
    print(json.dumps(report, indent=1), flush=True)
    eng.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--shared-prefix", action="store_true",
                    help="shared-system-prompt burst scenario "
                         "(prefix cache on vs off)")
    ap.add_argument("--mixed", action="store_true",
                    help="sustained decode + admission bursts, fused "
                         "mixed dispatch on vs off")
    ap.add_argument("--small", action="store_true",
                    help="tiny CPU config (smoke) instead of 8B")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--prefix-tokens", type=int, default=512)
    ap.add_argument("--streams", type=int, default=48,
                    help="--mixed: sustained decode streams")
    ap.add_argument("--bursts", type=int, default=3,
                    help="--mixed: admission bursts injected mid-stream")
    ap.add_argument("--burst-size", type=int, default=16,
                    help="--mixed: requests per burst")
    args = ap.parse_args()
    jax.config.update("jax_compilation_cache_dir",
                      "/root/.cache/localai_xla")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    if args.shared_prefix:
        shared_prefix_scenario(args.small, args.requests,
                               args.prefix_tokens)
    elif args.mixed:
        mixed_scenario(args.small, args.streams, args.bursts,
                       args.burst_size)
    else:
        main()
