#!/usr/bin/env python3
"""Static telemetry lint: metric-name contract + README coverage.

Thin compatibility wrapper: the check itself now lives in the graftlint
framework as the ``metrics-contract`` rule
(tools/lint/rules/metrics_contract.py) so it shares the suppression/
baseline machinery and runs in the tier-1 ``python -m tools.lint`` gate.
This CLI keeps the historical entry point (bench scripts, CI
invocations, tests/test_telemetry.py) working unchanged:

    python tools/check_metrics.py      # exit 0 iff the contract holds
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tools.lint import load_context, run_rules  # noqa: E402
from tools.lint.rules.metrics_contract import (  # noqa: E402,F401
    REQUIRED_FAMILIES, SUFFIXES, MetricsContract, find_registrations,
)


def main(argv=None) -> int:
    ctx = load_context(ROOT)
    problems = run_rules(ctx, [MetricsContract()])
    regs, _ = find_registrations(ctx)
    if problems:
        for p in problems:
            print(f"check_metrics: {p.render()}", file=sys.stderr)
        print(f"check_metrics: {len(problems)} problem(s) in "
              f"{len(regs)} registration(s)", file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({len(regs)} metric registrations)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
