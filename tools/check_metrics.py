#!/usr/bin/env python3
"""Static telemetry lint: metric-name contract + README coverage.

Scans ``localai_tfp_tpu/`` for registry registrations
(``REGISTRY.counter("...")`` / ``.gauge`` / ``.histogram``) and fails
when any registered name

- is not snake_case,
- is missing a unit suffix — counters MUST end in ``_total``;
  histograms in ``_seconds``/``_bytes``; gauges in one of
  ``_seconds``/``_bytes``/``_count``/``_ratio``/``_info`` — or
- does not appear in the README.md "Observability" table.

Run from the repo root:  python tools/check_metrics.py
Wired into the test suite (tests/test_telemetry.py) so metric drift
fails tier-1 instead of silently rotting dashboards and this table.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PKG = ROOT / "localai_tfp_tpu"
README = ROOT / "README.md"

_SNAKE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")
# one registration: `<registry>.counter(\n?  "name"` — literal names
# only; a computed name cannot be linted or documented and is a finding
_REG = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*\n?\s*['\"]([A-Za-z0-9_]+)['\"]"
)

_SUFFIXES = {
    "counter": ("_total",),
    "histogram": ("_seconds", "_bytes"),
    "gauge": ("_seconds", "_bytes", "_count", "_ratio", "_info"),
}

# rate/intensity gauges: a unit suffix followed by a `_per_<x>`
# qualifier (Prometheus bytes_per_second convention) is also valid
_PER_GAUGE = re.compile(r"_(seconds|bytes|count)_per_[a-z0-9_]+$")

# families that MUST exist (removing one silently breaks dashboards
# and the bench's extra blocks): the paged-KV pool series introduced
# with the block-granular HBM allocator
REQUIRED_FAMILIES = {
    "engine_kv_pages_in_use_count",
    "engine_kv_pages_shared_count",
    "engine_kv_page_alloc_total",
    "engine_kv_hbm_per_live_token_bytes",
    # ragged paged attention: the variant-explosion kill must stay
    # visible and regression-guarded
    "engine_dispatch_compile_variants_count",
    "engine_ragged_rows_total",
}


def find_registrations() -> list[tuple[str, str, str]]:
    """(kind, name, file) for every literal registration in the
    package."""
    out = []
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _REG.finditer(text):
            out.append((m.group(1), m.group(2),
                        str(path.relative_to(ROOT))))
    return out


def main(argv=None) -> int:
    regs = find_registrations()
    problems: list[str] = []
    if not regs:
        problems.append("no metric registrations found under "
                        f"{PKG} — scanner or layout broke")
    try:
        readme = README.read_text(encoding="utf-8")
    except OSError:
        readme = ""
        problems.append(f"cannot read {README}")
    for kind, name, where in regs:
        if not _SNAKE.match(name):
            problems.append(
                f"{where}: metric '{name}' is not snake_case")
        if not name.endswith(_SUFFIXES[kind]) and not (
                kind == "gauge" and _PER_GAUGE.search(name)):
            problems.append(
                f"{where}: {kind} '{name}' lacks a unit suffix "
                f"(one of {', '.join(_SUFFIXES[kind])})")
        if readme and f"`{name}`" not in readme:
            problems.append(
                f"{where}: metric '{name}' is not documented in the "
                f"README.md Observability table (add a `{name}` row)")
    missing = REQUIRED_FAMILIES - {name for _, name, _ in regs}
    for name in sorted(missing):
        problems.append(
            f"required metric family '{name}' is not registered "
            "anywhere under localai_tfp_tpu/")
    if problems:
        for p in problems:
            print(f"check_metrics: {p}", file=sys.stderr)
        print(f"check_metrics: {len(problems)} problem(s) in "
              f"{len(regs)} registration(s)", file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({len(regs)} metric registrations)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
