"""Paged KV pool profiler: occupancy, sharing, HBM-per-live-token.

The paged pool's whole point is that HBM follows LIVE tokens instead of
worst-case context and that shared prefixes cost refcount bumps instead
of row copies. This tool measures both claims under the two traffic
shapes that stress them:

  python tools/profile_kv.py --shared-prefix [--small] \
      [--requests N] [--prefix-tokens P]

drives a burst of N requests sharing a P-token prefix, then N fully
distinct requests, straight through the engine scheduler. Reports, per
burst: page-allocation outcomes (fresh / zero-copy shared / COW),
kvcopy dispatches (whole-page shares must need ZERO for the aligned
prefix body), peak pool occupancy, share ratio (refs vs distinct
pages), and HBM bytes per live token.

  python tools/profile_kv.py --mixed [--small] \
      [--streams N] [--bursts K] [--burst-size B]

sustains N decode streams while injecting K admission bursts of B
requests, sampling the pool every 50 ms. Reports peak/mean occupancy
and HBM-per-live-token across the run — the series that shows the
arena tracking expected context while traffic churns.

  python tools/profile_kv.py --returning-users [--small] [--users N]

measures the tiered KV memory claim (engine/kv_tier.py): N distinct
sessions (N > n_slots) are served through slot churn, then every user
RETURNS. With LOCALAI_KV_TIER=off a returning session re-prefills
unless it still sits in a slot; with the tier on, demoted sessions are
prefetched back from host RAM. Reports resident-session capacity
(off vs on, and the multiple), prefetch hit rate, re-prefill tokens
avoided, and re-prefill tokens paid on hits (must be ZERO — a hit
promotes the full covered prefix by reference).

``--small`` runs the tiny CPU config (smoke) with a 16-token page so
page-granular sharing is visible at toy prompt lengths.
"""

from __future__ import annotations

import argparse
import json
import os
import queue as _queue
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _CopySpy:
    """Count kvcopy dispatches at the engine._run layer — ground truth
    for the zero-copy claim (telemetry is cross-checked against it)."""

    def __init__(self, eng):
        self.eng = eng
        self.copies = 0
        self._orig = eng._run
        eng._run = self._run

    def reset(self):
        self.copies = 0

    def _run(self, kind, payload):
        if kind == "kvcopy":
            self.copies += 1
        return self._orig(kind, payload)


def _pool_block(eng) -> dict:
    from bench import _paged_kv_extra

    return _paged_kv_extra(eng)


def _drain_all(qs, timeout=300):
    pending = list(qs)
    while pending:
        nxt = []
        for q in pending:
            done = False
            while True:
                try:
                    ev = q.get_nowait()
                except _queue.Empty:
                    break
                if ev.done:
                    if ev.error:
                        raise RuntimeError(ev.error)
                    done = True
                    break
            if not done:
                nxt.append(q)
        pending = nxt
        if pending:
            time.sleep(0.002)


def _build(small: bool):
    if small:
        # 16-token pages: page-run sharing becomes visible at toy
        # prompt lengths (the default 256-token page needs a 256-token
        # aligned prefix before the first zero-copy share)
        os.environ.setdefault("LOCALAI_KV_PAGE", "16")
    from tools.profile_ttft import build_engine

    return build_engine(small)


def shared_prefix_shape(small: bool, n_req: int,
                        prefix_tokens: int) -> dict:
    from localai_tfp_tpu.engine.engine import GenRequest
    from localai_tfp_tpu.engine.prefix_index import PrefixIndex

    eng, tok, _, _ = _build(small)
    if small:
        n_req = min(n_req, eng.n_slots)
        prefix_tokens = min(prefix_tokens, eng.max_seq // 2)
    n_tok = 8 if small else 32
    spy = _CopySpy(eng)
    out: dict = {"paged": getattr(eng, "_paged", False),
                 "page_tokens": getattr(eng, "_page", None)}
    shared = "S" * prefix_tokens
    shapes = {
        "shared": [shared + f" req {i:03d}" for i in range(n_req)],
        "distinct": [f"{i:03d} " + os.urandom(8).hex() + " distinct"
                     for i in range(n_req)],
    }
    try:
        # warm pass compiles every dispatch variant the measured waves
        # hit, so wave timing reflects the allocator, not the jit
        _drain_all(eng.submit_many([
            GenRequest(prompt_ids=tok.encode(c), max_tokens=n_tok,
                       temperature=0.0, ignore_eos=True)
            for c in shapes["shared"]]))
        for name, contents in shapes.items():
            # cold start per shape: drop residents so occupancy and
            # sharing are attributable to THIS wave
            for s in eng.slots:
                s.cache_tokens = []
                s.n_past = 0
                if eng._paged:
                    eng._pool.drop(s.idx)
            eng._prefix_index = PrefixIndex()
            spy.reset()
            alloc0 = (dict(eng._pool.allocs) if eng._paged else {})
            # donor first (its KV must be resident before sharers), then
            # the sharer wave
            _drain_all(eng.submit_many([GenRequest(
                prompt_ids=tok.encode(contents[0]), max_tokens=n_tok,
                temperature=0.0, ignore_eos=True)]))
            _drain_all(eng.submit_many([
                GenRequest(prompt_ids=tok.encode(c), max_tokens=n_tok,
                           temperature=0.0, ignore_eos=True)
                for c in contents[1:]]))
            blk = _pool_block(eng)
            if eng._paged:
                blk["alloc"] = {k: v - alloc0.get(k, 0)
                                for k, v in eng._pool.allocs.items()}
            blk["kv_copies"] = spy.copies
            out[name] = blk
        if out["paged"]:
            sh = out["shared"]
            sh["share_ratio"] = round(
                sh["page_refs"] / max(sh["pages_in_use"], 1), 3)
    finally:
        eng.close()
    return out


def mixed_shape(small: bool, n_streams: int, n_bursts: int,
                burst_size: int) -> dict:
    from localai_tfp_tpu.engine.engine import GenRequest

    eng, tok, _, _ = _build(small)
    n_streams = min(n_streams, max(1, eng.n_slots // 2))
    burst_size = min(burst_size, max(1, eng.n_slots - n_streams))
    n_tok = 48 if small else 128
    bp = "burst " * max(1, min(eng.max_seq // 2, 256) // 6)
    out: dict = {"paged": getattr(eng, "_paged", False),
                 "page_tokens": getattr(eng, "_page", None),
                 "streams": n_streams, "bursts": n_bursts,
                 "burst_size": burst_size}
    samples: list[tuple[int, float]] = []  # (pages_in_use, hbm/tok)
    stop = threading.Event()

    def sampler():
        while not stop.wait(0.05):
            if not eng._paged:
                continue
            st = eng._pool.stats()
            live = sum(len(s.cache_tokens) for s in eng.slots)
            c = eng.cache
            tb = 2 * c.k.dtype.itemsize * c.k.shape[0] * c.k.shape[-1]
            if c.quantized:
                tb += 2 * 4 * c.k.shape[0]
            samples.append((st.in_use,
                            st.in_use * eng._page * tb / max(live, 1)))

    try:
        # warm compile pass
        _drain_all(eng.submit_many([GenRequest(
            prompt_ids=tok.encode(bp + "w"), max_tokens=4,
            temperature=0.0, ignore_eos=True)]))
        t = threading.Thread(target=sampler, daemon=True)
        t.start()
        qs = eng.submit_many([
            GenRequest(prompt_ids=tok.encode(f"stream {i:02d}"),
                       max_tokens=n_tok, temperature=0.0,
                       ignore_eos=True)
            for i in range(n_streams)])
        burst_qs = []
        for j in range(n_bursts):
            time.sleep(0.1)
            burst_qs += eng.submit_many([
                GenRequest(prompt_ids=tok.encode(bp + f"{j}-{b}"),
                           max_tokens=8, temperature=0.0,
                           ignore_eos=True)
                for b in range(burst_size)])
        _drain_all(qs + burst_qs)
        stop.set()
        t.join(timeout=2)
        blk = _pool_block(eng)
        if samples:
            occ = [s[0] for s in samples]
            hbm = [s[1] for s in samples]
            blk["pages_in_use_peak"] = max(occ)
            blk["pages_in_use_mean"] = round(sum(occ) / len(occ), 1)
            blk["hbm_bytes_per_live_token_peak"] = round(max(hbm), 1)
            blk["hbm_bytes_per_live_token_mean"] = round(
                sum(hbm) / len(hbm), 1)
        out["pool"] = blk
    finally:
        stop.set()
        eng.close()
    return out


def _resident_sessions(eng, ids) -> int:
    """Sessions whose full prompt KV is still reachable without a
    re-prefill: resident in a slot, or promotable from the tier."""

    def covered(pid) -> bool:
        need = len(pid) - 1  # the relogit token always reprocesses
        if any(_common(s.cache_tokens, pid) >= need for s in eng.slots):
            return True
        tier = getattr(eng, "_tier", None)
        if tier is not None:
            _, n = tier._lookup(pid)
            return n >= need
        return False

    return sum(1 for pid in ids if covered(pid))


def _common(a, b) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


def returning_users_shape(small: bool, n_users: int) -> dict:
    """Churn n_users distinct sessions through the slots, then have
    every user return — tier off vs on, same traffic."""
    from localai_tfp_tpu.engine.engine import GenRequest

    out: dict = {"users": n_users}
    saved = os.environ.get("LOCALAI_KV_TIER")
    try:
        for mode in ("off", "on"):
            os.environ["LOCALAI_KV_TIER"] = mode
            eng, tok, _, _ = _build(small)
            tier = getattr(eng, "_tier", None)
            ids = [tok.encode(f"user {i:03d} " + "ctx " * 12
                              + f"tail {i}")
                   for i in range(n_users)]
            total_prompt = sum(len(i) for i in ids)

            def serve(round_ids):
                for lo in range(0, len(round_ids), eng.n_slots):
                    _drain_all(eng.submit_many([
                        GenRequest(prompt_ids=pid, max_tokens=4,
                                   temperature=0.0, ignore_eos=True)
                        for pid in round_ids[lo:lo + eng.n_slots]]))
                if tier is not None:
                    tier.settle()

            try:
                serve(ids)  # round 1: every session served once
                blk: dict = {
                    "resident_sessions": _resident_sessions(eng, ids),
                }
                reused0 = eng.metrics.prefix_reused_tokens
                t0 = (dict(tier.counters) if tier is not None else {})
                wall = time.perf_counter()
                serve(ids)  # round 2: every user returns
                wall = time.perf_counter() - wall
                reused = eng.metrics.prefix_reused_tokens - reused0
                blk["return_wall_s"] = round(wall, 3)
                blk["reprefill_tokens"] = total_prompt - reused
                blk["reused_tokens"] = reused
                if tier is not None:
                    tc = {k: tier.counters[k] - t0.get(k, 0)
                          for k in tier.counters}
                    ret = tc["prefetch_hit"] + tc["prefetch_late"] \
                        + tc["prefetch_miss"]
                    blk["prefetch_hits"] = tc["prefetch_hit"]
                    blk["prefetch_hit_rate"] = round(
                        tc["prefetch_hit"] / max(ret, 1), 3)
                    blk["tier_reused_tokens"] = tc["reused_tokens"]
                    # a hit promotes the full covered prompt (less the
                    # relogit token): re-prefill paid on hits must be 0
                    blk["reprefill_tokens_on_hits"] = (
                        tc["prefetch_hit"] * (len(ids[0]) - 1)
                        - min(tc["reused_tokens"],
                              tc["prefetch_hit"] * (len(ids[0]) - 1)))
                    blk["tier"] = {k: v for k, v in
                                   tier.stats().items() if v}
                    tier.leak_check()
                if eng._paged:
                    eng._pool.leak_check()
                out[mode] = blk
            finally:
                eng.close()
    finally:
        if saved is None:
            os.environ.pop("LOCALAI_KV_TIER", None)
        else:
            os.environ["LOCALAI_KV_TIER"] = saved
    out["capacity_multiple"] = round(
        out["on"]["resident_sessions"]
        / max(out["off"]["resident_sessions"], 1), 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--small", action="store_true",
                    help="tiny CPU config (smoke)")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="shared-prefix burst vs distinct burst")
    ap.add_argument("--mixed", action="store_true",
                    help="sustained streams + admission bursts")
    ap.add_argument("--returning-users", action="store_true",
                    help="session churn + return: KV tiering on vs off")
    ap.add_argument("--users", type=int, default=16)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--prefix-tokens", type=int, default=96)
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--bursts", type=int, default=3)
    ap.add_argument("--burst-size", type=int, default=4)
    args = ap.parse_args()
    if not (args.shared_prefix or args.mixed or args.returning_users):
        ap.error("pick a traffic shape: --shared-prefix, --mixed "
                 "and/or --returning-users")
    report: dict = {}
    if args.shared_prefix:
        report["shared_prefix"] = shared_prefix_shape(
            args.small, args.requests, args.prefix_tokens)
    if args.mixed:
        report["mixed"] = mixed_shape(args.small, args.streams,
                                      args.bursts, args.burst_size)
    if args.returning_users:
        report["returning_users"] = returning_users_shape(
            args.small, args.users)
    # ragged paged attention: jit-cache variant counts + warmup wall
    # time, on vs off — the compile-variant collapse next to the pool
    # numbers it rides on
    from bench import ragged_variant_report

    report["ragged_attn"] = ragged_variant_report()
    print(json.dumps(report, indent=1), flush=True)


if __name__ == "__main__":
    main()
