"""Roofline profile: warmup-captured cost model on a live engine.

Builds a tiny engine, runs the warmup pass (which AOT-captures every
dispatch variant's XLA flops / bytes-accessed — telemetry/costmodel.py),
drives a little real traffic, and prints one JSON report:

  per-kind rows      — accounted FLOPs, bytes accessed, dispatch count,
                       arithmetic intensity, compute- vs bandwidth-bound
                       against the platform ridge point
  mfu_ewma           — EWMA model-flops-utilization from flight spans
  verdicts           — decode_bandwidth_bound / prefill_compute_bound:
                       the physical shape the cost model must recover
                       (decode re-reads the weights per token; batched
                       prefill amortizes them over the bucket)

Run:  python tools/profile_roofline.py [--requests N] [--max-tokens N]

CPU smoke (what CI can afford):

  python tools/profile_roofline.py --smoke

Cost-scheduling probe (``--mixed``): the adversarial long-prompt flood
— sustained decode streams on half the slots while near-context-length
prompts land continuously. Runs the flood twice, token-budget
scheduling (LOCALAI_COST_SCHED=off) then ms-budget scheduling (on,
with an explicit LOCALAI_ITL_BUDGET_MS derived from the off run), and
reports each mode's ITL p99 + max inter-token gap plus the
predicted-vs-measured device-time geomean ratio after EWMA warmup.
``run_mixed(smoke=True)`` is the CPU leg bench.py embeds as
``extra.cost_sched``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_engine(n_slots=4, max_seq=128):
    import jax
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    eng = LLMEngine(spec, params, tk, n_slots=n_slots, max_seq=max_seq,
                    prefill_buckets=(8, 32, 128), cache_dtype=jnp.float32)
    return eng, tk


def run(requests: int, max_tokens: int) -> dict:
    from localai_tfp_tpu.engine.engine import GenRequest

    eng, tk = _build_engine()
    try:
        # warmup is where capture happens: every compiled variant's
        # cost_analysis() lands in the table keyed by dispatch signature
        eng.warmup()
        # real traffic so the totals and the MFU EWMA have samples; a
        # long prompt exercises the big prefill bucket, the decode tail
        # exercises the per-token path
        for i in range(requests):
            ev = eng.generate(GenRequest(
                prompt_ids=tk.encode(f"roofline probe {i} " * 4),
                max_tokens=max_tokens, ignore_eos=True))
            if ev.finish_reason not in ("length", "stop"):
                raise SystemExit(
                    f"probe request ended {ev.finish_reason!r} — the "
                    "report below would have no dispatch traffic")
        stats = eng.cost_stats()
    finally:
        eng.close()
    if stats is None:
        raise SystemExit("cost model disabled — set LOCALAI_COSTMODEL=on")

    kinds = stats["kinds"]
    decode = {k: v for k, v in kinds.items() if k.startswith("decode")}
    prefill = {k: v for k, v in kinds.items()
               if k.startswith("prefill") or k == "mixed"}
    stats["verdicts"] = {
        # decode must sit under the ridge (weights re-read per token)...
        "decode_bandwidth_bound": bool(decode) and all(
            v["bound"] == "bandwidth" for v in decode.values()),
        # ...and batched prefill above it (weights amortized per bucket)
        "prefill_compute_bound": bool(prefill) and any(
            v["bound"] == "compute" for v in prefill.values()),
    }
    return stats


def _flood_leg(n_tok: int, flood_n: int) -> dict:
    """One long-prompt flood against a fresh engine under the CURRENT
    LOCALAI_COST_SCHED / LOCALAI_ITL_BUDGET_MS environment: sustained
    decode streams on half the slots, near-context prompts landing
    continuously, per-stream inter-event gaps collected host-side.
    Returns gap percentiles plus every (predicted, measured) ms pair
    the harvests produced."""
    import os
    import queue as _queue
    import time

    from localai_tfp_tpu.engine.engine import GenRequest

    eng, tk = _build_engine()
    pairs: list[tuple[float, float]] = []
    try:
        # force the full warmup pass even when a persistent-cache
        # marker would skip it: a skipped warmup smears per-variant
        # trace + cache-load time into the first measured dispatches,
        # inflating both the ITL tail and the calibration EWMAs this
        # harness exists to validate
        reuse_prev = os.environ.get("LOCALAI_WARMUP_REUSE")
        os.environ["LOCALAI_WARMUP_REUSE"] = "off"
        try:
            eng.warmup()
        finally:
            if reuse_prev is None:
                os.environ.pop("LOCALAI_WARMUP_REUSE", None)
            else:
                os.environ["LOCALAI_WARMUP_REUSE"] = reuse_prev
        n_streams = max(1, eng.n_slots // 2)
        long_prompt = "flood " * ((eng.max_seq * 3 // 4) // 6)
        # calibration traffic BEFORE the measurement spy goes in: warm
        # the per-kind/per-variant EWMAs on the same shapes the flood
        # will dispatch (the fallback-before-warm path is unit-tested;
        # here we want the converged predictor). Two mini-flood rounds
        # — concurrent short streams + near-context prompts — touch
        # the mixed, decodek and chunked-prefill variants the real
        # flood measures.
        for i in range(2):
            eng.generate(GenRequest(
                prompt_ids=tk.encode(f"calibrate {i} " * 8),
                max_tokens=8, ignore_eos=True))
        for rnd in range(2):
            calib_qs = eng.submit_many(
                [GenRequest(
                    prompt_ids=tk.encode(f"calib {rnd} {i:02d}"),
                    max_tokens=8, temperature=0.0, ignore_eos=True)
                 for i in range(n_streams)]
                + [GenRequest(
                    prompt_ids=tk.encode(long_prompt + f"c{rnd}{j}"),
                    max_tokens=2, ignore_eos=True)
                   for j in range(2)])
            for q in calib_qs:
                while not q.get(timeout=300).done:
                    pass
        cm = eng._costmodel
        warm_keys: set = set()
        if cm is not None:
            # variants the calibration rounds already converged — their
            # flood samples are all "after warmup"; anything else first
            # touched mid-flood still gets the cold-sample skip in
            # _geomean_ratio
            with cm._lock:
                warm_keys = {k for k, c in cm._calib_var.items()
                             if c[1] >= 2}
            # record predicted-vs-measured at the same point the
            # calibration fold sees them
            orig = cm.on_harvest

            def spy(kind, key, span_s, predicted_ms=None):
                if predicted_ms is not None and span_s > 0.0:
                    pairs.append((key, predicted_ms, span_s * 1e3))
                return orig(kind, key, span_s,
                            predicted_ms=predicted_ms)

            cm.on_harvest = spy
        qs = eng.submit_many([
            GenRequest(prompt_ids=tk.encode(f"stream {i:02d}"),
                       max_tokens=n_tok, temperature=0.0,
                       ignore_eos=True)
            for i in range(n_streams)])
        times: list[list[float]] = [[] for _ in range(n_streams)]
        done = [False] * n_streams
        for i, q in enumerate(qs):  # all streams live before the flood
            ev = q.get(timeout=300)
            assert not ev.done, ev.error
            times[i].append(time.perf_counter())
        flood_qs: list = []
        flood_done: list[bool] = []
        next_flood = 0
        while not all(done):
            idle = True
            # keep the flood saturated: one long prompt in the queue
            # per free-ish slot until flood_n have been injected
            in_flight = sum(1 for d in flood_done if not d)
            if next_flood < flood_n and in_flight < 2:
                q = eng.submit_many([GenRequest(
                    prompt_ids=tk.encode(long_prompt + f"{next_flood:02d}"),
                    max_tokens=4, temperature=0.0, ignore_eos=True)])[0]
                flood_qs.append(q)
                flood_done.append(False)
                next_flood += 1
                idle = False
            for i, q in enumerate(qs):
                if done[i]:
                    continue
                try:
                    ev = q.get_nowait()
                except _queue.Empty:
                    continue
                idle = False
                if ev.done:
                    done[i] = True
                elif ev.token_id is not None:
                    times[i].append(time.perf_counter())
            for j, q in enumerate(flood_qs):
                if flood_done[j]:
                    continue
                try:
                    ev = q.get_nowait()
                except _queue.Empty:
                    continue
                idle = False
                if ev.done:
                    flood_done[j] = True
            if idle:
                time.sleep(0.001)
        for j, q in enumerate(flood_qs):  # drain stragglers pre-close
            while not flood_done[j]:
                try:
                    flood_done[j] = q.get(timeout=300).done
                except _queue.Empty:
                    break
    finally:
        eng.close()
    gaps: list[float] = []
    max_gaps: list[float] = []
    for ts in times:
        g = [(b - a) * 1e3 for a, b in zip(ts, ts[1:])]
        if g:
            gaps += g
            max_gaps.append(max(g))
    gaps.sort()
    return {
        "streams": n_streams,
        "flood_injected": next_flood,
        "predicted_pairs": len(pairs),
        "itl_p50_ms": round(gaps[len(gaps) // 2], 2) if gaps else None,
        "itl_p99_ms": round(gaps[min(len(gaps) - 1,
                                     int(len(gaps) * 0.99))], 2)
        if gaps else None,
        "max_gap_ms": round(max(max_gaps), 2) if max_gaps else None,
        "pairs": pairs,
        "warm_keys": warm_keys,
    }


def _geomean_ratio(pairs: list[tuple],
                   warm_keys: frozenset = frozenset()) -> float | None:
    """Geomean predicted/measured AFTER EWMA warmup: a variant first
    touched mid-flood spends its first two harvests on cold
    analytic-only predictions (the calibration EWMA needs two samples
    before predict_ms trusts it), so those are excluded; variants in
    ``warm_keys`` converged during the calibration rounds and count
    from their first flood sample — the gate measures the converged
    predictor, not the bootstrap. Ratios are mean-predicted over
    mean-measured PER VARIANT, then geomean'd across variants: a
    single span's wall time swings several-x with pipeline occupancy
    (the predictor models the mean, not the draw), so per-sample
    ratios would gate on scheduler noise instead of calibration
    quality."""
    seen: dict = {}
    sums: dict = {}
    for key, p, m in pairs:
        n = seen.get(key, 0)
        seen[key] = n + 1
        if (n >= 2 or key in warm_keys) and p > 0 and m > 0:
            ps, ms, cnt = sums.get(key, (0.0, 0.0, 0))
            sums[key] = (ps + p, ms + m, cnt + 1)
    ratios = [ps / ms for ps, ms, _ in sums.values() if ms > 0]
    if not ratios:
        return None
    return math.exp(sum(math.log(r) for r in ratios) / len(ratios))


def run_mixed(smoke: bool = False,
              itl_budget_ms: float = 0.0) -> dict:
    """The --mixed probe: token-budget baseline first, then ms-budget
    scheduling with an explicit ITL budget (given, or derived as half
    the baseline's ITL p50 so the budget provably bites), same flood
    both times."""
    n_tok, flood_n = (64, 6) if smoke else (96, 12)
    saved = {k: os.environ.get(k)
             for k in ("LOCALAI_COST_SCHED", "LOCALAI_ITL_BUDGET_MS")}
    try:
        os.environ["LOCALAI_COST_SCHED"] = "off"
        os.environ["LOCALAI_ITL_BUDGET_MS"] = "0"
        off = _flood_leg(n_tok, flood_n)
        budget = itl_budget_ms
        if budget <= 0.0:
            # apples-to-apples: pack to the device time the token
            # baseline actually spends per step, so the gate compares
            # predictor-driven packing against heuristic packing at
            # the SAME latency target. (A deliberately-choking budget
            # — e.g. half the p50 — trades p99 for chattier dispatch
            # by design; that behavior is unit-tested in
            # tests/test_cost_sched.py, not gated here.)
            budget = max(1.0, off["itl_p50_ms"] or 2.0)
        os.environ["LOCALAI_COST_SCHED"] = "on"
        os.environ["LOCALAI_ITL_BUDGET_MS"] = str(budget)
        on = _flood_leg(n_tok, flood_n)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    geomean = _geomean_ratio(on.pop("pairs"),
                             frozenset(on.pop("warm_keys")))
    off.pop("pairs")
    off.pop("warm_keys")
    return {
        "itl_budget_ms": round(budget, 2),
        "token_budget": off,
        "cost_sched": on,
        "predicted_vs_measured_geomean": (round(geomean, 3)
                                          if geomean else None),
        "predicted_within_2x": (geomean is not None
                                and 0.5 <= geomean <= 2.0),
        "itl_p99_no_worse": (
            off["itl_p99_ms"] is not None
            and on["itl_p99_ms"] is not None
            # CPU-noise allowance: the p99 of a short smoke leg is a
            # near-max order statistic, so single-run jitter swings it
            # tens of percent either way. On TPU the two legs are
            # tightly repeatable and the gate is effectively exact.
            and on["itl_p99_ms"] <= max(off["itl_p99_ms"] * 1.5,
                                        off["itl_p99_ms"] + 5.0)),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=4,
                    help="generate() calls after warmup")
    ap.add_argument("--max-tokens", type=int, default=16,
                    help="decode length per request")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings (2 requests)")
    ap.add_argument("--mixed", action="store_true",
                    help="adversarial long-prompt flood: cost-sched "
                         "on vs off + predicted-vs-measured geomean")
    ap.add_argument("--itl-budget-ms", type=float, default=0.0,
                    help="explicit ITL budget for the --mixed on-leg "
                         "(0 = half the off-leg's ITL p50)")
    args = ap.parse_args()
    if args.smoke:
        args.requests, args.max_tokens = 2, 8

    if args.mixed:
        print(json.dumps(run_mixed(args.smoke, args.itl_budget_ms),
                         indent=2))
        return
    print(json.dumps(run(args.requests, args.max_tokens), indent=2))


if __name__ == "__main__":
    main()
