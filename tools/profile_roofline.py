"""Roofline profile: warmup-captured cost model on a live engine.

Builds a tiny engine, runs the warmup pass (which AOT-captures every
dispatch variant's XLA flops / bytes-accessed — telemetry/costmodel.py),
drives a little real traffic, and prints one JSON report:

  per-kind rows      — accounted FLOPs, bytes accessed, dispatch count,
                       arithmetic intensity, compute- vs bandwidth-bound
                       against the platform ridge point
  mfu_ewma           — EWMA model-flops-utilization from flight spans
  verdicts           — decode_bandwidth_bound / prefill_compute_bound:
                       the physical shape the cost model must recover
                       (decode re-reads the weights per token; batched
                       prefill amortizes them over the bucket)

Run:  python tools/profile_roofline.py [--requests N] [--max-tokens N]

CPU smoke (what CI can afford):

  python tools/profile_roofline.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_engine(n_slots=4, max_seq=128):
    import jax
    import jax.numpy as jnp

    from localai_tfp_tpu.engine.engine import LLMEngine
    from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
    from localai_tfp_tpu.models.llm_spec import tiny_spec
    from localai_tfp_tpu.models.transformer import init_params

    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    eng = LLMEngine(spec, params, tk, n_slots=n_slots, max_seq=max_seq,
                    prefill_buckets=(8, 32, 128), cache_dtype=jnp.float32)
    return eng, tk


def run(requests: int, max_tokens: int) -> dict:
    from localai_tfp_tpu.engine.engine import GenRequest

    eng, tk = _build_engine()
    try:
        # warmup is where capture happens: every compiled variant's
        # cost_analysis() lands in the table keyed by dispatch signature
        eng.warmup()
        # real traffic so the totals and the MFU EWMA have samples; a
        # long prompt exercises the big prefill bucket, the decode tail
        # exercises the per-token path
        for i in range(requests):
            ev = eng.generate(GenRequest(
                prompt_ids=tk.encode(f"roofline probe {i} " * 4),
                max_tokens=max_tokens, ignore_eos=True))
            if ev.finish_reason not in ("length", "stop"):
                raise SystemExit(
                    f"probe request ended {ev.finish_reason!r} — the "
                    "report below would have no dispatch traffic")
        stats = eng.cost_stats()
    finally:
        eng.close()
    if stats is None:
        raise SystemExit("cost model disabled — set LOCALAI_COSTMODEL=on")

    kinds = stats["kinds"]
    decode = {k: v for k, v in kinds.items() if k.startswith("decode")}
    prefill = {k: v for k, v in kinds.items()
               if k.startswith("prefill") or k == "mixed"}
    stats["verdicts"] = {
        # decode must sit under the ridge (weights re-read per token)...
        "decode_bandwidth_bound": bool(decode) and all(
            v["bound"] == "bandwidth" for v in decode.values()),
        # ...and batched prefill above it (weights amortized per bucket)
        "prefill_compute_bound": bool(prefill) and any(
            v["bound"] == "compute" for v in prefill.values()),
    }
    return stats


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=4,
                    help="generate() calls after warmup")
    ap.add_argument("--max-tokens", type=int, default=16,
                    help="decode length per request")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CPU smoke settings (2 requests)")
    args = ap.parse_args()
    if args.smoke:
        args.requests, args.max_tokens = 2, 8

    print(json.dumps(run(args.requests, args.max_tokens), indent=2))


if __name__ == "__main__":
    main()
