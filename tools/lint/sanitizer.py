"""graftsan: a lockdep-style runtime sanitizer for the engine.

graftlint proves lexical contracts; graftsan proves the DYNAMIC ones
the linter cannot see. Armed (``LOCALAI_SAN=1`` or ``arm()``), it:

1. **Lock-order graph.** Wraps ``threading.Lock`` / ``RLock`` /
   ``Condition`` factories so every lock created from package code
   carries its creation site (``file:line``). Each acquire records
   "held-site -> acquired-site" edges in a global graph; the first
   edge that closes a cycle produces a report carrying BOTH stacks —
   where the held lock was acquired and where the inverting acquire
   happened — exactly the information a post-mortem of a real deadlock
   never has. Like kernel lockdep, a cycle is reported even if the
   interleaving that deadlocks never ran.

2. **Dynamic guarded-by.** The ``# lint: guarded-by <lock>`` pragmas
   (parsed from source by graftlint's loader — the sanitizer never
   trusts runtime state for the contract) become checked at every
   attribute REBIND: patched ``__setattr__`` on annotated classes
   verifies the named lock is held by the current thread. Object
   construction is exempt (any ``__init__`` of the object on the
   stack), matching the static rule. Container method mutations
   (``.append``/``.update``) stay the static rule's territory — the
   dynamic check covers the rebind/augassign class the linter cannot
   follow through helper calls.

Disarmed cost: patched ``__setattr__`` reads ONE attribute
(``_STATE.armed``) before delegating; lock factories are fully
restored, so locks created while disarmed are raw stdlib objects.

API: ``arm(include=None)`` / ``disarm()`` / ``reports()`` /
``reset()`` / ``stats()``. ``include`` is a predicate over the
creating frame's filename (default: package files only); tests pass
``lambda f: True`` to sanitize fixture locks.

Pure stdlib. Lives in tools/ (dev tooling), imported by
``localai_tfp_tpu.utils.san`` behind the ``LOCALAI_SAN`` knob.
"""

from __future__ import annotations

import ast
import importlib.util
import sys
import threading
import traceback
from pathlib import Path
from typing import Callable, Optional

_REPO_ROOT = Path(__file__).resolve().parents[2]
_PKG = "localai_tfp_tpu"
_STACK_LIMIT = 8


def _default_include(filename: str) -> bool:
    return _PKG in filename


class _State:
    def __init__(self) -> None:
        self.armed = False
        self.include: Callable[[str], bool] = _default_include
        # graph: creation-site -> set of sites acquired WHILE holding it
        self.edges: dict[str, set[str]] = {}
        self.edge_stacks: dict[tuple[str, str], tuple[str, str]] = {}
        self.sites: set[str] = set()
        self.reports: list[dict] = []
        self.guarded: dict = {}          # (modname, clsqual) -> {attr: lock}
        self.patched: list[tuple] = []   # (cls, orig __setattr__)
        self.orig_factories: Optional[tuple] = None
        self.finder = None
        self.cycles = 0
        self.guarded_checks = 0
        self.violations = 0
        self.lock = threading.Lock()     # leaf lock guarding all of the above
        self.tls = threading.local()


_STATE = _State()


def _held() -> list:
    """Current thread's held-lock stack: (site, lock id, acquire stack)."""
    st = getattr(_STATE.tls, "held", None)
    if st is None:
        st = _STATE.tls.held = []
    return st


def _capture_stack(skip: int):
    """Cheap stack capture for the common (no-report) path: source
    lines are NOT resolved here — only when a report formats it."""
    return traceback.StackSummary.extract(
        traceback.walk_stack(sys._getframe(skip)),
        limit=_STACK_LIMIT, lookup_lines=False)


def _fmt_stack(summary) -> str:
    if not summary:
        return ""
    return "".join(summary.format())


# --------------------------------------------------------- lock wrapper

def _has_path(src: str, dst: str) -> bool:
    """DFS: does a held-after path src ->* dst exist in the edge graph?"""
    seen = {src}
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        for nxt in _STATE.edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


class _SanLock:
    """Proxy around a stdlib lock that feeds the lock-order graph and
    the per-thread held stack. Exposes ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` so ``threading.Condition``
    built on it keeps the held stack consistent across ``wait()``."""

    def __init__(self, inner, site: str) -> None:
        self._inner = inner
        self._site = site
        self.last_acquire_stack = None  # StackSummary

    # -- acquire / release -------------------------------------------
    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._note_acquire()
        return got

    def release(self) -> None:
        self._inner.release()
        self._note_release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        return self._held_count() > 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<_SanLock site={self._site} inner={self._inner!r}>"

    # -- Condition protocol ------------------------------------------
    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        if save is not None:
            state = save()
        else:
            self._inner.release()
            state = None
        count = self._pop_all()
        return (state, count)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        for _ in range(max(1, count)):
            self._note_acquire()

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        return self._held_count() > 0

    # -- graph bookkeeping -------------------------------------------
    def _held_count(self) -> int:
        me = id(self)
        return sum(1 for _, lid, _ in _held() if lid == me)

    def _pop_all(self) -> int:
        me = id(self)
        held = _held()
        n = len(held)
        held[:] = [e for e in held if e[1] != me]
        return n - len(held)

    def _note_acquire(self) -> None:
        held = _held()
        if not _STATE.armed:
            held.append((self._site, id(self), None))
            return
        acq_stack = _capture_stack(3)
        self.last_acquire_stack = acq_stack
        me = id(self)
        with _STATE.lock:
            _STATE.sites.add(self._site)
            for hsite, hid, hstack in held:
                if hid == me:
                    continue  # re-entrant acquire: no self edge
                if hsite == self._site:
                    # two locks born at the same site (one constructor
                    # line -> every instance) nest under per-instance
                    # discipline the site graph cannot order; kernel
                    # lockdep needs explicit nesting annotations here
                    # too, so same-site edges are not recorded
                    continue
                dests = _STATE.edges.setdefault(hsite, set())
                if self._site in dests:
                    continue  # known-good (or already-reported) edge
                # adding hsite -> site closes a cycle iff a path
                # site ->* hsite already exists
                if _has_path(self._site, hsite):
                    _STATE.cycles += 1
                    # the opposing direction was recorded when some
                    # earlier thread acquired these sites in the other
                    # order: surface ITS two stacks alongside ours
                    prior = _STATE.edge_stacks.get(
                        (self._site, hsite), (None, None))
                    _STATE.reports.append({
                        "kind": "lock-order-cycle",
                        "edge": (hsite, self._site),
                        "held_site": hsite,
                        "acquired_site": self._site,
                        "held_stack": _fmt_stack(hstack),
                        "acquire_stack": _fmt_stack(acq_stack),
                        "prior_held_stack": _fmt_stack(prior[0]),
                        "prior_acquire_stack": _fmt_stack(prior[1]),
                        "thread": threading.current_thread().name,
                    })
                dests.add(self._site)
                _STATE.edge_stacks[(hsite, self._site)] = (
                    hstack, acq_stack)
        held.append((self._site, me, acq_stack))

    def _note_release(self) -> None:
        me = id(self)
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == me:
                del held[i]
                return


def _creation_site(depth: int) -> Optional[str]:
    """file:line of the frame creating a lock, if include() admits it."""
    frame = sys._getframe(depth)
    fname = frame.f_code.co_filename
    if not _STATE.armed or not _STATE.include(fname):
        return None
    return f"{fname}:{frame.f_lineno}"


# ------------------------------------------------------ guarded-by map

def _build_guarded_map() -> dict:
    """(module dotted name, class qualname) -> {attr: lock attr}, parsed
    from the package SOURCES via graftlint's loader (the contract is
    the pragma text, never runtime state). Only ``self.<attr>`` lock
    expressions are dynamically checkable."""
    from .core import load_context

    gmap: dict = {}
    ctx = load_context(_REPO_ROOT)
    for m in ctx.modules:
        if not m.pragmas.guarded:
            continue
        modname = m.rel[:-3].replace("/", ".")
        parents: dict = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for line, lock in m.pragmas.guarded:
            if not lock.startswith("self."):
                continue
            lock_attr = lock.split("self.", 1)[1].strip()
            if not lock_attr.isidentifier():
                continue
            hit = None
            for node in ast.walk(m.tree):
                if (isinstance(node, (ast.Assign, ast.AnnAssign))
                        and node.lineno <= line + 1
                        and (node.end_lineno or node.lineno) >= line):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        while isinstance(t, (ast.Subscript, ast.Slice)):
                            t = t.value
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            hit = (node, t.attr)
                            break
                if hit:
                    break
            if hit is None:
                continue
            cls_parts = []
            cur = parents.get(hit[0])
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    cls_parts.append(cur.name)
                cur = parents.get(cur)
            if not cls_parts:
                continue
            clsqual = ".".join(reversed(cls_parts))
            gmap.setdefault((modname, clsqual), {})[hit[1]] = lock_attr
    return gmap


def _lock_held_by_current_thread(lock) -> bool:
    if isinstance(lock, _SanLock):
        return lock._held_count() > 0
    # threading.Condition: recurse into its underlying lock when we can
    # see it precisely; its own _is_owned is a coarse anyone-holds probe
    inner = getattr(lock, "_lock", None)
    if inner is not None and hasattr(lock, "notify_all"):
        return _lock_held_by_current_thread(inner)
    owned = getattr(lock, "_is_owned", None)
    if owned is not None:
        try:
            return bool(owned())
        except Exception:
            return True
    locked = getattr(lock, "locked", None)
    if locked is not None:
        return bool(locked())
    return True  # unknown lock object: never flag


def _check_guarded(obj, attr: str, lock_attr: str) -> None:
    # construction is single-threaded: any __init__ OF THIS OBJECT on
    # the stack exempts the write (matches the static rule, plus the
    # helpers __init__ delegates to)
    frame = sys._getframe(2)
    depth = 0
    while frame is not None and depth < _STACK_LIMIT:
        if (frame.f_code.co_name == "__init__"
                and frame.f_locals.get("self") is obj):
            return
        frame = frame.f_back
        depth += 1
    try:
        lock = getattr(obj, lock_attr)
    except AttributeError:
        return
    if lock is None:
        return
    with _STATE.lock:
        _STATE.guarded_checks += 1
    if _lock_held_by_current_thread(lock):
        return
    holder = getattr(lock, "last_acquire_stack", None)
    with _STATE.lock:
        _STATE.violations += 1
        _STATE.reports.append({
            "kind": "guarded-by",
            "class": type(obj).__name__,
            "attr": attr,
            "lock": f"self.{lock_attr}",
            "thread": threading.current_thread().name,
            "mutation_stack": _fmt_stack(_capture_stack(3)),
            "holder_stack": _fmt_stack(holder),
        })


def _patch_class(cls, attrs: dict) -> None:
    existing = getattr(cls, "_graftsan_guarded", None)
    if existing is not None and "_graftsan_guarded" in cls.__dict__:
        existing.update(attrs)
        return
    guarded = dict(attrs)
    orig = cls.__setattr__

    def __setattr__(self, name, value, _orig=orig, _g=guarded):
        if _STATE.armed:  # disarmed cost: this one attribute read
            lock_attr = _g.get(name)
            if lock_attr is not None:
                _check_guarded(self, name, lock_attr)
        _orig(self, name, value)

    cls.__setattr__ = __setattr__
    cls._graftsan_guarded = guarded
    with _STATE.lock:
        _STATE.patched.append((cls, orig))


def _patch_module(module) -> None:
    modname = getattr(module, "__name__", "")
    for (mod, clsqual), attrs in _STATE.guarded.items():
        if mod != modname:
            continue
        obj = module
        for part in clsqual.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                break
        if isinstance(obj, type):
            _patch_class(obj, attrs)


# --------------------------------------------------------- import hook

class _LoaderProxy:
    def __init__(self, inner) -> None:
        self._inner = inner

    def create_module(self, spec):
        return self._inner.create_module(spec)

    def exec_module(self, module) -> None:
        self._inner.exec_module(module)
        if _STATE.armed:
            _patch_module(module)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _SanFinder:
    """meta_path hook: package modules imported AFTER arm() still get
    their guarded classes patched."""

    def __init__(self) -> None:
        self._busy: set[str] = set()

    def find_spec(self, name, path=None, target=None):
        if not _STATE.armed:
            return None
        if name != _PKG and not name.startswith(_PKG + "."):
            return None
        if name in self._busy:
            return None
        self._busy.add(name)
        try:
            spec = importlib.util.find_spec(name)
        finally:
            self._busy.discard(name)
        if spec is None or spec.loader is None:
            return None
        spec.loader = _LoaderProxy(spec.loader)
        return spec


# ----------------------------------------------------------- factories

def _lock_factory():
    site = _creation_site(2)
    inner = _STATE.orig_factories[0]()
    if site is None:
        return inner
    return _SanLock(inner, site)


def _rlock_factory():
    site = _creation_site(2)
    inner = _STATE.orig_factories[1]()
    if site is None:
        return inner
    return _SanLock(inner, site)


def _condition_factory(lock=None):
    orig_condition = _STATE.orig_factories[2]
    if lock is None:
        site = _creation_site(2)
        if site is not None:
            lock = _SanLock(_STATE.orig_factories[1](), site)
    return orig_condition(lock)


# --------------------------------------------------------- control API

def arm(include: Optional[Callable[[str], bool]] = None) -> None:
    """Patch lock factories, patch guarded classes, install the import
    hook, start recording. Idempotent (re-arm updates ``include``)."""
    _STATE.include = include or _default_include
    if _STATE.armed:
        return
    if not _STATE.guarded:
        _STATE.guarded = _build_guarded_map()
    if _STATE.orig_factories is None:
        _STATE.orig_factories = (threading.Lock, threading.RLock,
                                 threading.Condition)
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _STATE.armed = True
    for module in list(sys.modules.values()):
        name = getattr(module, "__name__", "") or ""
        if name == _PKG or name.startswith(_PKG + "."):
            _patch_module(module)
    if _STATE.finder is None:
        _STATE.finder = _SanFinder()
    if _STATE.finder not in sys.meta_path:
        sys.meta_path.insert(0, _STATE.finder)


def disarm() -> None:
    """Restore factories and stop recording. Patched ``__setattr__``
    stays installed (its disarmed cost is one attribute read) because
    instances created while armed may outlive the arming window.
    Reports survive until ``reset()``."""
    if not _STATE.armed:
        return
    _STATE.armed = False
    if _STATE.orig_factories is not None:
        (threading.Lock, threading.RLock,
         threading.Condition) = _STATE.orig_factories
    if _STATE.finder is not None and _STATE.finder in sys.meta_path:
        sys.meta_path.remove(_STATE.finder)


def reports() -> list[dict]:
    with _STATE.lock:
        return list(_STATE.reports)


def reset() -> None:
    """Clear the graph, the reports and the counters (keeps the guarded
    map and any class patches — they are contract, not state)."""
    with _STATE.lock:
        _STATE.edges.clear()
        _STATE.edge_stacks.clear()
        _STATE.sites.clear()
        _STATE.reports.clear()
        _STATE.cycles = 0
        _STATE.guarded_checks = 0
        _STATE.violations = 0


def stats() -> dict:
    with _STATE.lock:
        return {
            "armed": _STATE.armed,
            "sites": len(_STATE.sites),
            "edges": sum(len(v) for v in _STATE.edges.values()),
            "cycles": _STATE.cycles,
            "guarded_checks": _STATE.guarded_checks,
            "violations": _STATE.violations,
            "guarded_classes": len(_STATE.guarded),
        }
