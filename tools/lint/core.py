"""graftlint core: pragmas, module model, finding/baseline machinery.

The engine's correctness contracts (scalar-only dispatch payloads,
no device syncs in the scheduler loop, lock-guarded registries,
donation-after-use) live in source comments and code review memory.
``tools.lint`` turns them into AST checks the tier-1 suite enforces —
the project-local analogue of the reference's golangci-lint +
``go test -race`` gates.

Pure stdlib (``ast`` + ``re`` + ``json``): the linter must run in any
environment the tests run in, including ones without jax.

Pragma syntax (all live in ``#`` comments so they are invisible at
runtime):

- ``# lint: region <name>`` / ``# lint: endregion <name>``
  Mark a contiguous source region. Region-scoped rules (hot-path-sync)
  only fire inside their region.
- ``# lint: ignore[rule-id] <reason>``
  Suppress ``rule-id`` findings on this line and the next. Multiple ids:
  ``ignore[a,b]``. A missing reason is itself a finding (``lint-pragma``).
- ``# lint: guarded-by <lock-expr>``
  On an attribute assignment inside a class: every later MUTATION of
  that ``self.<attr>`` must sit inside ``with <lock-expr>:`` (or in a
  function carrying a ``holds`` pragma, or in ``__init__``).
- ``# lint: holds <lock-expr>``
  On or inside a ``def``: the function body runs with ``<lock-expr>``
  held by its caller (lock-discipline treats it as guarded).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# rule ids a pragma may reference; rules register themselves on import
KNOWN_RULES: set[str] = {"lint-pragma"}

_PRAGMA = re.compile(r"#\s*lint:\s*(.*)$")
_IGNORE = re.compile(r"ignore\[([A-Za-z0-9_,\- ]+)\]\s*(.*)$")
_REGION = re.compile(r"(endregion|region)\s+([A-Za-z0-9_\-]+)\s*$")
_GUARDED = re.compile(r"guarded-by\s+(.+)$")
_HOLDS = re.compile(r"holds\s+(.+)$")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix
    line: int
    message: str
    scope: str = ""  # dotted Class.func enclosing the finding
    # stable identity for the baseline: everything except the line
    # number, which drifts with unrelated edits

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.message}"

    def render(self) -> str:
        where = f" ({self.scope})" if self.scope else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{where}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "scope": self.scope,
                "fingerprint": self.fingerprint}


@dataclass
class Pragmas:
    """Per-file pragma index (1-based line numbers)."""

    regions: dict[str, list[tuple[int, int]]] = field(default_factory=dict)
    # line -> [(rule-or-*, reason)]; an entry suppresses its own line
    # and the following one (pragma-above-the-statement style)
    ignores: dict[int, list[tuple[str, str]]] = field(default_factory=dict)
    guarded: list[tuple[int, str]] = field(default_factory=list)
    holds: list[tuple[int, str]] = field(default_factory=list)
    errors: list[tuple[int, str]] = field(default_factory=list)

    def in_region(self, name: str, line: int) -> bool:
        return any(a <= line <= b for a, b in self.regions.get(name, ()))

    def suppressed(self, rule: str, line: int) -> bool:
        for at in (line, line - 1):
            for rid, reason in self.ignores.get(at, ()):
                if reason and rid in ("*", rule):
                    return True
        return False


def parse_pragmas(source: str) -> Pragmas:
    pr = Pragmas()
    open_regions: dict[str, int] = {}
    for i, raw in enumerate(source.splitlines(), start=1):
        m = _PRAGMA.search(raw)
        if not m:
            continue
        body = m.group(1).strip()
        if (mm := _REGION.match(body)) is not None:
            kw, name = mm.group(1), mm.group(2)
            if kw == "region":
                if name in open_regions:
                    pr.errors.append((i, f"region {name!r} reopened "
                                         "while already open"))
                else:
                    open_regions[name] = i
            else:
                start = open_regions.pop(name, None)
                if start is None:
                    pr.errors.append((i, f"endregion {name!r} without "
                                         "a matching region"))
                else:
                    pr.regions.setdefault(name, []).append((start, i))
        elif (mm := _IGNORE.match(body)) is not None:
            rules = [r.strip() for r in mm.group(1).split(",") if r.strip()]
            reason = mm.group(2).strip()
            if not reason:
                pr.errors.append((i, "ignore pragma without a reason "
                                     "(write: # lint: ignore[rule] why)"))
            for rid in rules:
                if rid != "*" and rid not in KNOWN_RULES:
                    pr.errors.append((i, f"ignore names unknown rule "
                                         f"{rid!r}"))
                pr.ignores.setdefault(i, []).append((rid, reason))
        elif (mm := _GUARDED.match(body)) is not None:
            # a further `#` starts an ordinary trailing comment
            pr.guarded.append((i, mm.group(1).split("#")[0].strip()))
        elif (mm := _HOLDS.match(body)) is not None:
            pr.holds.append((i, mm.group(1).split("#")[0].strip()))
        else:
            pr.errors.append((i, f"unrecognized lint pragma: {body!r}"))
    for name, start in open_regions.items():
        pr.errors.append((start, f"region {name!r} never closed"))
    return pr


class Module:
    """One parsed source file plus its pragma index and scope map."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source)
        self.pragmas = parse_pragmas(source)
        self._scopes: list[tuple[int, int, str]] = []
        # module-level call graph support: dotted qualname -> def node,
        # plus the set of class qualnames (to resolve `self.X(...)`)
        self.functions: dict[str, ast.AST] = {}
        self.classes: set[str] = set()
        self._index_scopes(self.tree, "")

    def _index_scopes(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                self._scopes.append(
                    (child.lineno, child.end_lineno or child.lineno, name))
                if isinstance(child, ast.ClassDef):
                    self.classes.add(name)
                else:
                    self.functions.setdefault(name, child)
                self._index_scopes(child, name)
            else:
                self._index_scopes(child, prefix)

    def resolve_call(self, caller_scope: str,
                     call: ast.Call) -> Optional[tuple[str, ast.AST]]:
        """Resolve a call to a function defined in THIS module:
        ``self.X(...)`` -> a method of the caller's enclosing class,
        ``name(...)`` -> a sibling nested def, an enclosing scope's
        def, or a module-level function. Anything else (other objects'
        methods, imports, jitted closures reached through instance
        attributes) is outside the module call graph."""
        f = call.func
        parts = caller_scope.split(".") if caller_scope else []
        if isinstance(f, ast.Name):
            for i in range(len(parts), -1, -1):
                qual = ".".join(parts[:i] + [f.id])
                fn = self.functions.get(qual)
                if fn is not None:
                    return qual, fn
            return None
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            # the innermost enclosing class: `self` in a closure nested
            # under a method still refers to that class's instance
            for i in range(len(parts), 0, -1):
                cls = ".".join(parts[:i])
                if cls in self.classes:
                    qual = f"{cls}.{f.attr}"
                    fn = self.functions.get(qual)
                    if fn is not None:
                        return qual, fn
                    return None
            return None
        return None

    def scope_at(self, line: int) -> str:
        best = ""
        best_span = None
        for a, b, name in self._scopes:
            if a <= line <= b and (best_span is None or b - a < best_span):
                best, best_span = name, b - a
        return best

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=rule, path=self.rel, line=line,
                       message=message, scope=self.scope_at(line))


@dataclass
class Context:
    """Repo-level lint context shared by all rules."""

    root: Path
    modules: list[Module]
    readme_text: str = ""

    def module(self, rel: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel == rel:
                return m
        return None


def load_context(root: Path = REPO_ROOT,
                 paths: Optional[Iterable[Path]] = None) -> Context:
    """Parse the lintable file set. Default: the ``localai_tfp_tpu``
    package (tools/ and tests/ are dev-side and out of contract
    scope)."""
    root = Path(root)
    if paths is None:
        paths = sorted((root / "localai_tfp_tpu").rglob("*.py"))
    modules = []
    for p in paths:
        p = Path(p)
        rel = p.relative_to(root).as_posix()
        modules.append(Module(rel, p.read_text(encoding="utf-8")))
    readme = root / "README.md"
    text = readme.read_text(encoding="utf-8") if readme.exists() else ""
    return Context(root=root, modules=modules, readme_text=text)


def callgraph_edges(ctx: Context) -> int:
    """Resolved module-local call edges across the context — the size
    of the graph the interprocedural rules walk (bench/--json metric)."""
    from .rules.scalar_payload import walk_shallow

    n = 0
    for m in ctx.modules:
        for qual, fn in m.functions.items():
            seen: set[str] = set()
            for node in walk_shallow(fn):
                if isinstance(node, ast.Call):
                    hit = m.resolve_call(qual, node)
                    if hit is not None:
                        seen.add(hit[0])
            n += len(seen)
    return n


def run_rules(ctx: Context, rules) -> list[Finding]:
    """All findings (suppressions applied, pragma errors included)."""
    findings: list[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    for m in ctx.modules:
        for line, msg in m.pragmas.errors:
            findings.append(m.finding("lint-pragma", line, msg))
    out = []
    for f in findings:
        m = ctx.module(f.path)
        if (f.rule != "lint-pragma" and m is not None
                and m.pragmas.suppressed(f.rule, f.line)):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ------------------------------------------------------------------ baseline


def load_baseline(path: Path = DEFAULT_BASELINE) -> dict[str, int]:
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    return {str(k): int(v) for k, v in data.get("entries", {}).items()}


def save_baseline(entries: dict[str, int],
                  path: Path = DEFAULT_BASELINE) -> None:
    payload = {
        "comment": ("grandfathered graftlint findings. This file may "
                    "only SHRINK: fixing a finding requires deleting "
                    "its entry (a stale entry fails the lint gate), and "
                    "new findings must be fixed, not added here."),
        "entries": dict(sorted(entries.items())),
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                          encoding="utf-8")


@dataclass
class BaselineResult:
    new: list[Finding]  # findings with no baseline budget -> errors
    grandfathered: list[Finding]  # matched a baseline entry
    stale: list[str]  # baseline entries no finding matched -> errors

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, int]) -> BaselineResult:
    """Findings beyond an entry's count are new; an entry with no
    matching finding is stale (the baseline must only shrink, so a
    fixed finding must also be deleted from the file)."""
    budget = dict(baseline)
    new, old = [], []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp, n in budget.items() if n > 0)
    return BaselineResult(new=new, grandfathered=old, stale=stale)
