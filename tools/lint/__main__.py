"""CLI for graftlint: ``python -m tools.lint``.

Exit status 0 means zero non-baselined findings AND zero stale baseline
entries (the baseline may only shrink). ``--update-baseline`` rewrites
the committed baseline from the current findings — for removing fixed
entries, never for burying new ones (bench tracks the baseline size per
release, so growth is visible).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from collections import Counter
from pathlib import Path

from . import (ALL_RULES, DEFAULT_BASELINE, REPO_ROOT, apply_baseline,
               load_baseline, load_context, rules_by_id, run_rules,
               save_baseline)
from .core import callgraph_edges


def _changed_files(root: Path) -> set[str]:
    """Repo-relative paths with uncommitted changes (vs HEAD) plus
    untracked files — the `--changed` filter set."""
    paths: set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30, check=True).stdout
        except (OSError, subprocess.SubprocessError):
            continue
        paths.update(p.strip() for p in out.splitlines() if p.strip())
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: engine contract static analysis")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--root", type=Path, default=REPO_ROOT)
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--rules", type=str, default="",
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in files with "
                         "uncommitted changes (analysis still loads "
                         "the whole package for cross-module context)")
    args = ap.parse_args(argv)

    rules = rules_by_id([r for r in args.rules.split(",") if r]) \
        if args.rules else list(ALL_RULES)
    ctx = load_context(args.root)
    findings = run_rules(ctx, rules)
    if args.changed:
        changed = _changed_files(args.root)
        findings = [f for f in findings if f.path in changed]

    if args.update_baseline:
        entries = Counter(f.fingerprint for f in findings)
        save_baseline(dict(entries), args.baseline)
        print(f"graftlint: baseline rewritten with {len(findings)} "
              f"finding(s) in {len(entries)} entr(ies) -> "
              f"{args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    res = apply_baseline(findings, baseline)

    if args.json:
        print(json.dumps({
            "rules": sorted(r.id for r in rules),
            "files_scanned": len(ctx.modules),
            "callgraph_edges": callgraph_edges(ctx),
            "findings": [f.to_json() for f in res.new],
            "grandfathered": len(res.grandfathered),
            "stale_baseline": res.stale,
            "baseline_size": sum(baseline.values()),
            "ok": res.ok,
        }, indent=2))
    else:
        for f in res.new:
            print(f.render(), file=sys.stderr)
        for fp in res.stale:
            print(f"stale baseline entry (fixed? delete it — the "
                  f"baseline only shrinks): {fp}", file=sys.stderr)
        n_files = len(ctx.modules)
        if res.ok:
            print(f"graftlint: OK ({n_files} files, "
                  f"{len(rules)} rules, "
                  f"{len(res.grandfathered)} grandfathered)")
        else:
            print(f"graftlint: {len(res.new)} finding(s), "
                  f"{len(res.stale)} stale baseline entr(ies) in "
                  f"{n_files} files", file=sys.stderr)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
