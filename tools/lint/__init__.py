"""graftlint — project-specific static analysis for the engine's
device/concurrency contracts.

Usage:
    python -m tools.lint              # human output, baseline applied
    python -m tools.lint --json      # machine output
    python -m tools.lint --update-baseline   # re-grandfather (shrink!)

Rules (see tools/lint/rules/ and the README "Static analysis" table):
    hot-path-sync     device syncs inside `# lint: region hot_path`
    scalar-payload    dispatch payload fields vs the multihost codec
    guarded-by        `# lint: guarded-by <lock>` mutation discipline
    donate-after-use  donated jit buffers referenced after the call
    except-swallow    silent broad-exception swallows
    metrics-contract  metric naming / README / required families
    lint-pragma       malformed lint pragmas (always on)

Programmatic entry points: ``lint_repo`` for the tier-1 gate and
bench, ``lint_sources`` for in-memory fixture runs.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from .core import (DEFAULT_BASELINE, REPO_ROOT, BaselineResult, Context,
                   Finding, Module, apply_baseline, load_baseline,
                   load_context, run_rules, save_baseline)
from .rules import ALL_RULES, rules_by_id

__all__ = [
    "ALL_RULES", "BaselineResult", "Context", "Finding", "Module",
    "DEFAULT_BASELINE", "REPO_ROOT", "apply_baseline", "lint_repo",
    "lint_sources", "load_baseline", "load_context", "rules_by_id",
    "run_rules", "save_baseline",
]


def lint_sources(sources: dict[str, str], *, readme_text: str = "",
                 rules=None) -> list[Finding]:
    """Lint in-memory ``{relpath: source}`` modules (fixture tests)."""
    ctx = Context(root=REPO_ROOT,
                  modules=[Module(rel, src)
                           for rel, src in sorted(sources.items())],
                  readme_text=readme_text)
    return run_rules(ctx, rules if rules is not None else ALL_RULES)


def lint_repo(root: Path = REPO_ROOT, *, rules=None,
              baseline_path: Optional[Path] = DEFAULT_BASELINE,
              ) -> tuple[list[Finding], BaselineResult]:
    """Full-package run: (all findings, baseline split)."""
    ctx = load_context(root)
    findings = run_rules(ctx, rules if rules is not None else ALL_RULES)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    return findings, apply_baseline(findings, baseline)
