"""scalar-payload: dispatch records carry only codec-whitelisted fields.

Every device dispatch is published as a ``(kind, payload)`` record that
multihost followers replay byte-for-byte (parallel/multihost.py). A
payload field the codec whitelist does not know about is how a new
dispatch kind silently breaks follower replay: the leader pickles it,
followers feed it to ``_dev_exec``, and the SPMD programs diverge.

This rule finds every dispatch site — ``self._run(kind, payload)`` and
the warmup's ``_warm(kind, payload)`` wrapper — and checks that

- the kind is a string literal (a computed kind cannot be audited), and
- every payload key is listed for that kind in
  ``parallel/multihost.py::PAYLOAD_FIELDS`` (the codec whitelist; adding
  a field there is the reviewed act that acknowledges the replay
  contract).

Payloads are resolved statically: a dict literal argument, or a local
name assigned a dict literal (following ``**spread`` of other local
dict literals and later ``payload["key"] = ...`` stores). Anything the
resolver cannot see is itself a finding — dispatch payloads must stay
simple enough to audit.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Context, Finding, Module

WHITELIST_MODULE = "localai_tfp_tpu/parallel/multihost.py"
WHITELIST_NAME = "PAYLOAD_FIELDS"

_DISPATCH_FUNCS = {"_run", "_warm"}


def walk_shallow(fn: ast.AST):
    """Walk a function body without descending into nested defs (each
    function is analyzed exactly once, with its own locals)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _load_whitelist(ctx: Context) -> Optional[dict[str, tuple[str, ...]]]:
    """PAYLOAD_FIELDS parsed from the codec module's AST (the linter
    never imports engine code). Fixture contexts may define the constant
    in any module."""
    mods = [m for m in ctx.modules if m.rel == WHITELIST_MODULE]
    mods += [m for m in ctx.modules if m.rel != WHITELIST_MODULE]
    for m in mods:
        for node in ast.walk(m.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == WHITELIST_NAME
                            for t in node.targets)):
                try:
                    raw = ast.literal_eval(node.value)
                except ValueError:
                    return None
                return {str(k): tuple(v) for k, v in raw.items()}
    return None


class ScalarPayload:
    id = "scalar-payload"
    doc = ("dispatch payload field not in the multihost codec whitelist "
           "(PAYLOAD_FIELDS)")

    def check(self, ctx: Context) -> Iterator[Finding]:
        wl = _load_whitelist(ctx)
        for m in ctx.modules:
            funcs = [n for n in ast.walk(m.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for fn in funcs:
                # only direct statements of this function (nested defs
                # are visited on their own)
                for node in walk_shallow(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if not self._is_dispatch(node):
                        continue
                    if len(node.args) < 2:
                        continue
                    if self._is_forwarding_wrapper(fn, node):
                        continue
                    yield from self._check_site(m, fn, node, wl)

    @staticmethod
    def _is_forwarding_wrapper(fn, call: ast.Call) -> bool:
        """``def _warm(kind, payload): ... self._run(kind, payload)`` is
        a dispatch WRAPPER, not a site — both args are the enclosing
        function's own parameters, so each caller is checked instead."""
        params = {a.arg for a in fn.args.args + fn.args.posonlyargs
                  + fn.args.kwonlyargs}
        return all(isinstance(a, ast.Name) and a.id in params
                   for a in call.args[:2])

    @staticmethod
    def _is_dispatch(call: ast.Call) -> bool:
        f = call.func
        if (isinstance(f, ast.Attribute) and f.attr in _DISPATCH_FUNCS
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            return True
        return isinstance(f, ast.Name) and f.id in _DISPATCH_FUNCS

    def _check_site(self, m: Module, fn, call: ast.Call,
                    wl) -> Iterator[Finding]:
        kinds = self._literal_kinds(call.args[0])
        if kinds is None:
            yield m.finding(
                self.id, call,
                "dispatch kind is not a string literal — the replay "
                "contract cannot be audited statically")
            return
        keys = self._resolve_keys(fn, call.args[1], call.lineno)
        if keys is None:
            yield m.finding(
                self.id, call,
                "dispatch payload does not resolve to a dict literal — "
                "build it as one (plus payload[...] stores) so the "
                "codec whitelist can be checked")
            return
        if wl is None:
            yield m.finding(
                self.id, call,
                f"codec whitelist {WHITELIST_NAME} not found in "
                f"{WHITELIST_MODULE}")
            return
        for kind in kinds:
            if kind in ("load", "unload", "stop"):
                continue  # lifecycle records, not engine dispatches
            if kind not in wl:
                yield m.finding(
                    self.id, call,
                    f"dispatch kind '{kind}' is not in the multihost "
                    f"codec whitelist ({WHITELIST_MODULE} "
                    f"{WHITELIST_NAME}) — followers cannot replay it")
                continue
            for key in sorted(set(keys) - set(wl[kind])):
                yield m.finding(
                    self.id, call,
                    f"payload field '{key}' for kind '{kind}' is not "
                    f"in the multihost codec whitelist — add it to "
                    f"{WHITELIST_NAME} (and the follower codec) or "
                    f"drop it")

    @staticmethod
    def _literal_kinds(node: ast.AST) -> Optional[list[str]]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, ast.IfExp):
            a = ScalarPayload._literal_kinds(node.body)
            b = ScalarPayload._literal_kinds(node.orelse)
            if a is not None and b is not None:
                return a + b
        return None

    def _resolve_keys(self, fn, payload: ast.AST,
                      call_line: int) -> Optional[list[str]]:
        if isinstance(payload, ast.Dict):
            return self._dict_keys(fn, payload, call_line)
        if not isinstance(payload, ast.Name):
            return None
        # latest `name = {...}` before the call, plus `name[k] = v`
        # stores between that assignment and the call
        assign = None
        for node in walk_shallow(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == payload.id
                    and node.lineno < call_line
                    and (assign is None or node.lineno > assign.lineno)):
                assign = node
        if assign is None or not isinstance(assign.value, ast.Dict):
            return None
        keys = self._dict_keys(fn, assign.value, assign.lineno)
        if keys is None:
            return None
        for node in walk_shallow(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == payload.id
                    and assign.lineno < node.lineno < call_line):
                sl = node.targets[0].slice
                if (isinstance(sl, ast.Constant)
                        and isinstance(sl.value, str)):
                    keys.append(sl.value)
                else:
                    return None  # computed key: unauditable
        return keys

    def _dict_keys(self, fn, d: ast.Dict,
                   at_line: int) -> Optional[list[str]]:
        keys: list[str] = []
        for k, v in zip(d.keys, d.values):
            if k is None:  # **spread: follow locally-assigned literals
                if not isinstance(v, ast.Name):
                    return None
                inner = self._resolve_keys(fn, v, at_line)
                if inner is None:
                    return None
                keys.extend(inner)
            elif isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.append(k.value)
            else:
                return None
        return keys
