"""except-swallow: no silent broad-exception swallows.

A bare ``except:`` / ``except Exception:`` whose handler neither
re-raises, logs, records telemetry, nor uses the caught exception value
is a *silent swallow* — the failure class PR 3 had to dig out of the
prompt-cache restore path by hand. Recovery is fine; invisible recovery
is not: add a narrow exception type, or log/count what was swallowed
(the ``engine_prompt_cache_restores_total{result}`` pattern), or
suppress with a reasoned ``# lint: ignore[except-swallow] ...``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Context, Finding

BROAD = {"Exception", "BaseException"}

# a call to any of these attribute names counts as "the failure was
# made visible": loggers, telemetry counters/gauges/histograms, tracers
_EVIDENCE_ATTRS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "inc", "observe", "set", "labels", "event", "finish", "add_note",
}


def _broad_names(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in elts:
        name = n.id if isinstance(n, ast.Name) else (
            n.attr if isinstance(n, ast.Attribute) else "")
        if name in BROAD:
            return True
    return False


def _handled(h: ast.ExceptHandler) -> bool:
    for node in ast.walk(ast.Module(body=list(h.body), type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _EVIDENCE_ATTRS:
                return True
            if isinstance(f, ast.Name) and f.id == "print":
                return True
        # the exception VALUE flowing anywhere (an error field, a
        # result message) means the failure is surfaced, not swallowed
        if (h.name and isinstance(node, ast.Name) and node.id == h.name
                and isinstance(node.ctx, ast.Load)):
            return True
    return False


class ExceptionHygiene:
    id = "except-swallow"
    doc = ("broad except handler swallows the failure silently — narrow "
           "the exception, or log/count it")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if _broad_names(node) and not _handled(node):
                    caught = ("bare except" if node.type is None else
                              f"except {ast.unparse(node.type)}")
                    yield m.finding(
                        self.id, node,
                        f"{caught} swallows the failure silently "
                        "(no raise/log/telemetry, exception value "
                        "unused)")
