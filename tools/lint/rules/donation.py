"""donate-after-use: donated buffers must not be read after the call.

``donate_argnums`` hands a buffer's HBM to XLA: after the jitted call
the donated array is deleted, and touching it raises (on TPU) or — far
worse — silently reads stale memory through a leftover numpy view. The
engine's convention is that every donating call REBINDS the donated
state in the same statement (``self.cache = fn(..., self.cache, ...)``);
this rule checks the convention statically.

Same-module analysis: jitted functions declared with
``@partial(jax.jit, donate_argnums=...)`` (or ``jax.jit(f,
donate_argnums=...)``) are mapped to the factory method that defines
them and to any ``self.<attr>`` they are bound to; call sites through
those names have their positional args resolved (including ``*args``
where ``args`` is a locally-built list literal, optionally grown with
``args += [...]``). For each donated position holding a plain name or
``self.<attr>``, a LOAD of the same expression after the call — before
any rebinding — is a finding. Cross-module calls of jitted functions
are out of scope (the engine keeps all donating dispatches in
``engine/engine.py``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Context, Finding, Module
from .scalar_payload import walk_shallow


def _donate_spec(call: ast.Call) -> Optional[set[int]]:
    """Donated argnums from a ``jax.jit``/``partial(jax.jit, ...)``
    call node, if it declares any."""
    fname = ast.unparse(call.func)
    if fname not in ("jax.jit", "partial", "functools.partial", "jit"):
        return None
    if fname in ("partial", "functools.partial"):
        if not call.args or ast.unparse(call.args[0]) not in (
                "jax.jit", "jit"):
            return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                return None
            return {int(x) for x in (v if isinstance(v, (tuple, list))
                                     else (v,))}
    return None


class DonationAfterUse:
    id = "donate-after-use"
    doc = ("argument donated via donate_argnums referenced after the "
           "jitted call")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            yield from self._check_module(m)

    def _check_module(self, m: Module) -> Iterator[Finding]:
        # ---- pass 1: donating defs, factories, and bound attributes
        donating: dict[str, set[int]] = {}  # def name -> argnums
        factories: dict[str, set[int]] = {}  # enclosing fn -> union
        attrs: dict[str, set[int]] = {}  # self.<attr> -> argnums
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(m.tree):
            spec: Optional[set[int]] = None
            name: Optional[str] = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        spec = _donate_spec(dec)
                        if spec is not None:
                            name = node.name
                            break
            elif (isinstance(node, ast.Assign)
                  and isinstance(node.value, ast.Call)):
                spec = _donate_spec(node.value)
                if spec is not None and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name):
                        name = t.id
                    elif (isinstance(t, ast.Attribute)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == "self"):
                        attrs.setdefault(t.attr, set()).update(spec)
            if spec is None or name is None:
                continue
            donating[name] = donating.get(name, set()) | spec
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(cur)
            if cur is not None:
                factories.setdefault(cur.name, set()).update(spec)
        # `self._decode_fn = _decode` binds a donating def to an attr
        for node in ast.walk(m.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Name)
                    and node.value.id in donating):
                t = node.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attrs.setdefault(t.attr, set()).update(
                        donating[node.value.id])
        if not (donating or factories or attrs):
            return

        # ---- pass 2: per-function call-site analysis
        for fn in ast.walk(m.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(m, fn, donating, factories,
                                          attrs)

    def _callee_spec(self, fn, call: ast.Call, donating, factories,
                     attrs, aliases) -> Optional[set[int]]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in aliases:
                return aliases[f.id]
            if f.id in donating:
                return donating[f.id]
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and f.attr in attrs:
                return attrs[f.attr]
        if isinstance(f, ast.Call):  # self._factory(...)(args)
            ff = f.func
            if (isinstance(ff, ast.Attribute)
                    and isinstance(ff.value, ast.Name)
                    and ff.value.id == "self"
                    and ff.attr in factories):
                return factories[ff.attr]
            if isinstance(ff, ast.Name) and ff.id in factories:
                return factories[ff.id]
        return None

    def _check_fn(self, m: Module, fn, donating, factories,
                  attrs) -> Iterator[Finding]:
        # ONE chronological pass: alias (`fn = self._factory(...)`) and
        # arg-list (`args = [...]` / `args += [...]`) state is replayed
        # in source order, so per-branch rebindings resolve to the state
        # live at each call site, not to the function's last assignment
        aliases: dict[str, set[int]] = {}
        lists: dict[str, list[ast.AST]] = {}
        nodes = sorted(walk_shallow(fn),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        calls: list[tuple[ast.Call, set[int],
                          list[Optional[ast.AST]]]] = []
        for st in nodes:
            if isinstance(st, ast.Call):
                spec = self._callee_spec(fn, st, donating, factories,
                                         attrs, aliases)
                if spec:
                    calls.append((st, spec, self._positional(st, lists)))
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                tname = st.targets[0].id
                v = st.value
                aliases.pop(tname, None)
                lists.pop(tname, None)
                if isinstance(v, ast.Call):
                    # `x = self._factory(...)`: the factory CALL yields
                    # the jitted fn (a donating call's result is data)
                    ff = v.func
                    if (isinstance(ff, ast.Attribute)
                            and isinstance(ff.value, ast.Name)
                            and ff.value.id == "self"
                            and ff.attr in factories):
                        aliases[tname] = factories[ff.attr]
                    elif (isinstance(ff, ast.Name)
                          and ff.id in factories):
                        aliases[tname] = factories[ff.id]
                elif isinstance(v, ast.Name) and v.id in donating:
                    aliases[tname] = donating[v.id]
                elif isinstance(v, ast.List):
                    lists[tname] = list(v.elts)
                elif (isinstance(v, ast.BinOp)
                      and isinstance(v.op, ast.Add)
                      and isinstance(v.left, ast.Name)
                      and v.left.id in lists
                      and isinstance(v.right, ast.List)):
                    lists[tname] = lists[v.left.id] + list(v.right.elts)
            elif (isinstance(st, ast.AugAssign)
                  and isinstance(st.op, ast.Add)
                  and isinstance(st.target, ast.Name)
                  and st.target.id in lists
                  and isinstance(st.value, ast.List)):
                lists[st.target.id] = (lists[st.target.id]
                                       + list(st.value.elts))
        for node, spec, args in calls:
            stmt = self._enclosing_stmt(fn, node)
            for i in sorted(spec):
                if i >= len(args) or args[i] is None:
                    continue
                expr = args[i]
                if not self._trackable(expr):
                    continue
                key = ast.unparse(expr)
                if stmt is not None and self._stmt_rebinds(stmt, key):
                    continue
                bad = self._used_after(fn, stmt, node, key)
                if bad is not None:
                    yield m.finding(
                        self.id, bad,
                        f"'{key}' was donated to the jitted call at "
                        f"line {node.lineno} (donate_argnums={i}) and "
                        "is referenced afterwards — its buffer belongs "
                        "to XLA now; rebind the result instead")

    @staticmethod
    def _positional(call: ast.Call, lists) -> list[Optional[ast.AST]]:
        out: list[Optional[ast.AST]] = []
        for a in call.args:
            if isinstance(a, ast.Starred):
                if (isinstance(a.value, ast.Name)
                        and a.value.id in lists):
                    out.extend(lists[a.value.id])
                else:
                    out.append(None)  # unknown tail: stop resolving
                    break
            else:
                out.append(a)
        return out

    @staticmethod
    def _trackable(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return True
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name))

    @staticmethod
    def _enclosing_stmt(fn, node: ast.AST) -> Optional[ast.stmt]:
        best = None
        for st in walk_shallow(fn):
            if isinstance(st, ast.stmt) and st.lineno <= node.lineno \
                    and (st.end_lineno or st.lineno) >= (
                        node.end_lineno or node.lineno):
                if best is None or st.lineno >= best.lineno:
                    best = st
        return best

    @staticmethod
    def _stmt_rebinds(stmt: ast.stmt, key: str) -> bool:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    if ast.unparse(el) == key:
                        return True
        return False

    @staticmethod
    def _used_after(fn, stmt: Optional[ast.stmt], call: ast.Call,
                    key: str) -> Optional[ast.AST]:
        """First LOAD of ``key`` after the call statement, unless a
        rebind comes first (line-ordered approximation)."""
        after = (stmt.end_lineno or stmt.lineno) if stmt is not None \
            else (call.end_lineno or call.lineno)
        first_load: Optional[ast.AST] = None
        first_rebind: Optional[int] = None
        for node in walk_shallow(fn):
            ln = getattr(node, "lineno", None)
            if ln is None or ln <= after:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    els = t.elts if isinstance(t, ast.Tuple) else [t]
                    if any(ast.unparse(el) == key for el in els):
                        if first_rebind is None or ln < first_rebind:
                            first_rebind = ln
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load) \
                    and ast.unparse(node) == key:
                if first_load is None or ln < first_load.lineno:
                    first_load = node
        if first_load is None:
            return None
        if first_rebind is not None and first_rebind <= first_load.lineno:
            return None
        return first_load
