"""span-balance: every explicit trace span is closed on all paths.

``TraceRecorder.begin_span`` returns a token that MUST reach
``end_span`` on every control-flow path — including exceptions — or the
span silently never closes and the trace undercounts the very interval
it was added to measure. The enforced shape is exactly one idiom:

    tok = TRACER.begin_span(rid, "name")
    try:
        ...
    finally:
        TRACER.end_span(tok, ...)

(the assignment immediately followed by a ``try`` whose ``finally``
calls ``end_span``), or the balanced-by-construction context manager
``with TRACER.span(rid, "name"):``. Anything else — a discarded token,
an end_span outside the protecting ``finally``, statements between the
begin and the try that could raise — flags here.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Context, Finding


def _is_begin(call: ast.Call) -> bool:
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr == "begin_span"


def _has_end_span(stmts: list) -> bool:
    for s in stmts:
        for node in ast.walk(s):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "end_span"):
                return True
    return False


def _stmt_lists(tree: ast.AST) -> Iterator[list]:
    for node in ast.walk(tree):
        for name in ("body", "orelse", "finalbody"):
            lst = getattr(node, name, None)
            if isinstance(lst, list) and lst and isinstance(lst[0],
                                                            ast.stmt):
                yield lst


def _begin_calls_of(stmt: ast.stmt) -> Iterator[ast.Call]:
    """begin_span calls belonging to THIS statement's own expressions.

    Nested statement blocks (a compound statement's body) are yielded
    as their own lists by ``_stmt_lists`` and checked there, so the
    scan stops at child statements to avoid double-reporting."""
    todo: list = [stmt]
    while todo:
        n = todo.pop()
        if isinstance(n, ast.Call) and _is_begin(n):
            yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, ast.stmt):
                todo.append(child)


class SpanBalance:
    id = "span-balance"
    doc = ("begin_span without a guaranteed end_span — use "
           "`tok = ...begin_span(...)` immediately followed by "
           "try/finally end_span(tok), or the span() context manager")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            for stmts in _stmt_lists(m.tree):
                for i, stmt in enumerate(stmts):
                    yield from self._check_stmt(m, stmts, i, stmt)

    def _check_stmt(self, m, stmts: list, i: int,
                    stmt: ast.stmt) -> Iterator[Finding]:
        calls = list(_begin_calls_of(stmt))
        if not calls:
            return
        # the one balanced shape: `tok = ...begin_span(...)` as the
        # WHOLE statement, with the very next statement a try whose
        # finally reaches end_span
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
                and _is_begin(stmt.value) and len(calls) == 1):
            nxt = stmts[i + 1] if i + 1 < len(stmts) else None
            if isinstance(nxt, ast.Try) and _has_end_span(nxt.finalbody):
                return
            yield m.finding(
                self.id, stmt,
                "begin_span result is not protected by an immediately "
                "following try/finally that calls end_span")
            return
        for call in calls:
            yield m.finding(
                self.id, call,
                "begin_span token is discarded or buried in a larger "
                "expression — it cannot reach end_span on all paths")
