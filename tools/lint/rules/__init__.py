"""graftlint rule registry."""

from __future__ import annotations

from ..core import KNOWN_RULES
from .donation import DonationAfterUse
from .env_knobs import EnvKnobRegistry
from .exception_hygiene import ExceptionHygiene
from .hot_path_sync import HotPathSync
from .lock_discipline import LockDiscipline
from .metrics_contract import MetricsContract
from .scalar_payload import ScalarPayload
from .sharding_contract import ShardingContract
from .span_balance import SpanBalance


class LintPragma:
    """Malformed / unreasoned lint pragmas. The findings themselves are
    emitted by core.run_rules (pragma parsing is part of loading a
    module); this rule object gives the id a row in the registry,
    ``--rules`` selection and the README table."""

    id = "lint-pragma"
    doc = ("malformed lint pragma: unknown rule id, missing ignore "
           "reason, unbalanced region (always on)")

    def check(self, ctx):
        return iter(())


ALL_RULES = (
    HotPathSync(),
    ScalarPayload(),
    LockDiscipline(),
    DonationAfterUse(),
    ExceptionHygiene(),
    MetricsContract(),
    SpanBalance(),
    ShardingContract(),
    EnvKnobRegistry(),
    LintPragma(),
)

for _r in ALL_RULES:
    KNOWN_RULES.add(_r.id)


def rules_by_id(ids=None):
    if not ids:
        return list(ALL_RULES)
    known = {r.id: r for r in ALL_RULES}
    missing = [i for i in ids if i not in known]
    if missing:
        raise KeyError(f"unknown rule id(s): {missing} "
                       f"(known: {sorted(known)})")
    return [known[i] for i in ids]
