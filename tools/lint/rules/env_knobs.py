"""env-knob-registry: every LOCALAI_* knob reads through config/knobs.py.

~45 ``LOCALAI_*`` environment knobs steer the engine. Before the
registry each call site hand-rolled its own default and truthiness
parsing (``not in ("0", "off", "false")`` vs ``in ("1", "true")`` —
subtly different at every site), and a typo'd knob name read its
default forever with no error anywhere. The registry
(``localai_tfp_tpu/config/knobs.py``) makes each knob a declared
(name, default, parser, doc) row; this rule enforces that it stays the
single point of truth:

- raw ``os.environ["LOCALAI_..."]`` / ``os.environ.get`` /
  ``os.getenv`` access outside ``config/`` is a finding (migrate to a
  ``knobs.flag/int_/float_/str_/raw/present`` accessor);
- an f-string/computed ``LOCALAI_`` env key outside ``config/`` is a
  finding (unauditable: the registry cannot cross-check it);
- a knobs accessor naming an UNREGISTERED knob (or a non-literal name)
  is a finding — the typo now fails the lint gate;
- every registered knob needs a `` `LOCALAI_X` `` row in the README
  "Configuration knobs" table (metrics-contract style).

``config/`` is exempt: the registry lives there, and
``app_config.py`` maps computed CLI-flag names onto ``LOCALAI_<FLAG>``
aliases generically (a deliberate, documented carve-out). Repo-wide
checks (README coverage) only run when the real registry module is in
the context, so fixture runs stay hermetic.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Context, Finding, Module

KNOBS_MODULE = "localai_tfp_tpu/config/knobs.py"
_EXEMPT_PREFIX = "localai_tfp_tpu/config/"
_ACCESSORS = {"flag", "int_", "float_", "str_", "raw", "present"}
_ENV_FUNCS = {"get", "getenv", "setdefault", "pop"}


def registered_knobs(ctx: Context) -> Optional[set[str]]:
    """Knob names parsed from the registry module's AST (`_knob("X",
    ...)` calls) — the linter never imports package code."""
    mods = [m for m in ctx.modules if m.rel == KNOBS_MODULE]
    mods += [m for m in ctx.modules if m.rel != KNOBS_MODULE]
    for m in mods:
        names: set[str] = set()
        for node in ast.walk(m.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "_knob"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                names.add(node.args[0].value)
        if names:
            return names
    return None


def _is_environ(node: ast.AST) -> bool:
    """`os.environ` / `environ` / `_os.environ`."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _knob_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("LOCALAI_"):
        return node.value
    return None


def _computed_knob(node: ast.AST) -> bool:
    """An f-string env key starting with LOCALAI_ (computed name)."""
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        return (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("LOCALAI_"))
    return False


class EnvKnobRegistry:
    id = "env-knob-registry"
    doc = ("raw os.environ access to LOCALAI_* knobs outside "
           "config/knobs.py, unregistered knob names, missing README "
           "knob-table rows")

    def check(self, ctx: Context) -> Iterator[Finding]:
        registry = registered_knobs(ctx)
        for m in ctx.modules:
            if m.rel.startswith(_EXEMPT_PREFIX):
                continue
            yield from self._check_module(m, registry)
        # repo-wide checks need the real registry in context
        if ctx.module(KNOBS_MODULE) is not None and registry:
            yield from self._check_readme(ctx, registry)

    def _check_module(self, m: Module,
                      registry: Optional[set[str]]) -> Iterator[Finding]:
        for node in ast.walk(m.tree):
            # os.environ["LOCALAI_X"] / del os.environ[...]
            if isinstance(node, ast.Subscript) and \
                    _is_environ(node.value):
                key = node.slice
                name = _knob_literal(key)
                if name is not None or _computed_knob(key):
                    shown = name or "LOCALAI_<computed>"
                    yield m.finding(
                        self.id, node,
                        f"raw os.environ[{shown!r}] — read knobs "
                        "through config/knobs.py accessors (flag/int_/"
                        "float_/str_/raw/present)")
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # os.environ.get(...) / os.getenv(...)
            if isinstance(f, ast.Attribute) and f.attr in _ENV_FUNCS \
                    and node.args:
                is_env_call = _is_environ(f.value) or (
                    f.attr == "getenv"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("os", "_os"))
                if is_env_call:
                    name = _knob_literal(node.args[0])
                    if name is not None:
                        yield m.finding(
                            self.id, node,
                            f"raw os.environ access to {name!r} — "
                            "read it through config/knobs.py (the "
                            "registry owns the default and parser)")
                    elif _computed_knob(node.args[0]):
                        yield m.finding(
                            self.id, node,
                            "computed LOCALAI_* env key — the knob "
                            "registry cannot audit an f-string name; "
                            "register each knob in config/knobs.py")
                continue
            # knobs.flag("LOCALAI_X") — accessor name validation
            if isinstance(f, ast.Attribute) and f.attr in _ACCESSORS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "knobs" and node.args:
                name = _knob_literal(node.args[0])
                if name is None:
                    yield m.finding(
                        self.id, node,
                        f"knobs.{f.attr}() with a non-literal or "
                        "non-LOCALAI_ name — knob reads must name a "
                        "registered LOCALAI_* literal")
                elif registry is not None and name not in registry:
                    yield m.finding(
                        self.id, node,
                        f"knobs.{f.attr}({name!r}) names an "
                        "UNREGISTERED knob — declare it in "
                        "config/knobs.py (name, default, parser, doc)")

    def _check_readme(self, ctx: Context,
                      registry: set[str]) -> Iterator[Finding]:
        m = ctx.module(KNOBS_MODULE)
        for name in sorted(registry):
            if f"`{name}`" not in ctx.readme_text:
                yield m.finding(
                    self.id, 1,
                    f"knob {name} has no row in the README "
                    "\"Configuration knobs\" table — every registered "
                    "knob ships documented")
