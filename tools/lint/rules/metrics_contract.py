"""metrics-contract: metric naming + README coverage + required set.

The lint-framework port of ``tools/check_metrics.py`` (whose CLI now
wraps this rule). Every literal registry registration
(``REGISTRY.counter("...")`` / ``.gauge`` / ``.histogram``) must

- be snake_case,
- carry a unit suffix (counters ``_total``; histograms ``_seconds`` /
  ``_bytes``/``_ratio``; gauges ``_seconds``/``_bytes``/``_count``/
  ``_ratio``/``_info``, or a ``<unit>_per_<x>`` rate),
- appear as `` `name` `` in the README Observability table, and
- a computed (non-literal) name is itself a finding: it can be neither
  linted nor documented.

``REQUIRED_FAMILIES`` must all stay registered — deleting one silently
breaks dashboards and the bench's extra blocks. Repo-wide checks
(required set, empty-scan guard, README coverage without an explicit
readme) only run on full-package scans so fixture tests stay hermetic.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import Context, Finding

_SNAKE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")

SUFFIXES = {
    "counter": ("_total",),
    "histogram": ("_seconds", "_bytes", "_ratio"),
    "gauge": ("_seconds", "_bytes", "_count", "_ratio", "_info"),
}

# rate/intensity gauges: unit suffix + `_per_<x>` qualifier
# (Prometheus bytes_per_second convention) is also valid
_PER_GAUGE = re.compile(r"_(seconds|bytes|count)_per_[a-z0-9_]+$")

# families that MUST exist (removing one silently breaks dashboards
# and the bench's extra blocks)
REQUIRED_FAMILIES = {
    "engine_kv_pages_in_use_count",
    "engine_kv_pages_shared_count",
    "engine_kv_page_alloc_total",
    "engine_kv_hbm_per_live_token_bytes",
    "engine_kv_tier_pages_count",
    "engine_kv_tier_moves_total",
    "engine_kv_tier_prefetch_total",
    "engine_kv_tier_bytes_moved_total",
    "engine_weight_pages_count",
    "engine_weight_page_moves_total",
    "engine_weight_prefetch_total",
    "engine_model_residency_count",
    "engine_disagg_requests_total",
    "engine_kv_migrated_pages_total",
    "engine_kv_migration_seconds",
    "engine_disagg_stage_seconds",
    "engine_dispatch_compile_variants_count",
    "engine_ragged_rows_total",
    "engine_mesh_devices_count",
    "engine_warmup_seconds",
    "engine_requests_shed_total",
    "engine_deadline_exceeded_total",
    "federation_node_state_count",
    "federation_retries_total",
    "federation_digest_errors_total",
    "federation_route_locality_total",
    "federation_prefix_matched_tokens_total",
    "fleet_replicas_desired_count",
    "fleet_scale_events_total",
    "fleet_ttft_seconds",
    "fleet_itl_seconds",
    "fleet_queue_wait_seconds",
    "fleet_node_queue_depth_count",
    "fleet_node_slots_busy_count",
    "fleet_node_mfu_ratio",
    "fleet_node_hbm_bytes",
    "fleet_node_predicted_drain_seconds",
    "fleet_digest_age_seconds",
    "fleet_digest_stale_count",
    "fleet_slo_burn_rate_ratio",
    "fleet_slo_state_info",
    "faults_injected_total",
    "engine_device_step_seconds",
    "trace_spans_dropped_total",
    "timeline_ring_events_count",
    "engine_device_flops_total",
    "engine_device_bytes_total",
    "engine_mfu_ratio",
    "engine_dispatch_predicted_seconds",
    "engine_dispatch_predicted_ratio",
    "engine_hbm_bytes",
    "device_hbm_used_bytes",
    "process_rss_bytes",
}

_METRICS_MODULE = "localai_tfp_tpu/telemetry/metrics.py"


def find_registrations(ctx: Context):
    """(kind, name, module, line) for every literal registration, plus
    (module, line) for computed names."""
    regs, computed = [], []
    for m in ctx.modules:
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in SUFFIXES):
                continue
            # skip unrelated attr calls with no args (e.g. obj.gauge())
            if not node.args:
                continue
            name = node.args[0]
            if isinstance(name, ast.Constant) \
                    and isinstance(name.value, str):
                regs.append((node.func.attr, name.value, m, node.lineno))
            else:
                computed.append((node.func.attr, m, node.lineno))
    return regs, computed


class MetricsContract:
    id = "metrics-contract"
    doc = ("metric registration violates the naming/README contract "
           "(snake_case, unit suffix, Observability table row)")

    def check(self, ctx: Context) -> Iterator[Finding]:
        regs, computed = find_registrations(ctx)
        full = ctx.module(_METRICS_MODULE) is not None
        for kind, m, line in computed:
            yield m.finding(
                self.id, line,
                f".{kind}() registration with a computed name — literal "
                "names only (a computed name cannot be linted or "
                "documented)")
        readme = ctx.readme_text
        for kind, name, m, line in regs:
            if not _SNAKE.match(name):
                yield m.finding(self.id, line,
                                f"metric '{name}' is not snake_case")
            if not name.endswith(SUFFIXES[kind]) and not (
                    kind == "gauge" and _PER_GAUGE.search(name)):
                yield m.finding(
                    self.id, line,
                    f"{kind} '{name}' lacks a unit suffix (one of "
                    f"{', '.join(SUFFIXES[kind])})")
            if (readme or full) and f"`{name}`" not in readme:
                yield m.finding(
                    self.id, line,
                    f"metric '{name}' is not documented in the "
                    f"README.md Observability table (add a `{name}` "
                    "row)")
        if full:
            main = ctx.module(_METRICS_MODULE)
            if not regs:
                yield main.finding(
                    self.id, 1,
                    "no metric registrations found under "
                    "localai_tfp_tpu/ — scanner or layout broke")
            missing = REQUIRED_FAMILIES - {n for _, n, _, _ in regs}
            for name in sorted(missing):
                yield main.finding(
                    self.id, 1,
                    f"required metric family '{name}' is not "
                    "registered anywhere under localai_tfp_tpu/")
