"""hot-path-sync: no device syncs inside scheduler/dispatch regions.

The scheduler's async pipeline shape (enqueue everything, harvest on
readiness) is why decode ITL survives admission waves. ONE stray
``.item()`` / ``np.asarray(device_value)`` / ``block_until_ready`` in
the loop serializes host against device and collapses the pipeline —
a bug class that profiles as "mysteriously slow", never as an error.

Scope: code inside ``# lint: region hot_path`` .. ``# lint: endregion
hot_path`` spans (the scheduler loop and dispatch/harvest paths in
``engine/engine.py``), plus — interprocedurally — every module-local
helper those spans call: a region call site that resolves to a method
of the same class or a module-level function pulls the callee's body
into the hot path (to a bounded depth), so a ``.item()`` buried two
helpers below the region fires, with the call chain in the finding.

Inside hot-path code the rule flags:

- ``.item()``, ``block_until_ready``, ``jax.device_get`` — always;
- ``np.asarray`` / ``np.array`` / ``np.frombuffer``, ``int()`` /
  ``float()`` / ``bool()``, ``.tolist()`` / ``.tobytes()`` applied to a
  DEVICE-TAINTED expression.

Taint is a per-function forward pass: results of ``self._run`` /
``self._dev_exec``, the engine's device state attributes
(``self.cache``, ``self.sampling``, ...) and flight ``.arrays`` are
device values; names assigned from tainted expressions inherit the
taint. Interprocedural calls seed the callee's parameters with the
caller's argument taint, and a callee whose return value is tainted
taints the call expression back at the caller. Shape/dtype metadata
access (``.shape``, ``.dtype``, ...) and a flagged conversion's own
result (it IS the host copy) drop it.

Intentionally-blocking paths (``_decode1_step``'s per-token grammar
harvest, flight completion after ``ready()``) carry reasoned
``# lint: ignore[hot-path-sync]`` suppressions at the sync line — a
suppression in a helper keeps covering it no matter which region
reaches it.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Context, Finding, Module
from .scalar_payload import walk_shallow

REGION = "hot_path"

# how many helper hops below a region a sync can hide and still fire
MAX_DEPTH = 4

# engine attributes that hold live device arrays
DEVICE_ATTRS = {
    "cache", "draft_cache", "sampling", "params", "draft",
    "_dev_tokens", "_dev_pos", "_dev_active",
}
# attribute reads that yield host metadata, not device data
_META_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "nbytes",
               "sharding", "quantized"}
# attribute names whose access marks the object as device-held
_DEVICE_BEARING_ATTRS = {"arrays"}

_ALWAYS_FLAG_ATTRS = {"item", "block_until_ready", "device_get"}
_TAINT_FLAG_ATTRS = {"tolist", "tobytes"}
_CONVERTERS = {"int", "float", "bool"}
_NP_CONVERTERS = {"asarray", "array", "frombuffer"}
_NP_NAMES = {"np", "numpy"}
_HOST_CALLS = {"len", "range", "enumerate", "zip", "sorted", "min",
               "max", "sum", "any", "all"}


class _FnTaint:
    """One forward taint pass over a function body (statement order;
    loops are not fix-pointed — good enough for lexical hot paths)."""

    def __init__(self) -> None:
        self.names: set[str] = set()
        # optional interprocedural hook: Call -> tainted / clean / None
        # (None = unresolvable, fall back to the argument heuristic)
        self.call_taint = None

    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _META_ATTRS:
                return False
            if node.attr in _DEVICE_BEARING_ATTRS:
                return True
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return node.attr in DEVICE_ATTRS
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in _HOST_CALLS:
                return False
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in ("_run", "_dev_exec")):
                return True
            if self._is_flagged_conversion(f):
                return False  # the conversion result IS the host copy
            if self.call_taint is not None:
                known = self.call_taint(node, self)
                if known is not None:
                    return known
            parts = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(f, ast.Attribute):
                parts.append(f.value)
            return any(self.expr(p) for p in parts)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return False  # comparisons yield host bools... on device
            # arrays they yield arrays, but comparing device values in
            # the hot path surfaces at the int()/bool() conversion site
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False

    @staticmethod
    def _is_flagged_conversion(f: ast.AST) -> bool:
        if isinstance(f, ast.Name) and f.id in _CONVERTERS:
            return True
        return (isinstance(f, ast.Attribute)
                and f.attr in _NP_CONVERTERS
                and isinstance(f.value, ast.Name)
                and f.value.id in _NP_NAMES)

    def assign(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            t = self.expr(node.value)
            for target in node.targets:
                self._bind(target, t)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._bind(node.target, self.expr(node.value))
        elif isinstance(node, ast.AugAssign):
            if self.expr(node.value) and isinstance(node.target, ast.Name):
                self.names.add(node.target.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._bind(node.target, self.expr(node.iter))
        elif isinstance(node, ast.withitem) and node.optional_vars:
            self._bind(node.optional_vars, self.expr(node.context_expr))

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.names.add if tainted
             else self.names.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, tainted)
        # stores into attributes/subscripts don't rebind a local name


class HotPathSync:
    id = "hot-path-sync"
    doc = ("device sync (.item()/np.asarray/block_until_ready/...) on "
           "a hot path: inside a '# lint: region hot_path' region or "
           "any module-local helper it calls")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            if REGION not in m.pragmas.regions:
                continue
            # per-module interprocedural state: return-taint memo and
            # the set of (callee, seed) bodies already reported (a
            # helper reached from several regions reports once)
            self._ret_memo: dict[tuple, Optional[bool]] = {}
            self._reported: set[tuple] = set()
            for fn in ast.walk(m.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                # run taint over any function that overlaps a region
                end = fn.end_lineno or fn.lineno
                if any(a <= end and b >= fn.lineno
                       for a, b in m.pragmas.regions[REGION]):
                    qual = m.scope_at(fn.lineno)
                    _, findings = self._scan_fn(
                        m, fn, qual, seed=frozenset(),
                        chain=(fn.name,), depth=0,
                        region_gated=True, emit=True)
                    yield from findings

    # ------------------------------------------------------- traversal

    def _scan_fn(self, m: Module, fn, qual: str, seed: frozenset,
                 chain: tuple, depth: int, region_gated: bool,
                 emit: bool) -> tuple[bool, list[Finding]]:
        """Ordered taint walk of one function. Returns (return value is
        tainted, findings). ``region_gated`` limits checking/descent to
        region lines (the root functions); callee bodies are hot
        throughout. ``seed`` holds parameter names tainted by the call
        site's arguments."""
        findings: list[Finding] = []
        taint = _FnTaint()
        taint.names |= seed
        taint.call_taint = (
            lambda call, t: self._ret_taint(m, qual, call, t, depth))
        ret_tainted = False
        # statement-ordered shallow traversal: check calls with the
        # taint state BEFORE their enclosing assignment binds (in
        # `D = np.asarray(D)` the call must see the old, tainted D), so
        # assignment bindings are deferred to the statement's end
        nodes = sorted(walk_shallow(fn),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        pending: list[tuple[tuple[int, int], ast.AST]] = []
        for node in nodes:
            pos = (getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0))
            while pending and pos > pending[0][0]:
                taint.assign(pending.pop(0)[1])
            if isinstance(node, ast.Call):
                hot = (not region_gated
                       or m.pragmas.in_region(REGION, node.lineno))
                if hot:
                    findings.extend(
                        self._check_call(m, taint, node, chain, emit))
                    findings.extend(
                        self._descend(m, qual, taint, node, chain,
                                      depth, emit))
            if isinstance(node, ast.Return) and node.value is not None:
                if taint.expr(node.value):
                    ret_tainted = True
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                end = (node.end_lineno or node.lineno,
                       node.end_col_offset or 0)
                pending.append((end, node))
                pending.sort(key=lambda e: e[0])
            else:
                taint.assign(node)  # loop/with targets bind up front
        return ret_tainted, findings

    def _seed_params(self, fn, call: ast.Call,
                     taint: _FnTaint) -> frozenset:
        """Callee parameter names bound to tainted caller arguments."""
        params = [a.arg for a in fn.args.args]
        if params and params[0] == "self":
            params = params[1:]
        seeded = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(params) and taint.expr(arg):
                seeded.add(params[i])
        for kw in call.keywords:
            if kw.arg is not None and taint.expr(kw.value):
                seeded.add(kw.arg)
        return frozenset(seeded)

    def _descend(self, m: Module, caller_qual: str, taint: _FnTaint,
                 call: ast.Call, chain: tuple, depth: int,
                 emit: bool) -> list[Finding]:
        """A hot call site resolving to a module-local function pulls
        the callee body into the hot path."""
        if depth >= MAX_DEPTH:
            return []
        hit = m.resolve_call(caller_qual, call)
        if hit is None:
            return []
        callee_qual, fn = hit
        leaf = callee_qual.rsplit(".", 1)[-1]
        if leaf in chain:  # recursion guard
            return []
        seed = self._seed_params(fn, call, taint)
        key = (callee_qual, seed)
        do_emit = emit and key not in self._reported
        if do_emit:
            self._reported.add(key)
        elif (callee_qual, seed) in self._ret_memo:
            return []  # fully analyzed already, nothing new to report
        _, findings = self._scan_fn(
            m, fn, callee_qual, seed, chain + (leaf,), depth + 1,
            region_gated=False, emit=do_emit)
        return findings

    def _ret_taint(self, m: Module, caller_qual: str, call: ast.Call,
                   taint: _FnTaint, depth: int) -> Optional[bool]:
        """Interprocedural return-value taint for the _FnTaint hook
        (no finding emission — emission is _descend's job)."""
        if depth >= MAX_DEPTH:
            return None
        hit = m.resolve_call(caller_qual, call)
        if hit is None:
            return None
        callee_qual, fn = hit
        seed = self._seed_params(fn, call, taint)
        key = (callee_qual, seed)
        if key in self._ret_memo:
            memo = self._ret_memo[key]
            return False if memo is None else memo  # None: in progress
        self._ret_memo[key] = None
        ret, _ = self._scan_fn(m, fn, callee_qual, seed,
                               (callee_qual.rsplit(".", 1)[-1],),
                               depth + 1, region_gated=False, emit=False)
        self._ret_memo[key] = ret
        return ret

    # --------------------------------------------------------- checks

    def _check_call(self, m: Module, taint: _FnTaint, call: ast.Call,
                    chain: tuple, emit: bool) -> Iterator[Finding]:
        if not emit:
            return
        via = ("" if len(chain) <= 1
               else " (hot path via " + " -> ".join(chain) + ")")
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in _ALWAYS_FLAG_ATTRS:
                yield m.finding(
                    self.id, call,
                    f"'.{f.attr}()' forces a device sync in the hot "
                    "path — harvest via flight readiness instead" + via)
                return
            if f.attr in _TAINT_FLAG_ATTRS and taint.expr(f.value):
                yield m.finding(
                    self.id, call,
                    f"'.{f.attr}()' on a device value blocks the "
                    "scheduler on device completion" + via)
                return
            if (f.attr in _NP_CONVERTERS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _NP_NAMES
                    and any(taint.expr(a) for a in call.args)):
                yield m.finding(
                    self.id, call,
                    f"np.{f.attr}() on a device value is a blocking "
                    "device->host transfer in the hot path" + via)
                return
        if (isinstance(f, ast.Name) and f.id in _CONVERTERS
                and any(taint.expr(a) for a in call.args)):
            yield m.finding(
                self.id, call,
                f"{f.id}() coerces a device value on the host — an "
                "implicit device sync in the hot path" + via)
