"""sharding-contract: GSPMD layout contracts on the paged serving path.

PR 12's hardest bug class: GSPMD miscompiles the paged
gather -> forward -> scatter program unless every fallback branch pins
the gathered window's layout (``engine._pin_win_sharding``) — jit vs
eager silently diverges on the written pages, O(1)-wrong hidden states,
no error anywhere. This rule makes that class un-reintroducible, plus
two adjacent layout contracts:

1. **Pin discipline** — in any function (engine/, ops/,
   parallel/multihost.py) that both ``gather_kv_pages(...)`` and
   ``scatter_kv_pages(...)``, every name bound from the gather must be
   re-bound through ``_pin_win_sharding(name, ..., batch=True)`` before
   the forward, and every window passed to the scatter must come out of
   ``_pin_win_sharding(name, ..., batch=False)`` — the dense-layout /
   arena-layout round trip that anchors GSPMD.
2. **No inline PartitionSpec literals** — every ``P(...)`` spec in the
   scoped modules must be built from the named constants in
   ``parallel/sharding.py`` (``PAGED_KV_SPEC``, ``KV_CACHE_SPEC``,
   ``DENSE_ROW_SPEC``, ``REPLICATED``, ...); an inline literal is a
   layout fork that drifts from the arena the first time the arena
   changes.
3. **Host-owned page tables stay global** — int32 page/write tables
   (``phys``, ``wb``, ``page_table``, ``write_table``, ``pt``, ...) are
   scheduler state every device reads whole; passing one to
   ``with_sharding_constraint`` / ``device_put`` / ``_pin_win_sharding``
   turns host bookkeeping into a mesh-resident operand and re-opens the
   layout-guess hole.

Scope: ``localai_tfp_tpu/engine/*``, ``localai_tfp_tpu/ops/*`` and
``parallel/multihost.py``. ``parallel/sharding.py`` itself is where the
named constants LIVE and is exempt; ``parallel/ring_attention.py``
builds specs from dynamic axis names and is out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Context, Finding, Module
from .scalar_payload import walk_shallow

_SCOPED_DIRS = ("localai_tfp_tpu/engine/", "localai_tfp_tpu/ops/")
_SCOPED_FILES = ("localai_tfp_tpu/parallel/multihost.py",)

_GATHER = "gather_kv_pages"
_SCATTER = "scatter_kv_pages"
_PIN = "_pin_win_sharding"

# identifiers that name host-owned int32 page/write tables
PAGE_TABLE_NAMES = {
    "phys", "wb", "pt", "wt", "page_table", "write_table",
    "page_tables", "paged_tables", "ptab", "tables",
}
_CONSTRAIN_CALLS = {"with_sharding_constraint", "device_put", _PIN}


def _in_scope(rel: str) -> bool:
    return rel.startswith(_SCOPED_DIRS) or rel in _SCOPED_FILES


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _terminal_name(node: ast.AST) -> str:
    """`phys` / `self.phys` / `payload["phys"]`-style terminal id."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _terminal_name(node.value)
    return ""


def _pin_batch_arg(call: ast.Call):
    """The `batch` argument of a _pin_win_sharding call: True / False /
    None (not a literal — dynamic, counts for both directions)."""
    for kw in call.keywords:
        if kw.arg == "batch":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return None
    if len(call.args) >= 3 and isinstance(call.args[2], ast.Constant):
        return bool(call.args[2].value)
    return None


class ShardingContract:
    id = "sharding-contract"
    doc = ("paged-window pin discipline, named-constant PartitionSpecs "
           "and host-global page tables on the GSPMD serving path")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            if not _in_scope(m.rel):
                continue
            yield from self._check_spec_literals(m)
            yield from self._check_page_tables(m)
            yield from self._check_pins(m)

    # ------------------------------------------- inline P(...) literals

    def _spec_aliases(self, m: Module) -> set[str]:
        """Local names bound to jax.sharding.PartitionSpec by import."""
        aliases: set[str] = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith("jax"):
                for a in node.names:
                    if a.name == "PartitionSpec":
                        aliases.add(a.asname or a.name)
        return aliases

    def _check_spec_literals(self, m: Module) -> Iterator[Finding]:
        aliases = self._spec_aliases(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_alias = isinstance(f, ast.Name) and f.id in aliases
            is_attr = (isinstance(f, ast.Attribute)
                       and f.attr == "PartitionSpec")
            if is_alias or is_attr:
                yield m.finding(
                    self.id, node,
                    "inline PartitionSpec literal — build specs from "
                    "the named constants in parallel/sharding.py "
                    "(PAGED_KV_SPEC, KV_CACHE_SPEC, REPLICATED, ...) "
                    "so layouts cannot drift from the arena")

    # --------------------------------------------- page-table globality

    def _check_page_tables(self, m: Module) -> Iterator[Finding]:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in _CONSTRAIN_CALLS:
                continue
            if not node.args:
                continue
            name = _terminal_name(node.args[0])
            if name in PAGE_TABLE_NAMES:
                yield m.finding(
                    self.id, node,
                    f"sharding constraint on host-owned page table "
                    f"'{name}' — int32 page/write tables are scheduler "
                    "state every device reads whole and must never be "
                    "mesh-constrained")

    # -------------------------------------------------- pin discipline

    def _check_pins(self, m: Module) -> Iterator[Finding]:
        # assign each call to its INNERMOST enclosing function so the
        # jitted-closure fallbacks (`_spec` under `_spec_decode_fn`)
        # are analyzed once, at the level their calls actually live
        funcs = [n for n in ast.walk(m.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            gathers: list[tuple[str, ast.Call]] = []  # bound name, call
            scatters: list[ast.Call] = []
            pins: list[tuple[str, ast.Call, object]] = []
            for node in walk_shallow(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    call = node.value
                    if _call_name(call) == _GATHER and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name):
                        gathers.append((node.targets[0].id, call))
                if isinstance(node, ast.Call):
                    cn = _call_name(node)
                    if cn == _SCATTER:
                        scatters.append(node)
                    elif cn == _PIN and node.args and \
                            isinstance(node.args[0], ast.Name):
                        pins.append((node.args[0].id, node,
                                     _pin_batch_arg(node)))
            if not gathers or not scatters:
                continue  # gather-only (kernel_check) / scatter-only
            for name, call in gathers:
                if not any(pn == name and batch in (True, None)
                           for pn, _, batch in pins):
                    yield m.finding(
                        self.id, call,
                        f"paged fallback gathers window '{name}' and "
                        "scatters it back without routing through "
                        "_pin_win_sharding(..., batch=True) — GSPMD "
                        "picks a miscompiling layout for the fused "
                        "gather->forward->scatter program (PR 12 bug "
                        "class)")
            for call in scatters:
                win = (_terminal_name(call.args[1])
                       if len(call.args) >= 2 else "")
                if not win:
                    continue
                # the window fed to the scatter must have been pinned
                # back to the arena layout (batch=False) in this scope,
                # unless it IS a freshly gathered name that was pinned
                # (the pin rebinding keeps the same name)
                if not any(pn == win and batch in (False, None)
                           for pn, _, batch in pins):
                    yield m.finding(
                        self.id, call,
                        f"scatter_kv_pages writes window '{win}' that "
                        "never went through _pin_win_sharding(..., "
                        "batch=False) — the writeback must see updates "
                        "pinned to the arena's layout")
