"""guarded-by: annotated attributes mutate only under their lock.

``self._models = {}  # lint: guarded-by self._lock`` turns the comment
"registry map mutations only" into a checked contract: every statement
in the class that MUTATES ``self._models`` (assignment, ``del``,
subscript stores, ``.pop()``/``.append()``/... calls) must sit lexically
inside ``with self._lock:``. Reads are not checked (lock-free reads are
a deliberate, per-site judgement call). Exemptions:

- ``__init__`` bodies (single-threaded construction), and
- functions marked ``# lint: holds <lock>`` (caller holds the lock).

The analysis is lexical and per-class: helper methods called with the
lock held must carry the ``holds`` pragma rather than relying on call-
graph reasoning.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Context, Finding, Module

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
}


def _norm(expr: str) -> str:
    return "".join(expr.split())


def _root_self_attr(node: ast.AST) -> Optional[str]:
    """X for an expression rooted at ``self.X`` (through any chain of
    subscripts/attributes), else None."""
    while isinstance(node, (ast.Subscript, ast.Slice)):
        node = node.value  # type: ignore[union-attr]
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class LockDiscipline:
    id = "guarded-by"
    doc = ("attribute annotated '# lint: guarded-by <lock>' mutated "
           "outside 'with <lock>:'")

    def check(self, ctx: Context) -> Iterator[Finding]:
        for m in ctx.modules:
            yield from self._check_module(m)

    def _check_module(self, m: Module) -> Iterator[Finding]:
        if not m.pragmas.guarded:
            return
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def enclosing(node: ast.AST, kinds) -> Optional[ast.AST]:
            cur = parents.get(node)
            while cur is not None and not isinstance(cur, kinds):
                cur = parents.get(cur)
            return cur

        # map each guarded pragma to (class node, attr, lock expr):
        # the pragma is a trailing comment on (or the line above) the
        # attribute's assignment
        guarded: dict[ast.ClassDef, dict[str, str]] = {}
        for line, lock in m.pragmas.guarded:
            # trailing comment on the assignment, or a pragma line
            # directly above it — exact lines only (a +-1 window would
            # grab an ADJACENT attribute's assignment)
            hit = None
            for node in ast.walk(m.tree):
                if (isinstance(node, (ast.Assign, ast.AnnAssign))
                        and node.lineno <= line + 1
                        and (node.end_lineno or node.lineno) >= line):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _root_self_attr(t)
                        if attr is not None:
                            hit = (node, attr)
                            break
                if hit:
                    break
            if hit is None:
                yield m.finding(
                    "lint-pragma", line,
                    "guarded-by pragma is not attached to a self.<attr> "
                    "assignment")
                continue
            cls = enclosing(hit[0], ast.ClassDef)
            if cls is None:
                yield m.finding(
                    "lint-pragma", line,
                    "guarded-by pragma outside a class body")
                continue
            guarded.setdefault(cls, {})[hit[1]] = _norm(lock)

        # functions whose callers hold a lock
        holds: dict[ast.AST, set[str]] = {}
        for line, lock in m.pragmas.holds:
            fn = None
            for node in ast.walk(m.tree):
                if (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                        and node.lineno <= line + 1
                        and (node.end_lineno or node.lineno) >= line):
                    if fn is None or node.lineno > fn.lineno:
                        fn = node  # innermost
            if fn is None:
                yield m.finding(
                    "lint-pragma", line,
                    "holds pragma is not attached to a function")
                continue
            holds.setdefault(fn, set()).add(_norm(lock))

        for cls, attrs in guarded.items():
            for node in ast.walk(cls):
                attr = self._mutated_attr(node)
                if attr is None or attr not in attrs:
                    continue
                lock = attrs[attr]
                # exempt: inside `with <lock>:`
                cur = parents.get(node)
                ok = False
                fn_chain = []
                while cur is not None and cur is not cls:
                    if isinstance(cur, ast.With) and any(
                            _norm(ast.unparse(item.context_expr)) == lock
                            for item in cur.items):
                        ok = True
                        break
                    if isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fn_chain.append(cur)
                    cur = parents.get(cur)
                if ok:
                    continue
                if fn_chain and fn_chain[-1].name == "__init__":
                    continue  # construction is single-threaded
                if any(lock in holds.get(fn, ()) for fn in fn_chain):
                    continue
                yield m.finding(
                    self.id, node,
                    f"self.{attr} is guarded by '{lock}' but is mutated "
                    f"outside 'with {lock}:' (add the lock, or mark the "
                    f"function '# lint: holds {lock}')")

    @staticmethod
    def _mutated_attr(node: ast.AST) -> Optional[str]:
        """The guarded-candidate attribute a statement mutates, if any."""
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    attr = _root_self_attr(el)
                    if attr is not None:
                        return attr
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _root_self_attr(t)
                if attr is not None:
                    return attr
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                return _root_self_attr(f.value)
        return None
