"""Microbench: 8B-geometry decode-step weight-matmul strategies on TPU.

One decode step at batch B over 32 stacked layers (lax.scan, like the
engine's per-layer scan): q/k/v/o + gate/up/down projections only (no
attention, no sampling) — isolates the weight-read path that dominates
decode. Compares:
  xla_upcast   x @ q.astype(bf16) * scale      (current default path)
  pallas_512   current ops/int8_matmul (BK=BN=512)
  w8a8         dynamic per-row activation int8, int8xint8 dot (native MXU)

Roofline: int8 weights/layer ~218 MB; 32 layers ~7 GB; v5e ~819 GB/s
=> ~8.5 ms/step floor.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

B = 64
D, DQ, DKV, F, L = 4096, 4096, 1024, 14336, 32


def make_params(rng):
    def qt(k, n):
        q = rng.integers(-127, 128, (L, k, n), np.int8)
        s = (rng.random((L, n), np.float32) * 0.01 + 0.005) / 127.0
        return jnp.asarray(q), jnp.asarray(s)

    return {
        "wq": qt(D, DQ), "wk": qt(D, DKV), "wv": qt(D, DKV),
        "wo": qt(DQ, D), "w_gate": qt(D, F), "w_up": qt(D, F),
        "w_down": qt(F, D),
    }


def layer_xla(x, lw):
    def mm(x, w):
        q, s = w
        return (x @ q.astype(x.dtype)) * s.astype(x.dtype)

    h = mm(x, lw["wq"]) + mm(x, lw["wk"]).sum() + mm(x, lw["wv"]).sum()
    h = mm(h, lw["wo"])
    g = jax.nn.silu(mm(h, lw["w_gate"])) * mm(h, lw["w_up"])
    return x + mm(g, lw["w_down"])


def layer_pallas(x, lw):
    from localai_tfp_tpu.ops.int8_matmul import int8_matmul

    def mm(x, w):
        q, s = w
        return int8_matmul(x, q, s, out_dtype=x.dtype)

    h = mm(x, lw["wq"]) + mm(x, lw["wk"]).sum() + mm(x, lw["wv"]).sum()
    h = mm(h, lw["wo"])
    g = jax.nn.silu(mm(h, lw["w_gate"])) * mm(h, lw["w_up"])
    return x + mm(g, lw["w_down"])


def layer_w8a8(x, lw):
    def mm(x, w):
        q, s = w
        # dynamic per-row activation quant
        xs = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0 + 1e-9
        xq = jnp.clip(jnp.round(x / xs), -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            xq, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * xs * s).astype(x.dtype)

    h = mm(x, lw["wq"]) + mm(x, lw["wk"]).sum() + mm(x, lw["wv"]).sum()
    h = mm(h, lw["wo"])
    g = jax.nn.silu(mm(h, lw["w_gate"])) * mm(h, lw["w_up"])
    return x + mm(g, lw["w_down"])


def run(name, layer_fn, params, x, n_chain=8):
    """block_until_ready over the tunnel is optimistic (returns at
    enqueue-ack), so: time (n_chain dependent steps + download) and
    (1 step + download); per-step = delta / (n_chain - 1)."""
    @jax.jit
    def step(params, x):
        def body(h, lw):
            return layer_fn(h, lw), ()

        h, _ = jax.lax.scan(body, x, params)
        return jnp.tanh(h)  # keep output bounded across chained steps

    def timed(n):
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            h = x
            for _ in range(n):
                h = step(params, h)
            np.asarray(h[0, 0])
            best = min(best, time.perf_counter() - t0)
        return best

    np.asarray(step(params, x)[0, 0])  # compile
    t1 = timed(1)
    tn = timed(n_chain)
    t = (tn - t1) / (n_chain - 1) * 1e3
    print(f"{name:12s} {t:8.2f} ms/step (chained)   "
          f"1-step+rtt {t1 * 1e3:6.1f} ms   "
          f"({7e9 / 1e9 / (t / 1e3):6.1f} GB/s eff. weight BW)",
          flush=True)
    return t


def main():
    import sys

    sys.path.insert(0, "/root/repo")
    rng = np.random.default_rng(0)
    params = make_params(rng)
    x = jnp.asarray(rng.standard_normal((B, D), np.float32) * 0.1,
                    jnp.bfloat16)
    jax.block_until_ready(params)
    run("xla_upcast", layer_xla, params, x)
    run("w8a8", layer_w8a8, params, x)
    import os

    os.environ["LOCALAI_INT8_KERNEL"] = "1"
    run("pallas_512", layer_pallas, params, x)


if __name__ == "__main__":
    main()
