"""Native (C++) components: build + ctypes loading.

The reference keeps its hot paths in C++ (backend/cpp/llama); here the
TPU compute path is XLA, and the native pieces are the host-side hot
paths: the GBNF token-mask engine (per-decode-step work under grammar
constraints) and the vector store scan. Every native component has a
pure-Python fallback — `load_library` returns None when the .so is absent
and callers degrade gracefully.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
BUILD_DIR = os.path.join(_DIR, "build")

_cache: dict[str, Optional[ctypes.CDLL]] = {}


def build(quiet: bool = True) -> bool:
    """Invoke make; returns True if the libraries are present after."""
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            capture_output=quiet, check=True,
        )
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


def load_library(name: str, auto_build: bool = False) -> Optional[ctypes.CDLL]:
    """Load build/lib<name>.so; optionally build it first. None if
    unavailable (callers fall back to Python)."""
    if name in _cache:
        return _cache[name]
    path = os.path.join(BUILD_DIR, f"lib{name}.so")
    if not os.path.exists(path) and auto_build:
        build()
    lib: Optional[ctypes.CDLL] = None
    if os.path.exists(path):
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            lib = None
    _cache[name] = lib
    return lib
