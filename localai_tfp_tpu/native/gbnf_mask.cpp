// GBNF pushdown recognizer + per-step token-mask engine (C ABI).
//
// Native counterpart of grammars/gbnf.py + grammars/constrain.py — the
// per-token hot path of grammar-constrained decoding (SURVEY.md §7 hard
// part #3: the host-side mask must be ready before the device step lands;
// in the reference this work happens inside llama.cpp's C++ sampler).
// Same clean-room semantics as the Python engine: "set of stacks"
// pushdown states, vocab byte-trie DFS with prefix pruning, interned
// states so the Python side holds plain ints.
//
// Build: make -C localai_tfp_tpu/native   (produces build/libgbnf.so)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>
#include <algorithm>
#include <memory>

namespace {

using std::string;
using std::vector;

// ---------------------------------------------------------------- symbols

enum SymKind : uint8_t { LIT = 0, CLASS = 1, REF = 2 };

struct CharRange { uint32_t lo, hi; };

struct Sym {
    SymKind kind;
    uint32_t ch = 0;        // LIT
    int32_t rule = -1;      // REF
    int32_t cls = -1;       // CLASS: index into classes
};

struct CharClass {
    vector<CharRange> ranges;
    bool negated = false;
    bool matches(uint32_t c) const {
        bool hit = false;
        for (auto &r : ranges) if (c >= r.lo && c <= r.hi) { hit = true; break; }
        return negated ? !hit : hit;
    }
};

using Alt = vector<Sym>;       // sequence of symbols
using Rule = vector<Alt>;      // alternates

// ---------------------------------------------------------------- parser

struct Parser {
    string text;
    size_t i = 0;
    std::unordered_map<string, int32_t> rule_ids;
    vector<string> rule_names;
    vector<Rule> rules;
    vector<CharClass> classes;
    int aux = 0;
    string err;

    int32_t rid(const string &name) {
        auto it = rule_ids.find(name);
        if (it != rule_ids.end()) return it->second;
        int32_t id = (int32_t)rule_names.size();
        rule_ids[name] = id;
        rule_names.push_back(name);
        rules.emplace_back();
        return id;
    }

    void ws(bool newlines = true) {
        while (i < text.size()) {
            char c = text[i];
            if (c == '#') { while (i < text.size() && text[i] != '\n') i++; }
            else if (c == ' ' || c == '\t' || c == '\r' ||
                     (newlines && c == '\n')) i++;
            else break;
        }
    }

    char peek() { return i < text.size() ? text[i] : '\0'; }

    string name() {
        size_t j = i;
        while (j < text.size() &&
               (isalnum((unsigned char)text[j]) || text[j] == '-' ||
                text[j] == '_')) j++;
        if (j == i) { err = "expected name"; return ""; }
        string n = text.substr(i, j - i);
        i = j;
        return n;
    }

    // decode one possibly-escaped char as a unicode code point; the input
    // text is UTF-8, so non-escape bytes must be UTF-8-decoded too
    uint32_t escaped_char(bool &ok) {
        ok = true;
        unsigned char c = text[i];
        if (c != '\\') return utf8_next();
        i++;  // backslash
        char e = text[i++];
        switch (e) {
            case 'n': return '\n';
            case 't': return '\t';
            case 'r': return '\r';
            case '"': return '"';
            case '\\': return '\\';
            case '/': return '/';
            case '\'': return '\'';
            case '[': return '[';
            case ']': return ']';
            case 'x': { uint32_t v = hex(2, ok); return v; }
            case 'u': { uint32_t v = hex(4, ok); return v; }
            case 'U': { uint32_t v = hex(8, ok); return v; }
        }
        ok = false;
        err = "bad escape";
        return 0;
    }

    uint32_t hex(int n, bool &ok) {
        uint32_t v = 0;
        for (int k = 0; k < n; k++) {
            char c = text[i++];
            v <<= 4;
            if (c >= '0' && c <= '9') v |= c - '0';
            else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
            else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
            else { ok = false; err = "bad hex"; return 0; }
        }
        return v;
    }

    uint32_t utf8_next() {
        unsigned char c = text[i++];
        if (c < 0x80) return c;
        int extra = (c >= 0xF0) ? 3 : (c >= 0xE0) ? 2 : 1;
        uint32_t v = c & (0x3F >> extra);
        for (int k = 0; k < extra && i < text.size(); k++)
            v = (v << 6) | (text[i++] & 0x3F);
        return v;
    }

    string aux_name(const string &base) {
        return base + "-aux" + std::to_string(++aux);
    }

    bool parse() {
        ws();
        while (i < text.size() && err.empty()) {
            string n = name();
            if (!err.empty()) return false;
            ws();
            if (text.compare(i, 3, "::=") != 0) {
                err = "expected '::=' after rule '" + n + "'";
                return false;
            }
            i += 3;
            Rule alts;
            if (!alternates(n, alts)) return false;
            int32_t id = rid(n);
            for (auto &a : alts) rules[id].push_back(std::move(a));
            ws();
        }
        return err.empty();
    }

    bool alternates(const string &rulename, Rule &out) {
        Alt seq;
        if (!sequence(rulename, seq)) return false;
        out.push_back(std::move(seq));
        ws(false);
        while (peek() == '|') {
            i++;
            Alt s;
            if (!sequence(rulename, s)) return false;
            out.push_back(std::move(s));
            ws(false);
        }
        return true;
    }

    bool sequence(const string &rulename, Alt &seq) {
        for (;;) {
            ws(false);
            char c = peek();
            if (c == '\0' || c == '|' || c == ')' || c == '\n') break;
            Sym s;
            if (!symbol(rulename, s)) return false;
            ws(false);
            c = peek();
            if (c == '*' || c == '+' || c == '?' || c == '{') {
                if (!apply_repeat(rulename, s, c)) return false;
            }
            seq.push_back(s);
        }
        return true;
    }

    bool symbol(const string &rulename, Sym &out) {
        char c = peek();
        bool ok = true;
        if (c == '"') {
            i++;
            vector<uint32_t> chars;
            while (peek() != '"') {
                if (i >= text.size()) { err = "unterminated string"; return false; }
                chars.push_back(escaped_char(ok));
                if (!ok) return false;
            }
            i++;
            if (chars.size() == 1) {
                out = Sym{LIT, chars[0], -1, -1};
                return true;
            }
            string n = aux_name(rulename);
            int32_t id = rid(n);
            Alt alt;
            for (uint32_t ch : chars) alt.push_back(Sym{LIT, ch, -1, -1});
            rules[id].push_back(std::move(alt));
            out = Sym{REF, 0, id, -1};
            return true;
        }
        if (c == '[') {
            i++;
            CharClass cls;
            if (peek() == '^') { cls.negated = true; i++; }
            while (peek() != ']') {
                if (i >= text.size()) { err = "unterminated class"; return false; }
                uint32_t lo = escaped_char(ok);
                if (!ok) return false;
                uint32_t hi = lo;
                if (peek() == '-' && i + 1 < text.size() && text[i + 1] != ']') {
                    i++;
                    hi = escaped_char(ok);
                    if (!ok) return false;
                }
                cls.ranges.push_back({lo, hi});
            }
            i++;
            classes.push_back(std::move(cls));
            out = Sym{CLASS, 0, -1, (int32_t)classes.size() - 1};
            return true;
        }
        if (c == '(') {
            i++;
            string n = aux_name(rulename);
            int32_t id = rid(n);
            Rule alts;
            if (!alternates(n, alts)) return false;
            ws();
            if (peek() != ')') { err = "expected ')'"; return false; }
            i++;
            rules[id] = std::move(alts);
            out = Sym{REF, 0, id, -1};
            return true;
        }
        if (c == '.') {
            i++;
            classes.push_back(CharClass{{{0, 0x10FFFF}}, false});
            out = Sym{CLASS, 0, -1, (int32_t)classes.size() - 1};
            return true;
        }
        string n = name();
        if (!err.empty()) return false;
        out = Sym{REF, 0, rid(n), -1};
        return true;
    }

    bool apply_repeat(const string &rulename, Sym &sym, char op) {
        i++;
        if (op == '{') {
            size_t j = text.find('}', i);
            if (j == string::npos) { err = "unterminated {}"; return false; }
            string body = text.substr(i, j - i);
            i = j + 1;
            int lo = 0, hi = -1;
            auto comma = body.find(',');
            if (comma != string::npos) {
                string ls = body.substr(0, comma), hs = body.substr(comma + 1);
                lo = ls.empty() ? 0 : atoi(ls.c_str());
                hi = hs.find_first_not_of(" \t") == string::npos ? -1
                     : atoi(hs.c_str());
            } else {
                lo = hi = atoi(body.c_str());
            }
            return bounded(rulename, sym, lo, hi);
        }
        string n = aux_name(rulename);
        int32_t id = rid(n);
        if (op == '?') {
            rules[id] = {{sym}, {}};
        } else if (op == '*') {
            rules[id] = {{sym, Sym{REF, 0, id, -1}}, {}};
        } else {  // '+'
            string sn = aux_name(rulename);
            int32_t sid = rid(sn);
            rules[sid] = {{sym, Sym{REF, 0, sid, -1}}, {}};
            rules[id] = {{sym, Sym{REF, 0, sid, -1}}};
        }
        sym = Sym{REF, 0, id, -1};
        return true;
    }

    bool bounded(const string &rulename, Sym &sym, int lo, int hi) {
        string n = aux_name(rulename);
        int32_t id = rid(n);
        if (hi < 0) {
            string sn = aux_name(rulename);
            int32_t sid = rid(sn);
            rules[sid] = {{sym, Sym{REF, 0, sid, -1}}, {}};
            Alt alt(lo, sym);
            alt.push_back(Sym{REF, 0, sid, -1});
            rules[id] = {std::move(alt)};
        } else {
            for (int nrep = lo; nrep <= hi; nrep++)
                rules[id].push_back(Alt(nrep, sym));
            if (rules[id].empty()) rules[id].push_back({});
        }
        sym = Sym{REF, 0, id, -1};
        return true;
    }
};

// ---------------------------------------------------------- trie + engine

struct TrieNode {
    std::unordered_map<uint32_t, int32_t> children;  // char -> node idx
    vector<int32_t> token_ids;
};

// a stack is a vector of symbols still to match (front = top); stacks and
// states (sorted sets of stack ids) are interned so callers hold ints
struct Engine {
    vector<Rule> rules;
    vector<CharClass> classes;
    int32_t root = -1;

    vector<vector<Sym>> stacks;             // id -> stack
    std::unordered_map<string, int32_t> stack_ids;  // serialized -> id
    vector<vector<int32_t>> states;         // id -> sorted stack ids
    std::unordered_map<string, int32_t> state_ids;
    std::unordered_map<uint64_t, int32_t> accept_cache;  // (state, ch)

    vector<TrieNode> trie;
    vector<vector<uint32_t>> token_chars;   // token id -> code points
    int vocab_size = 0;
    vector<int32_t> eos_ids;

    string err;

    int32_t intern_stack(const vector<Sym> &st) {
        string key;
        key.reserve(st.size() * 9);
        for (auto &s : st) {
            key.append((const char *)&s.kind, 1);
            key.append((const char *)&s.ch, 4);
            key.append((const char *)&s.rule, 4);
            key.append((const char *)&s.cls, 4);
        }
        auto it = stack_ids.find(key);
        if (it != stack_ids.end()) return it->second;
        int32_t id = (int32_t)stacks.size();
        stacks.push_back(st);
        stack_ids[key] = id;
        return id;
    }

    int32_t intern_state(vector<int32_t> ids) {
        std::sort(ids.begin(), ids.end());
        ids.erase(std::unique(ids.begin(), ids.end()), ids.end());
        string key((const char *)ids.data(), ids.size() * 4);
        auto it = state_ids.find(key);
        if (it != state_ids.end()) return it->second;
        int32_t id = (int32_t)states.size();
        states.push_back(std::move(ids));
        state_ids[key] = id;
        return id;
    }

    // expand leading REFs until top is terminal or stack empty
    void expand(vector<Sym> stack, vector<int32_t> &out,
                std::unordered_set<string> &seen) {
        string key;
        key.reserve(stack.size() * 9);
        for (auto &s : stack) {
            key.append((const char *)&s.kind, 1);
            key.append((const char *)&s.ch, 4);
            key.append((const char *)&s.rule, 4);
            key.append((const char *)&s.cls, 4);
        }
        if (!seen.insert(key).second) return;
        if (stack.empty() || stack.front().kind != REF) {
            out.push_back(intern_stack(stack));
            return;
        }
        Sym ref = stack.front();
        vector<Sym> rest(stack.begin() + 1, stack.end());
        for (auto &alt : rules[ref.rule]) {
            vector<Sym> ns(alt);
            ns.insert(ns.end(), rest.begin(), rest.end());
            expand(std::move(ns), out, seen);
        }
    }

    int32_t initial_state() {
        vector<int32_t> out;
        std::unordered_set<string> seen;
        for (auto &alt : rules[root]) expand(alt, out, seen);
        return intern_state(std::move(out));
    }

    bool sym_matches(const Sym &s, uint32_t ch) const {
        if (s.kind == LIT) return s.ch == ch;
        if (s.kind == CLASS) return classes[s.cls].matches(ch);
        return false;
    }

    int32_t accept_char(int32_t state, uint32_t ch) {
        uint64_t key = ((uint64_t)state << 24) ^ ch;
        auto it = accept_cache.find(key);
        if (it != accept_cache.end()) return it->second;
        vector<int32_t> out;
        std::unordered_set<string> seen;
        for (int32_t sid : states[state]) {
            const auto &stack = stacks[sid];
            if (stack.empty()) continue;
            if (sym_matches(stack.front(), ch)) {
                vector<Sym> rest(stack.begin() + 1, stack.end());
                expand(std::move(rest), out, seen);
            }
        }
        int32_t res = intern_state(std::move(out));
        accept_cache[key] = res;
        return res;
    }

    bool is_dead(int32_t state) const { return states[state].empty(); }

    bool can_end(int32_t state) const {
        for (int32_t sid : states[state])
            if (stacks[sid].empty()) return true;
        return false;
    }

    int32_t advance_token(int32_t state, int32_t tok) {
        if (tok < 0 || tok >= (int)token_chars.size()) return state;
        for (uint32_t ch : token_chars[tok]) {
            if (is_dead(state)) return state;
            state = accept_char(state, ch);
        }
        return state;
    }

    // ------------------------------------------------------------- vocab

    void set_vocab(int n) {
        vocab_size = n;
        token_chars.assign(n, {});
        trie.clear();
        trie.emplace_back();
    }

    void add_token(int id, const char *utf8, int len) {
        if (id < 0 || id >= vocab_size || len <= 0) return;
        vector<uint32_t> chars;
        size_t i = 0;
        string s(utf8, len);
        while (i < s.size()) {
            unsigned char c = s[i++];
            uint32_t v;
            if (c < 0x80) v = c;
            else {
                int extra = (c >= 0xF0) ? 3 : (c >= 0xE0) ? 2 : 1;
                v = c & (0x3F >> extra);
                for (int k = 0; k < extra && i < s.size(); k++)
                    v = (v << 6) | (s[i++] & 0x3F);
            }
            chars.push_back(v);
        }
        token_chars[id] = chars;
        int32_t node = 0;
        for (uint32_t ch : chars) {
            auto it = trie[node].children.find(ch);
            if (it == trie[node].children.end()) {
                int32_t nxt = (int32_t)trie.size();
                trie[node].children[ch] = nxt;
                trie.emplace_back();
                node = nxt;
            } else node = it->second;
        }
        trie[node].token_ids.push_back(id);
    }

    void mask(int32_t state, uint8_t *out) {
        memset(out, 0, vocab_size);
        // DFS over the vocab trie, pruning rejected prefixes
        vector<std::pair<int32_t, int32_t>> stack = {{0, state}};
        while (!stack.empty()) {
            auto [node, st] = stack.back();
            stack.pop_back();
            for (int32_t tid : trie[node].token_ids) out[tid] = 1;
            for (auto &[ch, child] : trie[node].children) {
                int32_t nst = accept_char(st, ch);
                if (!is_dead(nst)) stack.push_back({child, nst});
            }
        }
        if (can_end(state))
            for (int32_t e : eos_ids)
                if (e >= 0 && e < vocab_size) out[e] = 1;
    }
};

}  // namespace

// ------------------------------------------------------------------ C ABI

extern "C" {

void *gbnf_new(const char *grammar_text, char *errbuf, int errlen) {
    Parser p;
    p.text = grammar_text;
    // pre-register nothing; parse builds rules
    if (!p.parse()) {
        if (errbuf && errlen > 0) {
            strncpy(errbuf, p.err.c_str(), errlen - 1);
            errbuf[errlen - 1] = 0;
        }
        return nullptr;
    }
    auto it = p.rule_ids.find("root");
    if (it == p.rule_ids.end()) {
        if (errbuf && errlen > 0)
            strncpy(errbuf, "grammar has no 'root' rule", errlen - 1);
        return nullptr;
    }
    // a rule that was referenced but never defined has zero alternates
    // (rid() auto-creates it empty); the Python engine raises KeyError for
    // this — surface the same error instead of a silently-dead grammar
    for (size_t r = 0; r < p.rules.size(); r++) {
        if (p.rules[r].empty()) {
            if (errbuf && errlen > 0) {
                std::string msg = "undefined rule '" + p.rule_names[r] + "'";
                strncpy(errbuf, msg.c_str(), errlen - 1);
                errbuf[errlen - 1] = 0;
            }
            return nullptr;
        }
    }
    auto *e = new Engine();
    e->rules = std::move(p.rules);
    e->classes = std::move(p.classes);
    e->root = it->second;
    return e;
}

void gbnf_free(void *h) { delete (Engine *)h; }

void gbnf_set_vocab(void *h, int vocab_size) {
    ((Engine *)h)->set_vocab(vocab_size);
}

void gbnf_add_token(void *h, int id, const char *utf8, int len) {
    ((Engine *)h)->add_token(id, utf8, len);
}

void gbnf_add_eos(void *h, int id) {
    ((Engine *)h)->eos_ids.push_back(id);
}

int gbnf_initial(void *h) { return ((Engine *)h)->initial_state(); }

int gbnf_advance(void *h, int state, int token) {
    return ((Engine *)h)->advance_token(state, token);
}

int gbnf_accept_text(void *h, int state, const char *utf8, int len) {
    auto *e = (Engine *)h;
    string s(utf8, len);
    size_t i = 0;
    while (i < s.size() && !e->is_dead(state)) {
        unsigned char c = s[i++];
        uint32_t v;
        if (c < 0x80) v = c;
        else {
            int extra = (c >= 0xF0) ? 3 : (c >= 0xE0) ? 2 : 1;
            v = c & (0x3F >> extra);
            for (int k = 0; k < extra && i < s.size(); k++)
                v = (v << 6) | (s[i++] & 0x3F);
        }
        state = e->accept_char(state, v);
    }
    return state;
}

int gbnf_can_end(void *h, int state) {
    return ((Engine *)h)->can_end(state) ? 1 : 0;
}

int gbnf_is_dead(void *h, int state) {
    return ((Engine *)h)->is_dead(state) ? 1 : 0;
}

void gbnf_mask(void *h, int state, uint8_t *out) {
    ((Engine *)h)->mask(state, out);
}

}  // extern "C"
