// In-memory vector store: contiguous f32 keys, exact-key index, cosine
// top-K (C ABI).
//
// Native counterpart of the reference's Go local-store worker
// (backend/go/stores/store.go:39-511 — StoresSet upsert :106, StoresGet
// :266, StoresFindNormalized :373 normalized fast path, top-K heap :349).
// Values stay on the Python side keyed by row id; this library owns the
// numeric hot path: key storage, dedup, deletion compaction, and the
// similarity scan (vectorized by the compiler at -O3 -march=native).
//
// Build: make -C localai_tfp_tpu/native   (produces build/libvecstore.so)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>
#include <algorithm>

namespace {

struct Store {
    int dim = 0;
    std::vector<float> keys;      // n * dim
    std::vector<float> norms;     // n
    std::unordered_map<std::string, int64_t> index;  // key bytes -> row
    bool normalized = true;

    int64_t rows() const { return dim ? (int64_t)norms.size() : 0; }

    std::string kb(const float *k) const {
        return std::string((const char *)k, dim * sizeof(float));
    }
};

}  // namespace

extern "C" {

void *vs_new(void) { return new Store(); }
void vs_free(void *h) { delete (Store *)h; }

int64_t vs_len(void *h) { return ((Store *)h)->rows(); }
int vs_dim(void *h) { return ((Store *)h)->dim; }
int vs_normalized(void *h) { return ((Store *)h)->normalized ? 1 : 0; }

// upsert n rows; out_rowids[n] receives each key's row id (existing row
// for duplicates — the caller stores values by row id). returns -1 on
// dim mismatch.
int64_t vs_set(void *h, const float *keys, int64_t n, int dim,
               int64_t *out_rowids) {
    auto *s = (Store *)h;
    if (s->dim == 0) s->dim = dim;
    if (dim != s->dim) return -1;
    for (int64_t i = 0; i < n; i++) {
        const float *k = keys + i * dim;
        auto key = s->kb(k);
        auto it = s->index.find(key);
        if (it != s->index.end()) {
            out_rowids[i] = it->second;
            continue;
        }
        int64_t row = s->rows();
        s->keys.insert(s->keys.end(), k, k + dim);
        double acc = 0;
        for (int d = 0; d < dim; d++) acc += (double)k[d] * k[d];
        float norm = (float)std::sqrt(acc);
        s->norms.push_back(norm);
        if (std::fabs(norm - 1.0f) > 1e-4f) s->normalized = false;
        s->index[std::move(key)] = row;
        out_rowids[i] = row;
    }
    return s->rows();
}

// exact-key lookups: out_rowids[i] = row or -1
void vs_get(void *h, const float *keys, int64_t n, int64_t *out_rowids) {
    auto *s = (Store *)h;
    for (int64_t i = 0; i < n; i++) {
        auto it = s->index.find(s->kb(keys + i * s->dim));
        out_rowids[i] = it == s->index.end() ? -1 : it->second;
    }
}

// delete rows by key; compacts storage. out_remap[old_row] = new_row or
// -1 for deleted (remap has vs_len entries BEFORE the call). returns
// number deleted.
int64_t vs_delete(void *h, const float *keys, int64_t n,
                  int64_t *out_remap) {
    auto *s = (Store *)h;
    int64_t old_n = s->rows();
    std::vector<char> drop(old_n, 0);
    int64_t dropped = 0;
    for (int64_t i = 0; i < n; i++) {
        auto it = s->index.find(s->kb(keys + i * s->dim));
        if (it != s->index.end() && !drop[it->second]) {
            drop[it->second] = 1;
            dropped++;
        }
    }
    if (!dropped) {
        for (int64_t r = 0; r < old_n; r++) out_remap[r] = r;
        return 0;
    }
    int64_t w = 0;
    for (int64_t r = 0; r < old_n; r++) {
        if (drop[r]) { out_remap[r] = -1; continue; }
        if (w != r) {
            memmove(s->keys.data() + w * s->dim,
                    s->keys.data() + r * s->dim, s->dim * sizeof(float));
            s->norms[w] = s->norms[r];
        }
        out_remap[r] = w++;
    }
    s->keys.resize(w * s->dim);
    s->norms.resize(w);
    s->index.clear();
    for (int64_t r = 0; r < w; r++)
        s->index[s->kb(s->keys.data() + r * s->dim)] = r;
    return dropped;
}

// cosine top-K: fills out_rows/out_sims (desc). returns count (<= topk).
int64_t vs_find(void *h, const float *query, int64_t topk,
                int64_t *out_rows, float *out_sims) {
    auto *s = (Store *)h;
    int64_t n = s->rows();
    if (!n) return 0;
    int dim = s->dim;
    double qacc = 0;
    for (int d = 0; d < dim; d++) qacc += (double)query[d] * query[d];
    float qn = (float)std::sqrt(qacc);

    std::vector<float> sims(n);
    const float *K = s->keys.data();
    for (int64_t r = 0; r < n; r++) {
        const float *k = K + r * dim;
        float dot = 0;
        for (int d = 0; d < dim; d++) dot += k[d] * query[d];
        sims[r] = s->normalized
            ? dot
            : dot / std::max(s->norms[r] * qn, 1e-12f);
    }
    int64_t k = std::min(topk, n);
    std::vector<int64_t> idx(n);
    for (int64_t r = 0; r < n; r++) idx[r] = r;
    std::partial_sort(idx.begin(), idx.begin() + k, idx.end(),
                      [&](int64_t a, int64_t b) { return sims[a] > sims[b]; });
    for (int64_t r = 0; r < k; r++) {
        out_rows[r] = idx[r];
        out_sims[r] = sims[idx[r]];
    }
    return k;
}

// copy a row's key out (for find results)
void vs_row_key(void *h, int64_t row, float *out) {
    auto *s = (Store *)h;
    memcpy(out, s->keys.data() + row * s->dim, s->dim * sizeof(float));
}

}  // extern "C"
