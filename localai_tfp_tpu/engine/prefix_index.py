"""Global cross-slot prefix index: a host-side radix trie over every
slot's KV-resident token prefix.

The engine's slot rows (``KVCache.k/v[:, slot]``) each hold the K/V of a
committed token prefix (``_Slot.cache_tokens``) — free slots keep their
last resident's prefix intact for reuse, and an ACTIVE slot's committed
prefix is immutable (decode/prefill writes always land at or beyond
``n_past``). This index makes that pool searchable across slots: an
admitted request asks "which slot holds the longest prefix of MY
prompt?", and the engine copies the matching rows on-device
(``kvcopy`` dispatch) instead of re-prefilling them — the host half of
RTP-LLM-style cross-request prefix caching on dense slot rows
(PAPERS.md; the Ragged Paged Attention paper is the block-granular
TPU-native endgame).

Structure: an edge-compressed radix trie. Each node's ``edge`` is a
numpy token array; ``slots`` is the set of slot indices whose
registered sequence covers the full path through that node. Edge
comparisons are vectorized (``np.argmin(a == b)`` shape, no per-token
Python loop), so walk cost is O(depth) numpy ops, not O(tokens).

The engine syncs the index LAZILY once per admission wave
(``sync()``) plus eagerly at the admission-path points that truncate a
slot's prefix mid-wave (``set_tokens``); decode-harvest appends and
window clamps are picked up by the next sync, which diffs the
registered sequence against the live one and extends in place when the
old registration is still a prefix (the common case — appends only).
"""

from __future__ import annotations

import time
from typing import Iterable, Optional

import numpy as np

__all__ = ["PrefixIndex", "common_prefix_len"]


def common_prefix_len(a, b) -> int:
    """Length of the shared token prefix of two sequences (lists or int
    arrays). Vectorized: elementwise compare + argmax instead of a
    per-token Python loop (this ran O(n_slots) per admission).
    ndarray inputs compare in ONE shot (~36x the loop at 4096 tokens);
    list inputs convert in 512-token blocks with early exit, so a long
    shared prefix pays block conversions (~4x the loop) while a
    first-token mismatch stays O(block) — see PR microbench."""
    n = min(len(a), len(b))
    if n == 0:
        return 0
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
        neq = a[:n] != b[:n]
        i = int(np.argmax(neq))  # first mismatch, or 0 when none
        return n if not neq[i] else i
    out = 0
    off, step = 0, 64  # geometric blocks: an early mismatch converts
    while off < n:     # O(64) elements, a deep match amortizes
        end = min(off + step, n)
        av = np.asarray(a[off:end], dtype=np.int64)
        bv = np.asarray(b[off:end], dtype=np.int64)
        neq = av != bv
        i = int(np.argmax(neq))
        if neq[i]:
            return off + i
        out = end
        off, step = end, min(step * 4, 4096)
    return out


class _Node:
    __slots__ = ("edge", "children", "slots")

    def __init__(self, edge: np.ndarray) -> None:
        self.edge = edge  # tokens on the edge INTO this node
        self.children: dict[int, _Node] = {}
        self.slots: set[int] = set()


class PrefixIndex:
    """Radix index over per-slot resident token prefixes.

    All methods are host-only and run on the scheduler thread; no
    internal locking. Registered sequences are snapshots (numpy
    copies), so callers may keep mutating their lists."""

    def __init__(self) -> None:
        self._root = _Node(np.empty(0, np.int64))
        self._seqs: dict[int, np.ndarray] = {}  # slot -> registered seq
        self._last_use: dict[int, float] = {}  # slot -> monotonic stamp
        # slot -> (edge fingerprint chain, prompt token length) — set at
        # assignment for HTTP-admitted requests (utils/fingerprint.py)
        self._chains: dict[int, tuple[tuple, int]] = {}
        # bumped on every content mutation; the engine uses it to skip
        # summary() rehashes when nothing changed and to force one
        # before the scheduler goes idle (gossip would otherwise miss
        # prefixes retained by requests shorter than the refresh
        # interval)
        self.revision = 0

    # ------------------------------------------------------------ register

    def set_tokens(self, slot: int, tokens, now: Optional[float] = None
                   ) -> None:
        """(Re-)register ``slot`` as holding exactly ``tokens``. Cheap
        when the old registration is a prefix of the new one (pure
        extension — membership along the existing path stays valid)."""
        seq = np.asarray(tokens, dtype=np.int64)
        old = self._seqs.get(slot)
        if old is not None:
            if len(old) == len(seq) and common_prefix_len(old, seq) == len(
                    seq):
                return  # unchanged
            if len(old) < len(seq) and common_prefix_len(old, seq) == len(
                    old):
                pass  # extension: insert walks the covered path again
            else:
                self._remove_path(slot, old)
        self.revision += 1
        self._seqs[slot] = seq
        self._last_use[slot] = time.monotonic() if now is None else now
        if len(seq):
            self._insert(slot, seq)

    def remove(self, slot: int) -> None:
        old = self._seqs.pop(slot, None)
        self._last_use.pop(slot, None)
        if self._chains.pop(slot, None) is not None or old is not None:
            self.revision += 1
        if old is not None:
            self._remove_path(slot, old)

    def set_chain(self, slot: int, chain, prompt_len: int) -> None:
        """Attach the HTTP-edge message-boundary fingerprint chain for
        the request resident in ``slot`` (see utils/fingerprint.py).
        ``prompt_len`` is the prompt's token length, used to convert
        the chain's canonical-byte offsets into token estimates in
        ``summary()``. An empty chain clears any prior registration
        (the slot falls back to token-bytes hashing)."""
        if chain and prompt_len > 0:
            self._chains[slot] = (tuple(chain), int(prompt_len))
            self.revision += 1
        elif self._chains.pop(slot, None) is not None:
            self.revision += 1

    def sync(self, slot_tokens: Iterable[tuple[int, list]]) -> None:
        """Diff-and-reregister every (slot, live_tokens) pair. Called
        once per admission wave; appends (decode harvests) extend in
        place, truncations (window clamps, releases) re-insert."""
        now = time.monotonic()
        seen = set()
        for slot, tokens in slot_tokens:
            seen.add(slot)
            self.set_tokens(slot, tokens, now=self._last_use.get(slot, now))
        for slot in [s for s in self._seqs if s not in seen]:
            self.remove(slot)

    def touch(self, slot: int, now: Optional[float] = None) -> None:
        """Refresh a slot's LRU stamp (reused as a copy donor, or newly
        assigned)."""
        if slot in self._seqs:
            self._last_use[slot] = time.monotonic() if now is None else now

    # --------------------------------------------------------------- query

    def match(self, tokens, exclude: frozenset = frozenset()
              ) -> tuple[int, set[int]]:
        """Longest registered prefix of ``tokens`` held by any slot not
        in ``exclude``. Returns (length, candidate slots); (0, set())
        when nothing matches."""
        seq = np.asarray(tokens, dtype=np.int64)
        n = len(seq)
        node = self._root
        i = 0
        best_len, best_slots = 0, set()
        while i < n:
            child = node.children.get(int(seq[i]))
            if child is None:
                break
            e = child.edge
            m = min(len(e), n - i)
            cp = common_prefix_len(e[:m], seq[i:i + m])
            cand = child.slots - exclude
            if cp > 0 and cand:
                # every slot registered through this node shares the
                # full edge, hence at least i+cp tokens with ``tokens``
                best_len, best_slots = i + cp, cand
            if cp < len(e):
                break
            node = child
            i += cp
        return best_len, best_slots

    def page_run(self, tokens, page_size: int,
                 exclude: frozenset = frozenset()
                 ) -> tuple[int, int, set[int]]:
        """The longest registered prefix of ``tokens`` expressed as a
        page run: (full_pages, tail_rows, donor slots). With the paged
        KV pool, admission takes ``full_pages`` by zero-copy reference
        share (refcount bump) and row-copies only the ``tail_rows``
        sub-page remainder — the split engine._maybe_prefix_copy and
        tools/profile_kv.py report."""
        n, donors = self.match(tokens, exclude)
        return n // page_size, n % page_size, donors

    def registered_len(self, slot: int) -> int:
        seq = self._seqs.get(slot)
        return 0 if seq is None else len(seq)

    def value(self, slot: int, now: Optional[float] = None) -> float:
        """Reuse value of a slot's resident prefix: LRU x length
        (longer and more recently useful prefixes are worth keeping; an
        empty or stale row is the cheapest victim)."""
        n = self.registered_len(slot)
        if n == 0:
            return 0.0
        now = time.monotonic() if now is None else now
        age = max(0.0, now - self._last_use.get(slot, 0.0))
        return n / (1.0 + age)

    def resident_tokens(self) -> int:
        """Total KV-resident (reusable) prefix tokens across all
        registered slots — free AND active."""
        return sum(len(s) for s in self._seqs.values())

    def summary(self, k: int = 16) -> tuple[tuple[str, int], ...]:
        """Top-k resident prefixes as (fingerprint, token count) pairs
        — the gossip payload for prefix-locality fleet routing
        (telemetry/digest.py). Slots admitted through the HTTP edge
        carry a message-boundary fingerprint chain registered via
        ``set_chain`` (utils/fingerprint.py); those emit one entry PER
        CHAIN BOUNDARY, the token count estimated by scaling the prompt
        token length by canonical-byte fraction and clamped to what is
        actually KV-resident. Because the chain is computed from raw
        request bytes, the federated balancer derives the SAME hashes
        from an incoming body without a tokenizer and matches them
        against these gossiped entries. Chainless slots (direct engine
        callers) fall back to a content hash over the canonical int64
        token bytes — stable across nodes, but only matchable by
        another engine. Scheduler-thread only, like every other method
        here."""
        import hashlib

        if k <= 0:
            return ()
        best: dict[str, int] = {}
        for slot, seq in self._seqs.items():
            resident = len(seq)
            if not resident:
                continue
            entry = self._chains.get(slot)
            if entry is not None:
                chain, prompt_len = entry
                total_b = chain[-1][1]
                if total_b > 0:
                    last = len(chain) - 1
                    for j, (h, cum_b) in enumerate(chain):
                        est = prompt_len if j == last else max(
                            1, (prompt_len * int(cum_b)) // total_b)
                        est = min(est, resident)
                        if est > best.get(h, 0):
                            best[h] = est
                    continue
            h = hashlib.blake2b(
                np.ascontiguousarray(seq, np.int64).tobytes(),
                digest_size=8).hexdigest()
            if resident > best.get(h, 0):
                best[h] = resident
        top = sorted(best.items(), key=lambda e: (-e[1], e[0]))[:k]
        return tuple((h, int(n)) for h, n in top)

    # ----------------------------------------------------------- internals

    def _insert(self, slot: int, seq: np.ndarray) -> None:
        node = self._root
        i, n = 0, len(seq)
        while i < n:
            first = int(seq[i])
            child = node.children.get(first)
            if child is None:
                leaf = _Node(seq[i:])
                leaf.slots.add(slot)
                node.children[first] = leaf
                return
            e = child.edge
            m = min(len(e), n - i)
            cp = common_prefix_len(e[:m], seq[i:i + m])
            if cp == len(e):
                child.slots.add(slot)
                node = child
                i += cp
                continue
            # split the edge at cp: mid inherits child's coverage
            mid = _Node(e[:cp])
            mid.slots = set(child.slots)
            mid.slots.add(slot)
            child.edge = e[cp:]
            mid.children[int(e[cp])] = child
            node.children[first] = mid
            if i + cp < n:
                tail = _Node(seq[i + cp:])
                tail.slots.add(slot)
                mid.children[int(seq[i + cp])] = tail
            return

    def _remove_path(self, slot: int, seq: np.ndarray) -> None:
        node = self._root
        i, n = 0, len(seq)
        path: list[tuple[_Node, int, _Node]] = []
        while i < n:
            child = node.children.get(int(seq[i]))
            if child is None or slot not in child.slots:
                break  # registration drift: nothing beyond here
            child.slots.discard(slot)
            path.append((node, int(seq[i]), child))
            node = child
            i += len(child.edge)
        for parent, key, child in reversed(path):
            if not child.slots and not child.children:
                del parent.children[key]
            elif len(child.children) == 1:
                # merge a redundant single-child chain back into one
                # edge when coverage became identical (keeps the trie
                # compact across many register/remove cycles)
                (only,) = child.children.values()
                if only.slots == child.slots:
                    child.edge = np.concatenate([child.edge, only.edge])
                    child.children = only.children
