"""Continuous-batching LLM serving engine (the TPU counterpart of the
reference's C++ llama.cpp engine).

Reference semantics mirrored (backend/cpp/llama/grpc-server.cpp):
- N slots share the device; each owns a row of the KV cache
  (`llama_client_slot` :188-385, `initialize()` :568-616).
- scheduler loop = `update_slots()` :1639-2075 — admit queued requests,
  chunked prompt prefill with common-prefix KV reuse (`common_part` :67,
  cache trim :1893), batched decode of all running slots, per-slot sampling
  + stop handling (`process_token` :1069-1160).
- context exhaustion ends the generation (LocalAI patch :1673-1683;
  context-shift intentionally disabled :2415).
- per-phase timings (`print_timings` :346-385) surfaced per request
  (backend.proto:163-164 timing_prompt_processing/timing_token_generation).

TPU-first re-design rather than translation:
- All shapes static: decode always dispatches [n_slots, 1]; prefill chunks
  are padded to a small set of buckets — the jit cache holds ≤ len(buckets)+1
  executables, so the hot loop never recompiles (SURVEY.md §7 hard part #1).
- Sampling state lives on device as arrays indexed by slot and the sampler
  fuses into the decode dispatch (ops/sampling.py).
- KV cache rows are donated through jit every step (no reallocation).
- Inactive slots still flow through the batched decode but write their K/V
  at their own row's tail position, so a free slot's cached prefix stays
  intact for prefix reuse.
- Prefix reuse is GLOBAL, not per-slot: a radix index over every slot's
  resident prefix (engine/prefix_index.py) plus an on-device row-to-row
  KV copy dispatch ("kvcopy") let an admitted request start from the
  best matching prefix held by ANY slot — free or active — with
  prefix-aware wave admission and LRU x length victim selection
  (see the README "Serving: cross-slot prefix KV cache" section).
- Prefill and decode are NOT mutually exclusive: when both coexist, a
  fused token-budgeted "mixed" dispatch advances prefill chunks and
  decode rows in the SAME identity-batch device step (the ragged-batch
  discipline of RTP-LLM / Ragged Paged Attention, PAPERS.md), so an
  admission wave never stalls active streams. Escape hatch:
  LOCALAI_MIXED_DISPATCH=off restores the legacy alternating scheduler
  (see the README "Scheduling" section).
- Paged engines serve every row kind — decode rows, prefill chunks,
  prefill finals, spec-decode verify rows — through ONE ragged paged
  attention path (ops/ragged_paged_attention.py): page tables ride
  dispatches at FULL width, so the jit cache holds one variant per
  token-budget shape (no bucket x window ladder) and kernel-eligible
  engines never materialize a gathered KV window on the prefill/mixed
  hot path. LOCALAI_RAGGED_ATTN=off restores the legacy windowed
  paths byte-identically (see the README "Kernels" section).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import knobs
from ..models.llm_spec import LLMSpec
from ..models.transformer import (
    KVCache, Params, forward, forward_hidden, gather_kv_pages,
    scatter_kv_pages,
)
from ..ops.sampling import (
    SamplingState, observe_tokens, sample, seed_windows,
)
from ..telemetry import costmodel, hbm_ledger
from ..telemetry import metrics as tm
from ..telemetry.flightrec import FLIGHT
from ..telemetry.tracing import TRACER, fault_scope
from ..utils import faultinject
from .kv_pool import TRASH_PAGE, PagePool, PagePoolExhausted
from .prefix_index import PrefixIndex, common_prefix_len
from .tokenizer import StreamDecoder, Tokenizer

log = logging.getLogger(__name__)

# Padded-prefill size ladder. The 4-bucket exists for the prefix-reuse
# fast path: a warm request re-processes only its last token(s), and at
# a 64-deep admission wave the difference between padding those rows to
# 32 columns vs 4 is ~2048 vs ~256 dead token-positions of 8B forward —
# measured ~400 ms vs ~30 ms on v5e, the difference between missing and
# making a <200 ms TTFT.
DEFAULT_PREFILL_BUCKETS = (4, 16, 128, 512, 2048)


@dataclass
class GenRequest:
    """One generation request (ref: backend.proto PredictOptions surface)."""

    prompt_ids: list[int]
    max_tokens: int = 128
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    repeat_penalty: float = 0.0
    repeat_last_n: int = 64
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    typical_p: float = 1.0  # locally typical sampling (>=1 disabled)
    mirostat: int = 0  # 0 off | 1 v1 | 2 v2 (ref: grpc-server.cpp:708)
    mirostat_tau: float = 5.0
    mirostat_eta: float = 0.1
    seed: Optional[int] = None
    stop: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    logit_bias: Optional[dict[int, float]] = None
    # grammar-constrained decoding: object with next_mask(state)->np.bool_[V]
    # and advance(state, token)->state (see grammars/constrain.py)
    constraint: Optional[Any] = None
    # on-disk prompt cache (ref: backend.proto:135-141 PromptCachePath/
    # PromptCacheAll/PromptCacheRO — llama.cpp prompt state save/restore)
    prompt_cache_path: str = ""
    prompt_cache_all: bool = False
    prompt_cache_ro: bool = False
    correlation_id: str = ""
    # multimodal soft tokens (ref: llava mmproj embedding path,
    # grpc-server.cpp:1476-1502): precomputed embeddings [N, d_model] f32
    # replacing the prompt tokens at soft_positions (absolute indices into
    # prompt_ids — usually the <image_soft_token> runs)
    soft_embeds: Optional[np.ndarray] = None
    soft_positions: Optional[np.ndarray] = None
    id: str = field(default_factory=lambda: uuid.uuid4().hex)
    # distributed trace id (32 hex, telemetry/tracing.py): adopted from
    # the request's trace at submit so dispatch records can carry it to
    # multihost followers without a recorder lookup per dispatch
    trace_id: str = ""
    t_submit: float = 0.0  # perf_counter at submit (queue-wait/TTFT
    # attribution; set by submit_many, 0 for directly-assigned tests)
    # request deadline: client-supplied budget in seconds (0 = use the
    # engine's LOCALAI_REQUEST_DEADLINE_S default, which may itself be
    # 0 = no deadline). submit_many converts it to the absolute
    # `deadline` (perf_counter clock); _apply_deadlines enforces it
    # while queued AND while decoding
    timeout_s: float = 0.0
    deadline: float = 0.0
    # HTTP-edge message-boundary fingerprint chain
    # (utils/fingerprint.py): (hash_hex, cum_canonical_bytes) pairs
    # registered with the prefix index at slot assignment so digest
    # gossip advertises hashes the federated balancer can recompute
    # from a raw request body without a tokenizer
    prefix_chain: tuple = ()
    # disaggregated serving handoff (engine/kv_migrate.KVHandoff): set
    # by the DisaggRouter on the decode-engine resubmit of a request
    # whose prompt was prefilled on the prefill engine. _admit adopts
    # the migrated pages instead of prefilling, and submit_many
    # preserves the ORIGINAL t_submit/deadline it carries so TTFT and
    # deadline enforcement stay end-to-end. Host-only — never rides a
    # dispatch payload.
    disagg: Optional[Any] = None


class _PadReq:
    """Neutral sampler params for prefill-group pad rows (their state
    writes target the out-of-bounds sentinel slot and are dropped)."""

    temperature = 0.0
    top_k = 0
    top_p = 1.0
    min_p = 0.0
    repeat_penalty = 0.0
    frequency_penalty = 0.0
    presence_penalty = 0.0
    repeat_last_n = 0
    typical_p = 1.0
    mirostat = 0
    mirostat_tau = 5.0
    mirostat_eta = 0.1
    seed = None


@dataclass
class StreamEvent:
    """Streamed to the caller per emitted text span; final carries stats."""

    text: str = ""
    token_id: Optional[int] = None
    done: bool = False
    finish_reason: str = ""  # stop | length | error
    error: str = ""
    full_text: str = ""
    prompt_tokens: int = 0
    completion_tokens: int = 0
    timing_prompt_processing_ms: float = 0.0
    timing_token_generation_ms: float = 0.0
    # request-lifecycle attribution (Extra-Usage surface): time queued
    # before admission, and submit-to-first-token latency
    timing_queue_ms: float = 0.0
    timing_first_token_ms: float = 0.0
    # prefill phase split: timing_prompt_processing_ms is DEVICE time
    # attributed at harvest of the covering flight(s); this is the
    # host-side enqueue component (payload build + dispatch call),
    # which used to be miscounted as prompt processing for chunked
    # prompts
    timing_prefill_enqueue_ms: float = 0.0
    # load-shed hint: suggested client backoff in seconds, set only on
    # finish_reason="shed" events (the HTTP layer maps it to a 429
    # Retry-After header)
    retry_after_s: float = 0.0


class SlotState(Enum):
    FREE = 0
    PREFILL = 1
    DECODE = 2
    # final prompt chunk dispatched; first sampled token still on device.
    # The slot joins decode scans once its prefill flight harvests.
    PENDING_FIRST = 3


@dataclass
class _Flight:
    """An in-flight device dispatch whose host-visible results are still
    pending. The scheduler enqueues dispatches without blocking (device
    queue time — hundreds of ms of scan work at serving shapes —
    pipelines behind host work) and harvests results in FIFO order —
    device execution is serialized by the donated cache/sampling
    buffers, so flight N's arrays are always ready no later than flight
    N+1's."""

    kind: str  # "prefill_final" | "decodek"
    arrays: list  # device arrays to harvest (copy_to_host_async started)
    meta: dict
    t_enqueue: float

    def ready(self) -> bool:
        return all(a.is_ready() for a in self.arrays)


@dataclass
class _Slot:
    idx: int
    state: SlotState = SlotState.FREE
    request: Optional[GenRequest] = None
    out: Optional[queue.SimpleQueue] = None
    cache_tokens: list[int] = field(default_factory=list)  # KV-resident ids
    n_past: int = 0  # valid prefix length in this slot's cache row
    n_prompt: int = 0
    generated: list[int] = field(default_factory=list)
    decoder: Optional[StreamDecoder] = None
    pending_text: str = ""  # withheld tail that may begin a stop string
    emit_buf: list[str] = field(default_factory=list)  # deferred text
    # spans coalesced into ONE stream event per harvest (a k=16 scan
    # over 64 slots otherwise wakes the consumers 1024 times)
    emit_tok: Optional[int] = None  # first token id of the buffered span
    constraint_state: Any = None
    cache_loaded: Any = None  # (path, n) the on-disk prompt cache holds
    n_reused: int = 0  # prompt tokens served from resident/copied KV
    # instead of prefill (set at _assign; read at prefill harvest)
    t_start: float = 0.0
    t_first: float = 0.0  # perf_counter at first emitted token
    t_prefill_ms: float = 0.0  # DEVICE prefill time, attributed at
    # harvest of the covering flight(s) — enqueue-only host time must
    # not land here (it made chunked prompts report near-zero prefill)
    t_prefill_enq_ms: float = 0.0  # host-side prefill enqueue time
    t_prefill_t0: float = 0.0  # perf_counter at the slot's FIRST
    # prefill dispatch; the covering flight's harvest attributes
    # (harvest - t0) as device+queue prefill time, so chunk dispatches
    # enqueued in earlier iterations are not lost
    t_decode_ms: float = 0.0
    t_last: float = 0.0

    @property
    def active(self) -> bool:
        return self.state is not SlotState.FREE


@dataclass
class EngineMetrics:
    """ref: backend.proto MetricsResponse / llama_metrics grpc-server.cpp
    :387-417."""

    requests_completed: int = 0
    tokens_generated: int = 0
    prompt_tokens_processed: int = 0
    tokens_per_second: float = 0.0
    prompt_tokens_per_second: float = 0.0
    slots_busy: int = 0
    spec_tokens: int = 0  # tokens emitted via speculative decoding
    spec_dispatches: int = 0
    # cross-slot prefix cache: tokens served from KV-resident prefixes
    # (same-slot resident, cross-slot copy, or disk restore) vs tokens
    # actually pushed through prefill dispatches
    prefix_reused_tokens: int = 0
    prefill_tokens: int = 0
    prefix_copies: int = 0  # kvcopy dispatches enqueued


def _soft_expand(tokens: jax.Array, rows: jax.Array, brow: jax.Array,
                 bpos: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Inside jit: compact multimodal rows -> the dense (embeds [B,T,D],
    mask [B,T]) override forward() consumes. Padding entries carry an
    out-of-range batch row and are dropped by the scatter, so the host
    ships only R×D real bytes instead of a B×T×D zero sea."""
    B, T = tokens.shape
    emb = jnp.zeros((B, T, rows.shape[-1]), rows.dtype)
    emb = emb.at[brow, bpos].set(rows, mode="drop")
    mask = jnp.zeros((B, T), bool).at[brow, bpos].set(True, mode="drop")
    return emb, mask


def _pack_masks(masks: Optional[np.ndarray]) -> Optional[dict]:
    """[B, V] bool → bit-packed record payload (multihost dispatch records
    must stay small; a dense 256k-vocab mask is 8x the packed size)."""
    if masks is None:
        return None
    return {"bits": np.packbits(masks, axis=1), "v": masks.shape[1]}


def _unpack_masks(p) -> Optional[jax.Array]:
    """Accepts None, a raw [B, V] bool array (solo mode, no wire), or a
    bit-packed record from _pack_masks (multihost replay)."""
    if p is None:
        return None
    if isinstance(p, dict):
        return jnp.asarray(
            np.unpackbits(p["bits"], axis=1, count=p["v"]).astype(bool)
        )
    return jnp.asarray(p)


# vectorized common-prefix (one elementwise compare + argmax instead of
# a per-token Python loop — this ran O(n_slots) times per admission);
# kept as the radix-index fallback and for the on-disk cache path
_common_prefix = common_prefix_len


def _sel_active(active, new, old):
    """Select new vs old leaves per slot (keeps inactive slots' state)."""
    if new.ndim == 0:
        return new
    a = active
    while a.ndim < new.ndim:
        a = a[..., None]
    return jnp.where(a, new, old)


def _window_cache(cache: KVCache, window: int):
    """Slice the cache to its first ``window`` positions; returns the
    windowed view and a restore fn writing it back into the full buffer.
    Per-dispatch windowing keeps attention/write traffic proportional to
    the live-context bucket, not max_seq (the XLA stand-in for ragged
    paged attention)."""
    L, S, SEQ, F = cache.k.shape
    if window >= SEQ:
        return cache, lambda c: c
    win = KVCache(
        k=lax.slice(cache.k, (0, 0, 0, 0), (L, S, window, F)),
        v=lax.slice(cache.v, (0, 0, 0, 0), (L, S, window, F)),
        k_scale=(lax.slice(cache.k_scale, (0, 0, 0), (L, S, window))
                 if cache.quantized else None),
        v_scale=(lax.slice(cache.v_scale, (0, 0, 0), (L, S, window))
                 if cache.quantized else None),
    )

    def restore(c: KVCache) -> KVCache:
        return KVCache(
            k=lax.dynamic_update_slice(cache.k, c.k, (0, 0, 0, 0)),
            v=lax.dynamic_update_slice(cache.v, c.v, (0, 0, 0, 0)),
            k_scale=(lax.dynamic_update_slice(
                cache.k_scale, c.k_scale, (0, 0, 0))
                if cache.quantized else None),
            v_scale=(lax.dynamic_update_slice(
                cache.v_scale, c.v_scale, (0, 0, 0))
                if cache.quantized else None),
        )

    return win, restore


def _pin_win_sharding(win: KVCache, mesh, batch: bool) -> KVCache:
    """Constrain a gathered window view [L, B, W, F] on a mesh. With
    ``batch`` True the slot dim rides "data" and F rides "model" — the
    DENSE cache's exact layout, which is the only window placement
    whose jitted forward is numerically correct on a data x model mesh:
    with the slot dim replicated (F-sharded or fully replicated alike),
    GSPMD picks a partitioning for the fused gather -> forward ->
    scatter program that computes O(1)-wrong hidden states and KV
    writes (jit vs eager diverges on the written pages). With ``batch``
    False the window is pinned back to the ARENA's layout (slot dim
    replicated, F over "model") so the writeback scatter sees updates
    shaped like its data-replicated operand. Scale planes are global
    per-row amax, replicated either way."""
    from jax.sharding import NamedSharding

    from ..parallel.sharding import (
        KV_CACHE_SPEC, PAGED_KV_SPEC, REPLICATED, _divisible_spec,
    )

    row_sp = KV_CACHE_SPEC if batch else PAGED_KV_SPEC
    plane_sp = REPLICATED

    def pin(a, sp):
        sp = _divisible_spec(a.shape, sp, mesh)
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, sp))

    return KVCache(
        k=pin(win.k, row_sp), v=pin(win.v, row_sp),
        k_scale=pin(win.k_scale, plane_sp) if win.quantized else None,
        v_scale=pin(win.v_scale, plane_sp) if win.quantized else None,
    )


def _sample_masked(sampling, slot_ids, logits, active, masks):
    toks, new_sampling = sample(sampling, slot_ids, logits, mask=masks)
    merged = jax.tree_util.tree_map(
        lambda new, old: _sel_active(active, new, old), new_sampling, sampling
    )
    return jnp.where(active, toks, 0), merged


class LLMEngine:
    """Continuous-batching engine over one jitted model."""

    def __init__(
        self,
        spec: LLMSpec,
        params: Params,
        tokenizer: Tokenizer,
        *,
        n_slots: int = 8,
        max_seq: int = 4096,
        prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
        cache_dtype: Any = jnp.bfloat16,
        penalty_window: int = 256,
        decode_steps: int = 8,
        mesh: Any = None,  # jax.sharding.Mesh: TP/DP serving (the GSPMD
        # counterpart of tensor_split / tensor_parallel_size — SURVEY §2.5)
        draft: Optional[tuple[LLMSpec, Params]] = None,  # speculative
        # decoding draft model (ref: proto DraftModel/NDraft plumbing)
        n_draft: int = 4,
        latency_target_ms: Optional[float] = None,  # open-capacity
        # latency/throughput knob: bound in-flight decode device-time to
        # this budget whenever a slot is free, so an unpredicted
        # arrival's prefill queues behind at most ~one short scan.
        # None = balanced (scans stay long enough to cover the dispatch
        # RTT; see _latency_k)
        autostart: bool = True,
        kv_pages: Optional[int] = None,  # paged KV pool size (data
        # pages). None: LOCALAI_KV_PAGES env, else full worst-case
        # capacity (n_slots * max_seq / page — no memory saving, no
        # admission failure). Sizing it below worst case is the paged
        # pool's point: HBM follows EXPECTED context, so n_slots can
        # grow past what a dense cache of the same budget allows.
        channel: Any = None,  # multihost dispatch publisher (leader side);
        # every device dispatch is published as a (kind, payload) record
        # before executing so follower hosts replay the identical SPMD
        # program (parallel/multihost.py, SURVEY.md §7 hard part #5)
        follower: bool = False,  # replay-only engine: no scheduler thread,
        # device ops arrive via _dev_exec from the follower loop
        tag: str = "",  # model tag routing this engine's records when
        # several models publish on one channel
        state_dir: Optional[str] = None,  # where OOM post-mortems and
        # profiler captures land (None: $STATE_DIR, else ./run)
        kv_tier: Optional[bool] = None,  # tiered KV memory override:
        # None follows LOCALAI_KV_TIER; the disaggregated prefill
        # engine passes False (its slots live one prompt each — the
        # migration interchange replaces warm-tier churn there)
        weight_paging: Optional[bool] = None,  # layer-granular weight
        # paging override: None follows LOCALAI_WEIGHT_PAGING; disagg
        # workers pass False (prefill/decode engines share one tree by
        # reference — paging either side would strand the other)
    ) -> None:
        self.channel = channel
        self.follower = follower
        self.tag = tag
        # Prometheus model label: the serving tag, or a stable fallback
        # for directly-constructed engines (tests/bench)
        self._mlabel = tag or "default"
        if follower:
            autostart = False
        self.decode_steps = max(1, decode_steps)
        self.latency_target_ms = latency_target_ms
        self.mesh = mesh
        self.draft = draft
        self.n_draft = max(2, n_draft)
        self._autostart = autostart
        self.spec = spec
        self.params = params
        self.tokenizer = tokenizer
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_seq
        ) or (max_seq,)

        # Paged KV pool (engine/kv_pool.py + models/transformer.py
        # gather/scatter views): one [L, n_pages, page, F] arena backs
        # every slot through host-owned page tables, so HBM scales with
        # live tokens and prefix pages share by reference. Dispatches
        # carry the tables as plain index arrays (multihost-replayable).
        # LOCALAI_PAGED_KV=off restores the dense per-slot cache.
        # Meshed serving pages too: the arena has no slot dim, so it
        # shards its head-flat F dim over "model"
        # (parallel/sharding.PAGED_KV_SPEC — each device holds its
        # kv-head slice of EVERY page) while the host-owned page tables
        # stay global. One meshed carve-out stays dense: seq-sharded
        # meshes (the paged prefill path has no ring-attention branch).
        # kv_dim not dividing the tp axis is a CONFIG ERROR, not a
        # fallback: shard_engine_state raises for dense and paged alike
        # (silent replication is a tp-times HBM regression), so such a
        # mesh fails engine construction with the actionable message.
        mesh_seq = 1 if mesh is None else mesh.shape.get("seq", 1)
        mesh_tp = 1 if mesh is None else mesh.shape.get("model", 1)
        self._paged = (
            (mesh is None or mesh_seq == 1)
            and knobs.flag("LOCALAI_PAGED_KV"))
        # page size: largest power of two <= min(256, max_seq) dividing
        # max_seq, so every window bucket (powers of two >= 256, capped
        # at max_seq) is page-aligned; LOCALAI_KV_PAGE overrides within
        # the same constraints. 256 matches the fused decode kernel's
        # native DMA granularity.
        page_cap = min(256, max_seq)
        pg = 1
        while pg * 2 <= page_cap and max_seq % (pg * 2) == 0:
            pg *= 2
        want_pg = knobs.int_("LOCALAI_KV_PAGE")
        if (want_pg >= 8 and want_pg <= page_cap
                and max_seq % want_pg == 0
                and want_pg & (want_pg - 1) == 0):
            pg = want_pg
        self._page = pg
        if pg < 8:  # degenerate geometry (tiny/odd max_seq): dense
            self._paged = False
        if self._paged:
            self._max_pages = max_seq // pg  # logical pages per slot
            pages_default = n_slots * self._max_pages + 1  # + trash
            self.kv_pages = max(2, int(
                kv_pages or knobs.int_("LOCALAI_KV_PAGES")
                or pages_default))
            self._pool = PagePool(self.kv_pages, pg)
            self.cache = KVCache.create(spec, self.kv_pages, pg,
                                        cache_dtype)
            self.draft_cache = (
                KVCache.create(draft[0], self.kv_pages, pg, cache_dtype)
                if draft is not None else None
            )
        else:
            self.kv_pages = 0
            self._pool = None
            self.cache = KVCache.create(spec, n_slots, max_seq,
                                        cache_dtype)
            self.draft_cache = (
                KVCache.create(draft[0], n_slots, max_seq, cache_dtype)
                if draft is not None else None
            )
        # Ragged paged attention (ops/ragged_paged_attention.py): every
        # dispatch kind — decode scans, prefill chunks, prefill finals,
        # mixed steps, spec-decode verify — pins its page tables to
        # FULL table width (max_seq // page entries), so the jit cache
        # holds ONE variant per token-budget shape instead of the
        # bucket x window ladder, and kernel-eligible engines route
        # every row kind through the ONE ragged Pallas kernel (no
        # materialized gather_kv_pages window on the prefill/mixed hot
        # path). CPU/meshed/ineligible engines keep the XLA
        # gather/scatter fallback at full width — same values, still
        # one variant per shape. LOCALAI_RAGGED_ATTN=off restores the
        # legacy windowed paths byte-identically.
        self._ragged = self._paged and knobs.flag(
            "LOCALAI_RAGGED_ATTN")
        self.warmup_variants = 0  # dispatch variants precompiled by the
        # last completed warmup() pass (engine_dispatch_compile_variants
        # gauge; 0 until warmup runs or when it was marker-skipped)
        self._alloc_sync: dict[str, int] = {}  # pool alloc counters
        # already exported to engine_kv_page_alloc_total
        self.sampling = SamplingState.create(
            n_slots, spec.vocab_size, window=penalty_window
        )
        if mesh is not None:
            from ..models import quant
            from ..parallel.sharding import shard_engine_state, shard_params

            # GSPMD cannot partition the fused int8 pallas call; meshed
            # serving takes the XLA dequant path (models/quant.py)
            quant.set_meshed_serving(True)
            self.params = shard_params(self.params, mesh)
            self.cache, self.sampling = shard_engine_state(
                self.cache, self.sampling, mesh, paged=self._paged
            )
            if self._paged and self.draft_cache is not None:
                # the draft arena shares the pool's geometry/tables, so
                # it shards the same way; a non-divisible draft kv_dim
                # is device_put REPLICATED on the mesh — explicitly, so
                # a multi-GB operand never reaches the first dispatch
                # with an uncommitted single-device placement for GSPMD
                # to guess at (the spec paths then run the GSPMD gather
                # fallback — _kernel_eligible gates the shard_map route
                # on draft eligibility)
                from ..parallel.sharding import PAGED_KV_SPEC, REPLICATED
                from jax.sharding import NamedSharding

                arena_sp = (PAGED_KV_SPEC
                            if draft[0].kv_dim % mesh_tp == 0
                            else REPLICATED)

                def _put_arena(arr, sp):
                    return jax.device_put(arr, NamedSharding(mesh, sp))

                dc = self.draft_cache
                self.draft_cache = type(dc)(
                    k=_put_arena(dc.k, arena_sp),
                    v=_put_arena(dc.v, arena_sp),
                    k_scale=(_put_arena(dc.k_scale, REPLICATED)
                             if dc.quantized else None),
                    v_scale=(_put_arena(dc.v_scale, REPLICATED)
                             if dc.quantized else None),
                )
        self.slots = [_Slot(i) for i in range(n_slots)]
        self._use_kernel = self._kernel_eligible()
        # the replica's tensor-parallel footprint on /metrics: how many
        # devices this engine's dispatches fan out over (1 unsharded)
        tm.ENGINE_MESH_DEVICES.labels(model=self._mlabel).set(
            1 if mesh is None else int(mesh.devices.size))
        # cross-slot prefix cache: radix index over every slot's
        # resident cache_tokens + on-device row-to-row KV copies
        # (engine/prefix_index.py). LOCALAI_PREFIX_CACHE=off restores
        # the old own-slot-only reuse.
        self._prefix_enabled = knobs.flag("LOCALAI_PREFIX_CACHE")
        # minimum token GAIN over the destination's own resident prefix
        # before a copy is worth dispatching (a copy is a sub-ms HBM
        # move, so the floor is low)
        self._prefix_min_copy = max(
            1, knobs.int_("LOCALAI_PREFIX_CACHE_MIN"))
        # minimum SHARED-prefix length before a same-wave request
        # defers behind a wave-mate's prefill: deferral delays the
        # sharer's TTFT by a scheduler iteration and splits the wave's
        # prefill group, so it must buy substantially more than the
        # ~6-token chat-template prefix every request shares
        self._prefix_defer_min = max(
            self._prefix_min_copy,
            knobs.int_("LOCALAI_PREFIX_CACHE_DEFER_MIN"))
        # stall-free mixed prefill+decode dispatch: ONE fused identity-
        # batch device step advances prefill chunks AND decode rows, so
        # an admission wave never serializes against active streams
        # (the legacy scheduler's _prefill_hold/_dispatch_decode sleep
        # holds). LOCALAI_MIXED_DISPATCH=off restores the legacy
        # alternating-phase scheduler (the escape hatch). Forced off
        # when no prefill bucket fits the identity-batch token budget.
        self._mixed = knobs.flag("LOCALAI_MIXED_DISPATCH")
        # token budget per fused prefill/mixed dispatch: the XLA
        # prefill attention materializes [B, H, T, window] f32 scores,
        # so B*bucket must stay bounded or big-bucket groups OOM at
        # compile (measured: a 64x2048 group at 1B/2048-ctx needs
        # 34 GB of scores on a 16 GB chip). Read once at construction:
        # the warmup variant set is sized from it, so a mid-life
        # change would dispatch never-warmed shapes.
        self._prefill_group_tokens = max(
            1, knobs.int_("LOCALAI_PREFILL_GROUP_TOKENS"))
        if not any(b * n_slots <= self._prefill_group_tokens
                   for b in self.prefill_buckets):
            self._mixed = False
        self._prefix_index = PrefixIndex()
        # fleet-digest prefix gossip: top-k (hash, tokens) summary,
        # recomputed on the scheduler thread ~1/s (the index has no
        # locking) and swapped in atomically for any-thread readers
        self._prefix_summary: tuple = ()
        self._prefix_summary_t = 0.0
        self._prefix_summary_rev = -1  # index revision last summarized
        # same-wave prefix grouping: request id -> (deadline, want_len)
        # for admissions deferred one scheduler iteration so a
        # wave-mate's prefill commits the shared prefix they copy from
        self._deferred: dict[str, tuple[float, int]] = {}
        self._pending: list[tuple[GenRequest, queue.SimpleQueue]] = []  # lint: guarded-by self._lock
        self._cancelled: dict[str, float] = {}  # lint: guarded-by self._lock
        # request lifecycle guards. Both knobs default OFF so the
        # unset path is byte-identical to the unguarded engine:
        # - LOCALAI_REQUEST_DEADLINE_S: default per-request deadline
        #   (seconds; a request's own timeout_s overrides)
        # - LOCALAI_MAX_QUEUE: admission queue cap — submit_many sheds
        #   beyond it with an immediate terminal "shed" event instead
        #   of queueing unbounded latency
        self._default_deadline_s = max(
            0.0, knobs.float_("LOCALAI_REQUEST_DEADLINE_S"))
        self.max_queue = max(0, knobs.int_("LOCALAI_MAX_QUEUE"))
        # sticky arm: flips on the first request that carries any
        # deadline, so deadline-free serving never pays the sweep
        self._deadlines_armed = self._default_deadline_s > 0
        # recent admission queue waits (seconds) — the live sample the
        # shed path turns into a Retry-After hint
        self._queue_waits: deque[float] = deque(maxlen=64)  # lint: guarded-by self._lock
        self._lock = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.metrics = EngineMetrics()
        self._all_slot_ids = jnp.arange(n_slots, dtype=jnp.int32)
        # Tiered KV memory (engine/kv_tier.py): hot HBM pages, warm
        # host-RAM pages (async spill + prefetch), cold on-disk
        # sessions in the prompt-cache format — resident sessions
        # become bound by host RAM instead of the arena. Single-chip
        # paged engines only: multihost/follower engines and
        # draft-model pairs force it off (spilled main-model pages
        # would strand the draft cache), and LOCALAI_KV_TIER=off
        # restores today's behavior byte-identically everywhere.
        self._tier = None
        # meshed engines force tiering off until spill learns to gather
        # the model-sharded arena (a host copy of a PAGED_KV_SPEC page
        # would be an implicit cross-shard all-gather per spill)
        if (self._paged and channel is None and not follower
                and draft is None and mesh is None
                and (knobs.flag("LOCALAI_KV_TIER") if kv_tier is None
                     else kv_tier)):
            from .kv_tier import KVTierManager

            self._tier = KVTierManager(self)
        # layer-granular weight paging (engine/weight_pager.py): the
        # parameter tree can leave the chip for a host-RAM warm mirror
        # while the engine (slots, KV, dispatch cache, tokenizer) stays
        # up, and streams back layer-by-layer ahead of first token.
        # Single-chip engines only: meshed trees don't round-trip
        # through one host mirror, follower/channel engines replay a
        # leader whose tree must stay put, and a draft pair would
        # strand its second tree. LOCALAI_WEIGHT_PAGING=off restores
        # the fully-resident path byte-identically (the pager never
        # touches eng.params while hot).
        self._pager = None
        if (channel is None and not follower and draft is None
                and mesh is None
                and (knobs.flag("LOCALAI_WEIGHT_PAGING")
                     if weight_paging is None else weight_paging)):
            from .weight_pager import WeightPager

            self._pager = WeightPager(self)
        # disaggregated serving hooks (engine/kv_migrate.Migrator): the
        # DisaggRouter attaches one per engine before start() — prefill
        # side captures finished slots' pages into the migration bus,
        # decode side adopts them at admission. None = no hooks, the
        # single-engine path byte-identical.
        self._migrator = None
        # stage label for active-slot deadline expiry: "decode" for a
        # normal engine, "prefill" for the disaggregated prefill engine
        # (its active slots are running prompts, not streams)
        self._deadline_stage = "decode"

        if self._paged:
            _page = self._page

            @partial(jax.jit, donate_argnums=(2, 5))
            def _decode(params, tokens, cache, pos0, slot_ids, sampling,
                        active, masks, phys, wb):
                if self._use_kernel and self._ragged:
                    # unified ragged kernel: q_len 1 per row, writes
                    # routed through wb (parked rows append to trash
                    # instead of their own tail pages)
                    logits, cache = forward(
                        spec, params, tokens, pos0, cache, None,
                        mesh=self.mesh,
                        page_table=phys, kv_page=_page,
                        q_lens=jnp.ones(tokens.shape[:1], jnp.int32),
                        write_table=wb,
                    )
                elif self._use_kernel:
                    # arena + page table straight into the fused kernel
                    # (the append routes through the table in-graph)
                    logits, cache = forward(
                        spec, params, tokens, pos0, cache, None, True,
                        page_table=phys, kv_page=_page,
                    )
                else:
                    win = gather_kv_pages(cache, phys, _page)
                    if self.mesh is not None:
                        # forward on the dense window layout, scatter on
                        # the arena's (_pin_win_sharding: GSPMD
                        # miscompiles any replicated-slot-dim window)
                        win = _pin_win_sharding(win, self.mesh,
                                                batch=True)
                    logits, win = forward(
                        spec, params, tokens, pos0, win, None, False,
                    )
                    if self.mesh is not None:
                        win = _pin_win_sharding(win, self.mesh,
                                                batch=False)
                    cache = scatter_kv_pages(cache, win, wb, _page)
                last = logits[:, -1, :]
                toks, sampling = _sample_masked(sampling, slot_ids, last,
                                                active, masks)
                return toks, cache, sampling
        else:
            @partial(jax.jit, donate_argnums=(2, 5))
            def _decode(params, tokens, cache, pos0, slot_ids, sampling,
                        active, masks):
                # slot_ids=None: decode batches every cache row in order,
                # so the KV write is a per-row DUS, not a cache-sized
                # scatter
                logits, cache = forward(
                    spec, params, tokens, pos0, cache, None,
                    self._use_kernel, mesh=self.mesh,
                )
                last = logits[:, -1, :]
                toks, sampling = _sample_masked(sampling, slot_ids, last,
                                                active, masks)
                return toks, cache, sampling

        @jax.jit
        def _sample_only(sampling, slot_ids, logits, masks):
            return sample(sampling, slot_ids, logits, mask=masks)

        @jax.jit
        def _hidden(params, tokens, cache, pos0, slot_ids):
            return forward_hidden(spec, params, tokens, pos0, cache, slot_ids)

        self._decode_fn = _decode
        self._sample_fn = _sample_only
        self._hidden_fn = _hidden
        self._decode_k_fns: dict[tuple, Any] = {}  # ("decode", k, W) |
        # ("spec", kd, rounds) | ("draft_prefill",) | ("prefill", W) |
        # ("prefill_final", W)
        # device-resident decode state (tokens/pos/active) reused across
        # dispatches while no slot changes; _epoch invalidates it
        self._epoch = 0
        self._dev_epoch = -1
        self._dev_akey: Any = None  # advancing-set of the saved carry:
        # with per-slot spec decoding the active set can change between
        # dispatches WITHOUT an epoch bump, and a stale inactive row in
        # the carry would stop writing K/V for a now-advancing slot
        self._dev_tokens: Any = None
        self._dev_pos: Any = None
        self._dev_active: Any = None
        # async dispatch pipeline (see step()): FIFO of in-flight device
        # dispatches awaiting host-side harvest
        self._flights: deque[_Flight] = deque()
        self._pipeline_depth = 2  # decode scans kept in flight
        self._harvest_last: dict[int, int] = {}  # last token per slot of
        # the most recently harvested scan (chained flights' prev_last)
        self._last_harvest_t = 0.0
        self._last_arrival = 0.0  # submit time of the newest request —
        # decode scheduling yields briefly to an admission burst
        self._hold_start = 0.0  # when the current admission-burst hold
        # began (0 = not holding); bounds hold duration
        self._step_ms = 0.0  # EWMA of device ms per decode step,
        # measured at scan harvest; _latency_k sizes open-capacity
        # scans from it
        self._arrivals: deque[float] = deque(maxlen=8)  # lint: guarded-by self._lock  # submit-call
        # timestamps (one per submit/submit_many); _prefill_hold reads
        # their spread to tell a still-landing burst from a lone
        # arrival or a single batched wave
        self._prefill_hold0 = 0.0  # when the current prefill-formation
        # hold began (0 = not holding); bounds hold duration.
        # _hold_start/_prefill_hold0 are LEGACY-ONLY state: the mixed
        # dispatcher has no hold loops (its decode/prefill fusion is
        # what the holds were approximating)
        self._last_decode_adv = 0.0  # perf_counter of the last dispatch
        # that advanced >=1 decode row; gaps between consecutive ones
        # while a slot decodes feed engine_decode_stall_seconds
        self.warmup_reused = False  # True when warmup() was skipped
        # because an identical variant set is already in the persistent
        # compile cache (see warmup docstring); surfaced in the load
        # phase breakdown
        self.state_dir = state_dir or hbm_ledger.default_state_dir()
        # warmup-captured XLA cost model: per-dispatch FLOPs/bytes
        # accounting + the MFU gauge (telemetry/costmodel.py). Host-held
        # counters only — the hot path never syncs for accounting.
        self._costmodel: Optional[costmodel.CostModel] = None
        if knobs.flag("LOCALAI_COSTMODEL"):
            try:
                plat = jax.devices()[0].platform
            except RuntimeError:  # backend not initialized
                plat = "cpu"
            self._costmodel = costmodel.CostModel(
                self._mlabel, plat,
                1 if mesh is None else int(mesh.devices.size))
        # component-level HBM ledger (telemetry/hbm_ledger.py):
        # long-lived device allocations registered here, reconciled
        # against device.memory_stats() each gauge sweep
        self._ledger: Optional[hbm_ledger.HBMLedger] = None
        self._ledger_t = 0.0  # last reconcile (rate-limited ~1s)
        if knobs.flag("LOCALAI_HBM_LEDGER"):
            led = hbm_ledger.HBMLedger(self._mlabel)
            if self._pager is not None:
                # paged weights attribute by tier: hot follows the
                # device-resident bytes (the promotion cursor's fraction
                # mid-stream), warm is the host mirror — host=True keeps
                # it out of the device drift sum
                pager = self._pager
                led.register("weights_hot", pager.device_bytes)
                led.register("weights_warm", pager.host_bytes,
                             host=True)
            else:
                led.register("weights", self.params)
            led.register("kv_arena",
                         (self.cache.k, self.cache.v))
            if getattr(self.cache, "k_scale", None) is not None:
                led.register("kv_scales",
                             (self.cache.k_scale, self.cache.v_scale))
            if self.draft_cache is not None:
                led.register("draft_cache", self.draft_cache)
            led.register("sampler", self.sampling)
            if self._tier is not None:
                # in-flight tier spill/fetch DMA buffers (callable
                # source: the windows' byte counts move every sweep)
                tier = self._tier
                led.register(
                    "staging",
                    lambda: tier._swin.flying + tier._fwin.flying)
            self._ledger = led

    def _kernel_eligible(self) -> bool:
        """Use the Pallas ragged decode kernels when the mosaic path is
        available and shapes qualify (ops/decode_attention.py). Env
        override: LOCALAI_DECODE_KERNEL=0/1."""
        from ..ops.decode_attention import PAGE, _interpret

        env = knobs.str_("LOCALAI_DECODE_KERNEL")
        if env in ("0", "false", "off"):
            return False
        # default ON where mosaic compiles: the fused per-slot kernel
        # (ragged page reads, full-cache addressing) beats the windowed
        # XLA path at serving shapes on v5e. Forcing =1 also allows the
        # (slow) interpret path so CPU tests exercise the kernel engine.
        from ..models.transformer import _layer_windows

        forced = env in ("1", "true", "on")
        if self.mesh is not None:
            # meshed serving runs the kernel per-shard under shard_map;
            # shapes must split evenly over the mesh axes
            if self._paged:
                # paged meshed engines have exactly ONE kernel route:
                # the ragged kernel over the model-sharded arena
                # (ops.ragged_paged_attention.sharded_ragged_append_
                # attend). The fused decode kernel's meshed wrapper
                # addresses the DENSE [L, S, SEQ, F] layout, so with
                # ragged off the engine takes the GSPMD gather
                # fallback instead.
                from ..ops.ragged_paged_attention import (
                    mesh_ragged_eligible,
                )

                if not self._ragged or not mesh_ragged_eligible(
                    self.mesh, self.spec.n_kv_heads, self.spec.n_heads,
                    self.spec.kv_dim,
                ):
                    return False
                if self.draft is not None and not mesh_ragged_eligible(
                    self.mesh, self.draft[0].n_kv_heads,
                    self.draft[0].n_heads, self.draft[0].kv_dim,
                ):
                    # spec-decode rounds run the draft through the same
                    # shard_map route; an ineligible draft keeps the
                    # whole engine on the GSPMD gather fallback
                    return False
            else:
                # dense meshed: ops.decode_attention.sharded_append_attend
                from ..ops.decode_attention import mesh_kernel_eligible

                if not mesh_kernel_eligible(
                    self.mesh, self.spec.n_kv_heads, self.spec.n_heads,
                    self.spec.kv_dim, self.n_slots,
                ):
                    return False
        return (
            (forced or not _interpret())
            # paged arenas DMA whole pool pages (page-table lookups), so
            # the pool's own divisibility guarantee replaces the dense
            # kernel's max_seq % PAGE requirement
            and (self.max_seq % PAGE == 0 if not self._paged else True)
            and self.spec.kv_dim % 128 == 0
            and not self.spec.attn_logit_softcap
            # conditions forward_hidden ALSO gates on — if they disagree
            # the engine would skip window bucketing while forward falls
            # back to the full-seq XLA path (int8 caches qualify: the
            # kernel reads int8 pages + per-row scales directly)
            and _layer_windows(self.spec) is None
        )

    # ------------------------------------------- paged KV pool (host side)

    def _phys_rows(self, slot_rows: list, window: int) -> np.ndarray:
        """Per-batch-row physical page tables [B, window//page] for a
        dispatch payload (plain int32 — multihost followers replay it
        like any scalar). ``slot_rows`` maps batch row -> slot index;
        None rows and entries beyond a slot's allocation point at the
        trash page, whose garbage reads are causally masked."""
        wp = window // self._page
        out = np.full((len(slot_rows), wp), TRASH_PAGE, np.int32)
        for r, si in enumerate(slot_rows):
            if si is None:
                continue
            t = self._pool.table(si)
            n = min(len(t), wp)
            if n:
                out[r, :n] = t[:n]
        return out

    def _wb_rows(self, entries: list, window: int) -> np.ndarray:
        """Write-back page tables [B, window//page]: the physical page
        for every window page intersecting the row's write span, trash
        everywhere else — so a dispatch persists exactly its own writes
        and can never touch a shared (refcount > 1) prefix page or a
        parked row's resident pages. ``entries``: (slot index | None,
        (start, end) token span | None) per batch row."""
        wp = window // self._page
        P = self._page
        out = np.full((len(entries), wp), TRASH_PAGE, np.int32)
        for r, (si, span) in enumerate(entries):
            if si is None or span is None:
                continue
            start, end = span
            if end <= start:
                continue
            t = self._pool.table(si)
            for p in range(start // P, min(-(-end // P), wp)):
                if p >= len(t) or not self._pool.writable(t[p]):
                    raise RuntimeError(
                        f"paged KV: slot {si} write span page {p} is not "
                        "privately writable — allocator invariant broken")
                out[r, p] = t[p]
        return out

    def _pool_ensure(self, slot: "_Slot", n_tokens: int) -> bool:
        """Grow the slot's page table to cover ``n_tokens`` positions,
        reclaiming free slots' resident prefixes (least valuable first,
        prefix_index LRU x length) under pool pressure. False = the
        arena is genuinely full of ACTIVE state; the caller ends or
        requeues the work."""
        if faultinject.ACTIVE:
            # chaos surface for the OOM-forensics path: a fault here is
            # the deterministic stand-in for a device RESOURCE_EXHAUSTED
            # during KV growth — _loop's catch writes the HBM
            # post-mortem before failing the active slots
            faultinject.fire("engine.hbm_alloc")
        try:
            self._pool.ensure(slot.idx, n_tokens)
            return True
        except PagePoolExhausted:
            pass
        now = time.monotonic()
        victims = sorted(
            (s for s in self.slots
             if not s.active and s is not slot
             and self._pool.held(s.idx)),
            key=lambda s: self._prefix_index.value(s.idx, now))
        for v in victims:
            if self._tier is not None:
                # enqueue an async D2H spill FIRST: the reclaim then
                # DEMOTES the resident prefix to host RAM instead of
                # discarding it — device-order serialization keeps the
                # copy coherent across the drop below, and an injected
                # spill fault simply falls back to today's plain drop
                self._tier.demote_urgent(v)
            self._pool.drop(v.idx)
            v.cache_tokens = []
            v.n_past = 0
            self._prefix_index.remove(v.idx)
            tm.ENGINE_KV_PAGE_ALLOC.labels(
                model=self._mlabel, outcome="reclaimed").inc()
            try:
                self._pool.ensure(slot.idx, n_tokens)
                return True
            except PagePoolExhausted:
                continue
        tm.ENGINE_KV_PAGE_ALLOC.labels(
            model=self._mlabel, outcome="exhausted").inc()
        log.warning("KV page pool exhausted: slot %d needs %d tokens",
                    slot.idx, n_tokens)
        return False

    def _page_headroom(self, req: GenRequest) -> bool:
        """Admission gate: worst-case pages for the prompt must fit in
        free + reclaimable (free slots' private pages) capacity, or the
        request waits in the queue instead of thrashing an admit/finish
        cycle. Soft check — dispatch-time _pool_ensure is the backstop."""
        st = self._pool.stats()
        need = self._pool.pages_for(len(req.prompt_ids) + 1)
        if st.free >= need:
            return True
        reclaim = sum(
            1 for s in self.slots if not s.active
            for p in self._pool.table(s.idx)
            if self._pool.writable(p) and not self._pool.pinned(p))
        return st.free + reclaim >= need

    def _spec_decode_fn(self, kd: int, rounds: int):
        """Jitted speculative decoding: ``rounds`` iterations of
        (draft kd-1 greedy tokens -> ONE main verify forward of T=kd ->
        on-device cumulative acceptance) per host dispatch. Greedy
        acceptance reproduces the main model's greedy sequence EXACTLY
        while paying ~1 main forward per accepted run instead of per
        token (ref: the proto's DraftModel/NDraft surface; greenfield on
        TPU). Rejected-draft cache rows land beyond the valid prefix and
        are rewritten next round — the same invariant the multi-step
        overshoot discard relies on."""
        key = ("spec", kd, rounds)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        dspec = self.draft[0]  # static; draft params passed per call
        paged = self._paged
        page = self._page
        mesh = self.mesh
        ragged_k = self._ragged and self._use_kernel

        @partial(jax.jit, donate_argnums=(2, 3))
        def _spec(params, dparams, cache, dcache, tokens, pos0, active,
                  *paged_tables):
            phys = wb = None
            if paged and ragged_k:
                # ragged kernel: verify rows are q_len == kd ragged rows
                # through the SAME kernel as decode/prefill; draft steps
                # are q_len == 1 rows. No gathered views — writes route
                # through wb (ineligible rows' spans are trash).
                phys, wb = paged_tables
            elif paged:
                # full-width gathered views for both caches; the arena
                # writeback at the end persists only the eligible rows'
                # verify/draft spans (wb)
                arena, darena = cache, dcache
                phys, wb = paged_tables
                cache = gather_kv_pages(arena, phys, page)
                dcache = gather_kv_pages(darena, phys, page)
                if mesh is not None:
                    cache = _pin_win_sharding(cache, mesh, batch=True)
                    dcache = _pin_win_sharding(dcache, mesh, batch=True)
            ones = jnp.ones(tokens.shape[:1], jnp.int32)

            def rag(n):
                if not ragged_k:
                    return {}
                return {"mesh": mesh, "page_table": phys,
                        "kv_page": page, "q_lens": ones * n,
                        "write_table": wb}

            def round_(carry, _):
                tok, pos, cache, dcache = carry

                def dstep(c, _):
                    t, p, dc = c
                    lg, dc = forward(dspec, dparams, t, p, dc, None,
                                     **rag(1))
                    nt = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
                    p2 = jnp.where(active, p + 1, p)
                    return (nt[:, None], p2, dc), nt

                # kd steps (not kd-1): the extra step's sampled token is
                # discarded, but it writes d_{kd-1}'s K/V so the draft
                # cache covers the full accepted prefix after a clean
                # round (otherwise a stale row sits inside the draft's
                # attended prefix and quietly kills the acceptance rate)
                (_, _, dcache2), dts = lax.scan(
                    dstep, (tok, pos, dcache), None, length=kd)
                d_toks = dts[: kd - 1].T  # [S, kd-1]
                xin = jnp.concatenate([tok, d_toks], axis=1)  # [S, kd]
                lg, cache2 = forward(spec, params, xin, pos, cache, None,
                                     **rag(kd))
                m_toks = jnp.argmax(lg, -1).astype(jnp.int32)  # [S, kd]
                ok = (m_toks[:, : kd - 1] == d_toks).astype(jnp.int32)
                j = 1 + jnp.cumprod(ok, axis=1).sum(1)  # [S] in 1..kd
                j = jnp.where(active, j, 0)
                last = jnp.take_along_axis(
                    m_toks, (jnp.maximum(j, 1) - 1)[:, None], axis=1)
                pos2 = jnp.where(active, pos + j, pos)
                return (last, pos2, cache2, dcache2), (d_toks, m_toks, j)

            (tok_f, pos_f, cache, dcache), (D, Mt, J) = lax.scan(
                round_, (tokens, pos0, cache, dcache), None, length=rounds)
            if paged and not ragged_k:
                if mesh is not None:
                    cache = _pin_win_sharding(cache, mesh, batch=False)
                    dcache = _pin_win_sharding(dcache, mesh, batch=False)
                cache = scatter_kv_pages(arena, cache, wb, page)
                dcache = scatter_kv_pages(darena, dcache, wb, page)
            return D, Mt, J, tok_f, pos_f, cache, dcache

        self._decode_k_fns[key] = _spec
        return _spec

    def _spec_sampled_fn(self, kd: int, rounds: int):
        """Jitted speculative REJECTION sampling (Leviathan et al.): the
        draft SAMPLES kd-1 tokens from its filtered distribution q, one
        main forward computes the filtered p at every position, and each
        draft token is accepted with prob min(1, p(t)/q(t)); the first
        rejection resamples from norm(max(p-q, 0)), and a fully-accepted
        run samples its last token from p directly. This reproduces exact
        samples from the main model's distribution — the sampled-path
        counterpart of the greedy _spec_decode_fn (ref: the proto's
        DraftModel/NDraft surface; greenfield on TPU). Temp<=0 slots
        collapse to exact one-hot distributions, so mixed greedy/sampled
        batches stay correct. RNG rides SamplingState.rng per slot with a
        static number of draws per round."""
        key = ("spec_s", kd, rounds)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn
        from ..ops.sampling import NEG_INF, filtered_candidates

        spec = self.spec
        dspec = self.draft[0]
        S = self.n_slots

        def split_rows(rng):  # [S, 2] -> (carry keys, use keys)
            s = jax.vmap(jax.random.split)(rng)
            return s[:, 0], s[:, 1]

        def gumbel_pick(keys, probs):  # [R,2], [R,C] -> [R] candidate idx
            logp = jnp.where(probs > 0,
                             jnp.log(jnp.maximum(probs, 1e-30)), NEG_INF)
            g = jax.vmap(
                lambda k, row: jax.random.gumbel(k, row.shape, jnp.float32)
            )(keys, logp)
            return jnp.argmax(logp + g, axis=-1)

        paged = self._paged
        page = self._page
        mesh = self.mesh
        ragged_k = self._ragged and self._use_kernel

        @partial(jax.jit, donate_argnums=(3, 4))
        def _spec_s(params, dparams, sampling, cache, dcache, tokens, pos0,
                    active, *paged_tables):
            phys = wb = None
            if paged and ragged_k:
                phys, wb = paged_tables
            elif paged:
                arena, darena = cache, dcache
                phys, wb = paged_tables
                cache = gather_kv_pages(arena, phys, page)
                dcache = gather_kv_pages(darena, phys, page)
                if mesh is not None:
                    cache = _pin_win_sharding(cache, mesh, batch=True)
                    dcache = _pin_win_sharding(dcache, mesh, batch=True)
            all_slots = jnp.arange(S, dtype=jnp.int32)
            rep_slots = jnp.repeat(all_slots, kd)
            ones = jnp.ones(tokens.shape[:1], jnp.int32)

            def rag(n):
                if not ragged_k:
                    return {}
                return {"mesh": mesh, "page_table": phys,
                        "kv_page": page, "q_lens": ones * n,
                        "write_table": wb}

            def round_(carry, _):
                tok, pos, cache, dcache, rng = carry

                def dstep(c, _):
                    t, p, dc, rng = c
                    lg, dc = forward(dspec, dparams, t, p, dc, None,
                                     **rag(1))
                    qp, qidx = filtered_candidates(
                        sampling, all_slots, lg[:, -1])
                    rng, k1 = split_rows(rng)
                    j = gumbel_pick(k1, qp)
                    nt = jnp.take_along_axis(
                        qidx, j[:, None], 1)[:, 0].astype(jnp.int32)
                    qsel = jnp.take_along_axis(qp, j[:, None], 1)[:, 0]
                    p2 = jnp.where(active, p + 1, p)
                    return (nt[:, None], p2, dc, rng), (nt, qsel, qp, qidx)

                # kd steps like the greedy path: the last step's K/V write
                # keeps the draft cache covering the full accepted prefix
                (_, _, dcache2, rng), (dts, qsel, qps, qidxs) = lax.scan(
                    dstep, (tok, pos, dcache, rng), None, length=kd)
                d_toks = dts[: kd - 1].T  # [S, kd-1]
                xin = jnp.concatenate([tok, d_toks], axis=1)  # [S, kd]
                lg, cache2 = forward(spec, params, xin, pos, cache, None,
                                     **rag(kd))
                pp, pidx = filtered_candidates(
                    sampling, rep_slots, lg.reshape(S * kd, -1))
                C = pp.shape[-1]
                pp = pp.reshape(S, kd, C)
                pidx = pidx.reshape(S, kd, C)
                qps_t = qps.transpose(1, 0, 2)  # [S, kd, C]
                qidxs_t = qidxs.transpose(1, 0, 2)
                d_all = dts.T  # [S, kd]
                # p_i(d_i): main filtered prob of each draft token
                p_at_d = jnp.sum(
                    pp * (pidx == d_all[:, :, None]), axis=-1)  # [S, kd]
                rng, ku = split_rows(rng)
                u = jax.vmap(
                    lambda k: jax.random.uniform(k, (kd - 1,))
                )(ku)  # [S, kd-1]
                ratio = p_at_d[:, : kd - 1] / jnp.maximum(
                    qsel.T[:, : kd - 1], 1e-30)
                ok = (u < jnp.minimum(ratio, 1.0)).astype(jnp.int32)
                j = 1 + jnp.cumprod(ok, axis=1).sum(1)  # [S] in 1..kd
                j = jnp.where(active, j, 0)
                # replacement token per position: residual norm(max(p-q,0))
                # at a rejection, p itself at the bonus position kd-1
                match = (qidxs_t[:, :, :, None] == pidx[:, :, None, :])
                q_on_p = jnp.sum(qps_t[:, :, :, None] * match, 2)  # [S,kd,C]
                residual = jnp.maximum(pp - q_on_p, 0.0)
                rsum = residual.sum(-1, keepdims=True)
                res_dist = jnp.where(rsum > 1e-9, residual / rsum, pp)
                is_bonus = (jnp.arange(kd) == kd - 1)[None, :, None]
                dist = jnp.where(is_bonus, pp, res_dist)
                rng, kr = split_rows(rng)
                kr_all = jax.vmap(
                    lambda k: jax.random.split(k, kd))(kr)  # [S, kd, 2]
                fj = gumbel_pick(
                    kr_all.reshape(S * kd, 2), dist.reshape(S * kd, C))
                fin = jnp.take_along_axis(
                    pidx.reshape(S * kd, C), fj[:, None], 1
                )[:, 0].astype(jnp.int32).reshape(S, kd)
                last = jnp.take_along_axis(
                    fin, (jnp.maximum(j, 1) - 1)[:, None], axis=1)
                pos2 = jnp.where(active, pos + j, pos)
                return (last, pos2, cache2, dcache2, rng), (d_toks, fin, j)

            (_, _, cache, dcache, rng), (D, Fin, J) = lax.scan(
                round_, (tokens, pos0, cache, dcache, sampling.rng),
                None, length=rounds)
            if paged and not ragged_k:
                if mesh is not None:
                    cache = _pin_win_sharding(cache, mesh, batch=False)
                    dcache = _pin_win_sharding(dcache, mesh, batch=False)
                cache = scatter_kv_pages(arena, cache, wb, page)
                dcache = scatter_kv_pages(darena, dcache, wb, page)
            return D, Fin, J, rng, cache, dcache

        self._decode_k_fns[key] = _spec_s
        return _spec_s

    def _prefill_fn(self, window: int, ring: bool = False):
        """Jitted prompt-chunk prefill over a ``window``-sliced cache
        (attention + KV writes scale with the live-context bucket).
        ``ring=True``: the chunk's attention runs as seq-parallel ring
        attention over the mesh's "seq" axis (first chunk of a long
        prompt on a seq-sharded serving mesh — VERDICT r3: long-context
        must flow through the SERVING path, not just exist as an op)."""
        key = ("prefill", window, ring)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        mesh = self.mesh

        if self._paged:
            page = self._page
            ragged_k = self._ragged and self._use_kernel

            @partial(jax.jit, donate_argnums=(2,))
            def _prefill(params, tokens, cache, pos0, slot_ids, phys, wb,
                         soft=None):
                # paged: the gathered view holds only this dispatch's
                # rows (identity layout), so the slot mapping lives in
                # phys/wb instead of slot_ids
                if soft is not None:
                    soft = _soft_expand(tokens, *soft)
                if ragged_k:
                    # ragged kernel: the chunk scatters through wb and
                    # attention walks pages in-kernel — no gathered
                    # window view (chunk dispatches are always
                    # full-bucket wide, so q_lens is the bucket)
                    qlens = jnp.full(tokens.shape[:1], tokens.shape[1],
                                     jnp.int32)
                    _, cache = forward_hidden(
                        spec, params, tokens, pos0, cache, None,
                        soft=soft, mesh=mesh, page_table=phys,
                        kv_page=page, q_lens=qlens, write_table=wb)
                    return cache
                win = gather_kv_pages(cache, phys, page)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=True)
                _, win = forward_hidden(spec, params, tokens, pos0, win,
                                        None, soft=soft)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=False)
                return scatter_kv_pages(cache, win, wb, page)
        else:
            @partial(jax.jit, donate_argnums=(2,))
            def _prefill(params, tokens, cache, pos0, slot_ids,
                         soft=None):
                # non-final chunk: only the K/V writes matter —
                # materializing [B, T, V] logits would waste bucket*V
                # f32 of HBM per row
                if soft is not None:
                    soft = _soft_expand(tokens, *soft)
                win, restore = _window_cache(cache, window)
                _, win = forward_hidden(spec, params, tokens, pos0, win,
                                        slot_ids, soft=soft, mesh=mesh,
                                        ring_prefill=ring)
                return restore(win)

        self._decode_k_fns[key] = _prefill
        return _prefill

    def _prefill_final_fn(self, window: int, identity: bool = False):
        """Final prompt chunks for a BATCH of slots + penalty-window seed
        + first-token sample in ONE dispatch — concurrent prompts share
        the round trip instead of paying one each, and TTFT pays one RTT,
        not three (SURVEY.md §7 hard part #2). The cache is windowed like
        the decode path: full-seq prefill attention measured ~7s/wave at
        1B/2048-seq shapes, windowed ~100ms.

        ``identity``: the batch spans EVERY slot in cache-row order
        (row b == slot b), so the K/V write takes forward_hidden's
        per-row DUS hot path instead of the whole-layer gather/scatter a
        cross-slot mapping forces — measured 234 -> 153 ms on the
        [64, 4] 8B int8 dispatch (tools/microbench_step.py r5).
        ``slot_ids`` still arrives for the SAMPLER scatters: non-member
        rows carry the out-of-bounds sentinel so their reset/seed/sample
        writes drop.

        tokens [B, bucket]; slot_ids/pos0/n_chunk/tail_lens [B];
        tails [B, W]."""
        key = ("prefill_final", window, identity)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        n_slots = self.n_slots
        paged = self._paged
        page = self._page
        mesh = self.mesh
        ragged_k = self._ragged and self._use_kernel

        @partial(jax.jit, donate_argnums=(2, 4))
        def _prefill_final(params, tokens, cache, pos0, sampling, slot_ids,
                           n_chunk, tails, tail_lens, masks, reset,
                           *paged_tables, soft=None):
            if soft is not None:
                soft = _soft_expand(tokens, *soft)
            if paged and ragged_k:
                # ragged kernel: n_chunk IS the per-row ragged query
                # length (pad rows carry 1 and write to trash via wb)
                phys, wb = paged_tables
                hidden, cache = forward_hidden(
                    spec, params, tokens, pos0, cache, None, soft=soft,
                    mesh=mesh, page_table=phys, kv_page=page,
                    q_lens=n_chunk, write_table=wb)
            elif paged:
                # paged: rows map to slots via phys/wb; parked and pad
                # rows simply never write back (their wb pages are
                # trash), so no write_mask is needed
                phys, wb = paged_tables
                win = gather_kv_pages(cache, phys, page)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=True)
                hidden, win = forward_hidden(
                    spec, params, tokens, pos0, win, None, soft=soft)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=False)
                cache = scatter_kv_pages(cache, win, wb, page)
            else:
                win, restore = _window_cache(cache, window)
                hidden, win = forward_hidden(
                    spec, params, tokens, pos0, win,
                    None if identity else slot_ids, soft=soft,
                    # identity parks non-members at pos 0 with a no-op
                    # write, so the window can track the MEMBERS' live
                    # context instead of max_seq
                    write_mask=(slot_ids < n_slots) if identity else None,
                )
                cache = restore(win)
            # sampler reset rides THIS dispatch (admission used to pay a
            # separate reset_batch round trip before the prefill — one
            # full tunnel RTT off TTFT for singles and waves alike)
            from ..models.transformer import _lm_head
            from ..ops.sampling import reset_slots

            sampling = reset_slots(sampling, slot_ids, *reset)
            # closed-form penalty-window seed (scan-equivalent; the W
            # sequential scatter steps dominated this dispatch's time)
            sampling = seed_windows(sampling, slot_ids, tails, tail_lens)
            # LM head on each row's LAST position only: full [B, T, V]
            # logits would cost bucket*V f32 per row (a 64x2048 group at
            # 32k vocab is 16 GB — instant OOM) for values the sampler
            # never reads
            last_h = jax.vmap(
                lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 0)[0]
            )(hidden, n_chunk)  # [B, D] at each chunk's true last position
            logits = _lm_head(spec, params, last_h[:, None, :])[:, 0]
            toks, sampling = sample(sampling, slot_ids, logits, mask=masks)
            return toks, cache, sampling

        self._decode_k_fns[key] = _prefill_final
        return _prefill_final

    def _mixed_fn(self, window: int):
        """Fused mixed-step dispatch: ONE identity-batch device function
        ([n_slots, bucket], row b == slot b) that, per step, runs a
        token-budgeted prefill chunk for PREFILL rows AND one decode
        step for DECODE rows — the ragged-batch discipline production
        engines converged on (RTP-LLM / Ragged Paged Attention,
        PAPERS.md), expressed as a single static shape so the variant
        set stays tiny (warmup-precompiled like the identity
        prefill_final).

        Row roles are encoded entirely in the per-row index vectors, so
        one compiled variant serves every composition:
        - decode rows: n_chunk=1 (their last sampled token at column
          0), sample_sids = own idx, reset_sids = OOB sentinel (their
          live sampler state must NOT be reset);
        - prefill final-chunk rows: n_chunk = remaining prompt,
          sample_sids = reset_sids = own idx — sampler reset, penalty-
          window seed, and first-token sample ride this dispatch
          exactly as in _prefill_final_fn;
        - prefill non-final chunk rows: sample_sids = sentinel (K/V
          writes only; their last-position logits are computed but the
          sampler scatters drop);
        - parked rows (FREE): write_mask False — a no-op re-write of
          what is already at their positions, so resident prefixes
          survive untouched (no tail clamping needed, unlike the
          decode scan's inactive rows).

        Per-slot sampler math is IDENTICAL to the split paths (same
        sample()/reset_slots/seed_windows calls, sentinel-id scatter
        drops instead of active-mask merges), so an identical request
        schedule produces byte-identical outputs with this path on or
        off (test_mixed_dispatch.py enforces it)."""
        key = ("mixed", window)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn
        spec = self.spec
        paged = self._paged
        page = self._page
        mesh = self.mesh
        ragged_k = self._ragged and self._use_kernel

        @partial(jax.jit, donate_argnums=(2, 4))
        def _mixed(params, tokens, cache, pos0, sampling, write_mask,
                   n_chunk, sample_sids, reset_sids, tails, tail_lens,
                   masks, reset, *paged_tables, soft=None):
            if soft is not None:
                soft = _soft_expand(tokens, *soft)
            if paged and ragged_k:
                # the ragged batch in one kernel invocation: decode
                # rows (n_chunk 1), prefill chunks, finals and parked
                # rows (write to trash via wb) together — the unified
                # dispatch RTP-LLM/Ragged-Paged-Attention converge on
                phys, wb = paged_tables
                hidden, cache = forward_hidden(
                    spec, params, tokens, pos0, cache, None, soft=soft,
                    mesh=mesh, page_table=phys, kv_page=page,
                    q_lens=n_chunk, write_table=wb)
            elif paged:
                # paged: per-row write spans live in wb (parked rows and
                # shared prefix pages are trash-redirected), so the
                # write_mask no-op rewrite is unnecessary
                phys, wb = paged_tables
                win = gather_kv_pages(cache, phys, page)
                if mesh is not None:
                    # run the forward on the DENSE cache's window layout
                    # and scatter on the arena's (_pin_win_sharding: any
                    # replicated-slot-dim window is miscompiled by GSPMD
                    # on a data x model mesh)
                    win = _pin_win_sharding(win, mesh, batch=True)
                hidden, win = forward_hidden(
                    spec, params, tokens, pos0, win, None, soft=soft)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=False)
                cache = scatter_kv_pages(cache, win, wb, page)
            else:
                win, restore = _window_cache(cache, window)
                hidden, win = forward_hidden(
                    spec, params, tokens, pos0, win, None, soft=soft,
                    write_mask=write_mask,
                )
                cache = restore(win)
            from ..models.transformer import _lm_head
            from ..ops.sampling import reset_slots

            # same phase order as _prefill_final_fn: reset -> seed ->
            # sample. Decode rows carry the sentinel in reset_sids, so
            # the scatters leave their live sampler state untouched.
            sampling = reset_slots(sampling, reset_sids, *reset)
            sampling = seed_windows(sampling, reset_sids, tails,
                                    tail_lens)
            last_h = jax.vmap(
                lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 0)[0]
            )(hidden, n_chunk)  # [S, D] at each row's true last position
            logits = _lm_head(spec, params, last_h[:, None, :])[:, 0]
            toks, sampling = sample(sampling, sample_sids, logits,
                                    mask=masks)
            return toks, cache, sampling

        self._decode_k_fns[key] = _mixed
        return _mixed

    @property
    def _mixed_buckets(self) -> tuple[int, ...]:
        """Prefill buckets whose identity-batch dispatch fits the
        per-dispatch token budget (LOCALAI_PREFILL_GROUP_TOKENS): the
        mixed step is always [n_slots, bucket], so n_slots*bucket bounds its
        device work — decode rows are admitted first (they cost one
        real token each) and the rest of the budget carries prefill
        chunk tokens, which is what bounds decode ITL under admission
        pressure."""
        return tuple(b for b in self.prefill_buckets
                     if b * self.n_slots <= self._prefill_group_tokens)

    def _window_bucket(self, need: int) -> int:
        """Smallest power-of-two window >= need (floor 256, cap max_seq)."""
        w = 256
        while w < need:
            w *= 2
        return min(w, self.max_seq)

    def _itl_budget_ms(self) -> float:
        """The explicit inter-token-latency budget cost scheduling
        packs against, in ms; 0.0 when cost scheduling is off, the
        cost model is absent, or no budget is set — every caller
        treats 0.0 as 'token-budget sizing only'."""
        if self._costmodel is None or not knobs.flag("LOCALAI_COST_SCHED"):
            return 0.0
        return max(0.0, knobs.float_("LOCALAI_ITL_BUDGET_MS"))

    def _cost_sched_on(self) -> bool:
        """Whether predictor-driven admission/deadline decisions are
        active (independent of the ITL packing budget)."""
        return (self._costmodel is not None
                and knobs.flag("LOCALAI_COST_SCHED"))

    def _mixed_window(self, prefilling: list, decoding: list,
                      bucket: int) -> int:
        """Context window the mixed dispatch for this composition and
        bucket would select — EXACTLY the choice _enqueue_mixed makes
        (ragged pins full width; otherwise the smallest compiled
        window covering every advancing row), factored out so the
        cost-packing pass can predict each candidate bucket's true
        variant before any arrays are built."""
        if self._ragged:
            return self.max_seq
        need_w = max(
            [s.n_past + 1 for s in decoding]
            + [s.n_past + min(s.n_prompt - s.n_past, bucket)
               for s in prefilling]) + 1
        window = self._window_bucket(need_w)
        compiled = [k[1] for k in self._decode_k_fns
                    if k[0] == "mixed" and window <= k[1]]
        return min(compiled) if compiled else self.max_seq

    def _cost_bucket(self, prefilling: list, decoding: list,
                     cover: int, budget_ms: float) -> int:
        """Predicted-device-time bucket choice for a mixed dispatch:
        the largest candidate <= ``cover`` (the token-budget pick, so
        cost packing only ever shrinks within the warmed variant set)
        whose predicted device time fits ``budget_ms``. When every
        predicted candidate exceeds the budget the smallest predicted
        one dispatches anyway — progress beats stalling, and it is the
        minimum-gap choice available. When NO candidate has a
        prediction (variant never captured) the token-budget pick
        stands."""
        cm = self._costmodel
        fit = smallest = None
        for b in self._mixed_buckets:
            if b > cover:
                break
            pred = cm.predict_ms(
                "mixed", ("mixed", (self.n_slots, b),
                          self._mixed_window(prefilling, decoding, b)))
            if pred is None:
                continue
            if smallest is None:
                smallest = b
            if pred <= budget_ms:
                fit = b  # ascending scan keeps the largest that fits
        return fit or smallest or cover

    def _draft_prefill_fn(self):
        """Draft-model prefill (the draft cache must mirror the main
        cache's token positions for speculative decoding)."""
        fn = self._decode_k_fns.get(("draft_prefill",))
        if fn is not None:
            return fn
        dspec = self.draft[0]

        if self._paged:
            page = self._page
            mesh = self.mesh
            ragged_k = self._ragged and self._use_kernel

            @partial(jax.jit, donate_argnums=(2,))
            def _dp(dparams, tokens, dcache, pos0, slot_ids, phys, wb,
                    qlens=None):
                # the draft arena shares the main pool's page geometry
                # and tables; wb carries ONLY the rows whose draft K/V
                # must land (prefill rows — decode rows never mirror)
                if ragged_k:
                    _, dcache = forward(
                        dspec, dparams, tokens, pos0, dcache, None,
                        mesh=mesh, page_table=phys, kv_page=page,
                        q_lens=qlens, write_table=wb)
                    return dcache
                win = gather_kv_pages(dcache, phys, page)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=True)
                _, win = forward(dspec, dparams, tokens, pos0, win, None)
                if mesh is not None:
                    win = _pin_win_sharding(win, mesh, batch=False)
                return scatter_kv_pages(dcache, win, wb, page)
        else:
            @partial(jax.jit, donate_argnums=(2,))
            def _dp(dparams, tokens, dcache, pos0, slot_ids):
                _, dcache = forward(dspec, dparams, tokens, pos0, dcache,
                                    slot_ids)
                return dcache

        self._decode_k_fns[("draft_prefill",)] = _dp
        return _dp

    def _kv_copy_fn(self, n: int, with_draft: bool):
        """Jitted, donated row-to-row KV prefix copy: ``n`` (static,
        power-of-two bucket) leading positions of the src slot's rows —
        k/v and, when quantized, k_scale/v_scale — land in the dst
        slot's rows via per-layer dynamic_slice/dynamic_update_slice.
        Copying past the actual match length is harmless (positions
        beyond dst's valid prefix are rewritten by prefill or causally
        invisible) and keeps the jit variant set tiny. ``with_draft``
        copies the draft cache rows in the SAME dispatch so speculative
        decoding's draft prefix stays exactly as coherent at dst as it
        was at src."""
        key = ("kvcopy", n, with_draft)
        fn = self._decode_k_fns.get(key)
        if fn is not None:
            return fn

        def _copy_rows(cache: KVCache, src, dst) -> KVCache:
            def cp4(a):
                L, _, _, F = a.shape
                row = lax.dynamic_slice(a, (0, src, 0, 0), (L, 1, n, F))
                return lax.dynamic_update_slice(a, row, (0, dst, 0, 0))

            def cp3(a):
                row = lax.dynamic_slice(a, (0, src, 0),
                                        (a.shape[0], 1, n))
                return lax.dynamic_update_slice(a, row, (0, dst, 0))

            return KVCache(
                k=cp4(cache.k), v=cp4(cache.v),
                k_scale=cp3(cache.k_scale) if cache.quantized else None,
                v_scale=cp3(cache.v_scale) if cache.quantized else None,
            )

        if with_draft:
            @partial(jax.jit, donate_argnums=(0, 1))
            def _copy(cache, dcache, src, dst):
                return (_copy_rows(cache, src, dst),
                        _copy_rows(dcache, src, dst))
        else:
            @partial(jax.jit, donate_argnums=(0,))
            def _copy(cache, src, dst):
                return _copy_rows(cache, src, dst)

        self._decode_k_fns[key] = _copy
        return _copy

    @staticmethod
    def _spec_eligible(s: _Slot) -> bool:
        """Penalty/grammar/bias/multimodal/mirostat slots need per-token
        sampler state the speculative path does not thread (mm: the draft
        cache never saw the image soft tokens; mirostat: mu adapts per
        emitted token)."""
        r = s.request
        return not (
            r is None or r.constraint or r.logit_bias
            or r.repeat_penalty not in (0.0, 1.0)
            or r.frequency_penalty or r.presence_penalty
            or r.soft_embeds is not None
            or r.mirostat
        )

    def _spec_mode(
        self, decoding: list[_Slot]
    ) -> tuple[Optional[str], list[_Slot]]:
        """PER-SLOT speculative eligibility (VERDICT r1 weak #7: one
        penalty slot must not disable spec decoding for the whole
        batch). Returns (mode, eligible slots): "greedy" when every
        eligible slot is temp<=0 (exact argmax replay), "sampled"
        otherwise (rejection sampling reproduces the main model's
        distribution exactly); (None, []) when spec cannot run."""
        if self.draft is None:
            return None, []
        elig = [s for s in decoding if self._spec_eligible(s)]
        if not elig:
            return None, []
        sampled = any(s.request.temperature > 0 for s in elig)
        return ("sampled" if sampled else "greedy"), elig

    # lint: region hot_path
    def _spec_decode_step(self, decoding: list[_Slot],
                          mode: str = "greedy") -> None:
        """One speculative dispatch (see _spec_decode_fn /
        _spec_sampled_fn)."""
        t0 = time.perf_counter()
        S = self.n_slots
        kd = self.n_draft
        # span must fit EVERY decode slot's row (ineligible active slots
        # ride along inactive but still receive verify-window writes
        # beyond their valid prefix)
        room = min(self.max_seq - 1 - s.n_past
                   for s in self.slots if s.state is SlotState.DECODE)
        need = max((s.request.max_tokens - len(s.generated)
                    for s in decoding if s.request is not None),
                   default=1)
        rounds = max(1, min(self.decode_steps // kd,
                            max(room // kd, 1),
                            -(-need // kd)))  # no overshoot rounds
        span = rounds * kd
        if self._paged:
            for s in list(decoding):
                if not self._pool_ensure(s, s.n_past + span):
                    self._finish(s, "length")
                    decoding.remove(s)
            if not decoding:
                return
        elig = {s.idx for s in decoding}
        tokens = np.zeros((S, 1), np.int32)
        pos0 = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for s in self.slots:
            if s.idx in elig:
                tokens[s.idx, 0] = (s.generated[-1] if s.generated
                                    else s.request.prompt_ids[-1])
                pos0[s.idx] = s.n_past
                active[s.idx] = True
            elif s.state is SlotState.DECODE:
                # active-but-ineligible: rides inactive (advances in the
                # normal dispatch after this one); its valid prefix must
                # NOT be trimmed — the span fit is guaranteed by `room`
                pos0[s.idx] = s.n_past
            else:
                # parked rows must not run off the row end mid-scan.
                # Paged rows never write back (trash wb), so only the
                # in-dispatch position clamps; the prefix survives.
                limit = max(self.max_seq - 1 - span, 0)
                if s.n_past > limit and not self._paged:
                    s.n_past = limit
                    s.cache_tokens = s.cache_tokens[:limit]
                pos0[s.idx] = min(s.n_past, limit)
        payload = {
            "kd": kd, "rounds": rounds, "tokens": tokens, "pos0": pos0,
            "active": active,
        }
        if self._paged:
            payload["pt"] = self._phys_rows(list(range(S)), self.max_seq)
            payload["wb"] = self._wb_rows(
                [(s.idx, ((s.n_past, s.n_past + span)
                          if s.idx in elig else None))
                 for s in self.slots], self.max_seq)
        self._note_ragged_rows("verify", len(decoding))
        D, Mt, J = self._run("spec_s" if mode == "sampled" else "spec",
                             payload)
        # lint: ignore[hot-path-sync] spec verify is a deliberately blocking dispatch: emission needs J/D/Mt on host before the next spec round is sized
        D = np.asarray(D)  # [rounds, S, kd-1] draft candidates
        # lint: ignore[hot-path-sync] same blocking spec harvest (see D above)
        Mt = np.asarray(Mt)  # [rounds, S, kd] main tokens (greedy verify
        # choices, or rejection-resample/bonus tokens on the sampled path)
        # lint: ignore[hot-path-sync] same blocking spec harvest (see D above)
        J = np.asarray(J)  # [rounds, S] emitted counts
        dt_ms = (time.perf_counter() - t0) * 1e3
        emitted_total = 0
        for s in decoding:
            s.t_decode_ms += dt_ms
            prev_last = int(tokens[s.idx, 0])
            for r in range(rounds):
                if s.state is not SlotState.DECODE:
                    break
                j = int(J[r, s.idx])
                emitted = [int(t) for t in D[r, s.idx, : j - 1]]
                emitted.append(int(Mt[r, s.idx, j - 1]))
                for tok_out in emitted:
                    if s.state is not SlotState.DECODE:
                        break
                    s.cache_tokens.append(prev_last)
                    s.n_past += 1
                    prev_last = tok_out
                    emitted_total += 1
                    self._emit_token(s, tok_out, defer=True)
            if s.state is SlotState.DECODE:
                self._flush_emit(s)
        self.metrics.spec_tokens += emitted_total
        self.metrics.spec_dispatches += 1
        if emitted_total:
            tm.ENGINE_GENERATED_TOKENS.labels(model=self._mlabel).inc(
                emitted_total)
        # spec advanced positions the decodek device-resident carry may
        # still hold stale copies of; a stale inactive-row position would
        # write K/V inside the advanced prefix
        self._epoch += 1
        dt = time.perf_counter() - t0
        if dt > 0 and emitted_total:
            self._note_tokens_per_second(emitted_total, dt)
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel, composition="decode_only").inc()
        self._note_decode_advance(t0)
        self.metrics.slots_busy = sum(1 for s in self.slots if s.active)
    # lint: endregion hot_path

    def _decode_k_fn(self, k: int, window: int):
        """Jitted k-step decode: ``lax.scan`` over k forward+sample steps so
        one host dispatch yields k tokens per active slot. This hides
        host<->device dispatch latency — the decisive factor when the chip
        sits behind a network tunnel, and still a win locally (SURVEY.md §7
        hard part #2: per-token host sync kills throughput).

        ``window`` (static) slices the KV cache to the live-context bucket
        for the whole scan: per-step attention traffic scales with actual
        context use, not max_seq — the XLA stand-in for ragged paged
        attention. The slice/write-back happens once per dispatch, inside
        the jit, so XLA keeps it in place on the donated buffer."""
        fn = self._decode_k_fns.get(("decode", k, window))
        if fn is not None:
            return fn
        spec = self.spec

        if self._paged:
            page = self._page
            use_kernel = self._use_kernel
            ragged_k = self._ragged and use_kernel

            @partial(jax.jit, donate_argnums=(2, 5))
            def _decode_k(params, tokens, cache, pos0, slot_ids, sampling,
                          active, phys, wb):
                if use_kernel:
                    # fused kernel addresses the arena through the page
                    # table directly — no gather, the paged decode hot
                    # path reads only live pages. Ragged mode routes the
                    # append through wb (parked rows write to trash
                    # instead of their own tail pages).
                    ones = jnp.ones(tokens.shape[:1], jnp.int32)

                    def step(carry, _):
                        tokens, pos, cache, sampling = carry
                        if ragged_k:
                            logits, cache = forward(
                                spec, params, tokens, pos, cache, None,
                                mesh=self.mesh, page_table=phys,
                                kv_page=page, q_lens=ones, write_table=wb,
                            )
                        else:
                            logits, cache = forward(
                                spec, params, tokens, pos, cache, None,
                                True, page_table=phys, kv_page=page,
                            )
                        toks, sampling = _sample_masked(
                            sampling, slot_ids, logits[:, -1, :], active,
                            None)
                        pos = jnp.where(active, pos + 1, pos)
                        return (toks[:, None], pos, cache, sampling), toks

                    (tok_next, pos_next, cache, sampling), toks_seq = \
                        lax.scan(step, (tokens, pos0, cache, sampling),
                                 None, length=k)
                    return (toks_seq.T, tok_next, pos_next, cache,
                            sampling)
                win = gather_kv_pages(cache, phys, page)
                if self.mesh is not None:
                    win = _pin_win_sharding(win, self.mesh, batch=True)

                def step(carry, _):
                    tokens, pos, win, sampling = carry
                    logits, win = forward(
                        spec, params, tokens, pos, win, None, False,
                    )
                    toks, sampling = _sample_masked(
                        sampling, slot_ids, logits[:, -1, :], active,
                        None)
                    pos = jnp.where(active, pos + 1, pos)
                    return (toks[:, None], pos, win, sampling), toks

                (tok_next, pos_next, win, sampling), toks_seq = lax.scan(
                    step, (tokens, pos0, win, sampling), None, length=k
                )
                if self.mesh is not None:
                    win = _pin_win_sharding(win, self.mesh, batch=False)
                return (toks_seq.T, tok_next, pos_next,
                        scatter_kv_pages(cache, win, wb, page), sampling)
        else:
            @partial(jax.jit, donate_argnums=(2, 5))
            def _decode_k(params, tokens, cache, pos0, slot_ids, sampling,
                          active):
                cache, restore = _window_cache(cache, window)

                def step(carry, _):
                    tokens, pos, cache, sampling = carry
                    logits, cache = forward(
                        spec, params, tokens, pos, cache, None,
                        self._use_kernel, mesh=self.mesh,
                    )
                    toks, sampling = _sample_masked(
                        sampling, slot_ids, logits[:, -1, :], active, None
                    )
                    pos = jnp.where(active, pos + 1, pos)
                    return (toks[:, None], pos, cache, sampling), toks

                (tok_next, pos_next, cache, sampling), toks_seq = lax.scan(
                    step, (tokens, pos0, cache, sampling), None, length=k
                )
                # tok_next/pos_next are returned so the next dispatch can
                # chain on device state without a host round trip
                return (toks_seq.T, tok_next, pos_next, restore(cache),
                        sampling)  # [S, k]

        self._decode_k_fns[("decode", k, window)] = _decode_k
        return _decode_k

    # ------------------------------------------- multihost dispatch funnel

    def _run(self, kind: str, payload: dict) -> Any:
        """Publish-then-execute: every device dispatch flows through here
        so a multihost leader's followers can replay the identical XLA
        program (parallel/multihost.py). Payloads carry only small host
        inputs; device state advances in place on every host."""
        if faultinject.ACTIVE:
            # chaos surface: a fault here behaves exactly like a device
            # dispatch blowing up — _loop's catch fails active slots
            # with one terminal error event each, scheduler survives.
            # The scope binds the wave's request ids so a delivered
            # fault lands as a span event on each affected trace
            with fault_scope(s.request.id for s in self.slots
                             if s.request is not None):
                faultinject.fire("engine.device_step")
        # cost-model accounting key: non-flight kinds account here, right
        # after the dispatch enqueues (flight kinds account at harvest,
        # where the span is known). Host-side dict math only — no syncs.
        cm = self._costmodel
        ckey = (costmodel.dispatch_key(kind, payload)
                if cm is not None and kind not in costmodel.FLIGHT_KINDS
                else None)
        ch = self.channel
        if ch is not None and not self.follower:
            # dense masks are bit-packed for the wire only; the local exec
            # keeps the raw ndarray (solo mode never pays the pack cost)
            wire = payload
            if isinstance(payload.get("masks"), np.ndarray):
                wire = {**payload, "masks": _pack_masks(payload["masks"])}
            # publish + device-enqueue under ONE critical section: the
            # follower replays records in published order, so the leader's
            # own XLA dispatch order must match it exactly or the
            # cross-host collectives inside the programs deadlock.
            # The envelope carries the wave's distributed trace ids
            # (OUTSIDE "data" — the codec whitelist governs replayed
            # payload fields only) so follower replays emit entries
            # joined to the leader's traces
            trace = sorted({s.request.trace_id for s in self.slots
                            if s.request is not None
                            and s.request.trace_id})
            with ch.order_lock:
                ch.publish(kind, {"model": self.tag, "data": wire,
                                  "trace": trace})
                out = self._dev_exec(kind, payload)
            if ckey is not None:
                cm.on_dispatch(kind, ckey)
            return out
        out = self._dev_exec(kind, payload)
        if ckey is not None:
            cm.on_dispatch(kind, ckey)
        return out

    def _dev_exec(self, kind: str, p: dict) -> Any:
        """Device-only work for one dispatch record. MUST be fully
        determined by (kind, payload) + engine construction — no reads of
        leader-side scheduler state — so follower replay stays lockstep."""
        # paged dispatches carry their page-table snapshots in the
        # payload ("pt"/"wb" int32 index arrays), so follower replay
        # needs no allocator state
        def tabs():
            return (jnp.asarray(p["pt"]), jnp.asarray(p["wb"]))

        def cap(fn, *args, **kw):
            # warmup capture hook: AOT-compile this exact variant and
            # record its XLA cost row (no-op outside capture mode —
            # the serving hot path pays one attribute check)
            cm = self._costmodel
            if cm is not None and cm.capturing:
                cm.capture(kind, costmodel.dispatch_key(kind, p),
                           fn, args, kw)

        if kind == "prefill":
            toks = jnp.asarray(p["toks"])
            pos0 = jnp.asarray(p["pos0"])
            sids = jnp.asarray(p["slot_ids"])
            soft = self._soft_dense(p.get("soft"), *p["toks"].shape)
            fn = self._prefill_fn(
                p.get("window", self.max_seq), p.get("ring", False))
            if self._paged:
                pt, wb = tabs()
                cap(fn, self.params, toks, self.cache, pos0, sids,
                    pt, wb, soft=soft)
                self.cache = fn(self.params, toks, self.cache, pos0,
                                sids, pt, wb, soft=soft)
                if self.draft is not None:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0,
                        sids, pt, wb,
                        jnp.full(toks.shape[:1], toks.shape[1],
                                 jnp.int32))
            else:
                cap(fn, self.params, toks, self.cache, pos0, sids,
                    soft=soft)
                self.cache = fn(self.params, toks, self.cache, pos0,
                                sids, soft=soft)
                if self.draft is not None:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0, sids
                    )
            return None
        if kind == "prefill_final":
            toks = jnp.asarray(p["toks"])
            pos0 = jnp.asarray(p["pos0"])
            sids = jnp.asarray(p["slot_ids"])
            masks = _unpack_masks(p["masks"])
            soft = self._soft_dense(p.get("soft"), *p["toks"].shape)
            reset = tuple(jnp.asarray(p["reset"][k]) for k in (
                "temperature", "top_k", "top_p", "min_p",
                "repeat_penalty", "freq_penalty", "presence_penalty",
                "repeat_last_n", "seeds", "has_seed",
                "typical_p", "mirostat", "mirostat_tau", "mirostat_eta"))
            fn = self._prefill_final_fn(
                p.get("window", self.max_seq), p.get("identity", False))
            args = [self.params, toks, self.cache, pos0, self.sampling,
                    sids, jnp.asarray(p["n_chunk"]),
                    jnp.asarray(p["tails"]), jnp.asarray(p["tail_lens"]),
                    masks, reset]
            if self._paged:
                pt, wb = tabs()
                args += [pt, wb]
            cap(fn, *args, soft=soft)
            toks_out, self.cache, self.sampling = fn(*args, soft=soft)
            if self.draft is not None:
                if self._paged:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0,
                        sids, pt, wb, jnp.asarray(p["n_chunk"]))
                else:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0, sids
                    )
            return toks_out
        if kind == "mixed":
            # fused mixed prefill+decode step: like prefill_final, a
            # pure device op with a scalar payload (token ids + per-row
            # index vectors only), so multihost followers replay it
            # like any other record
            toks = jnp.asarray(p["toks"])
            pos0 = jnp.asarray(p["pos0"])
            masks = _unpack_masks(p["masks"])
            soft = self._soft_dense(p.get("soft"), *p["toks"].shape)
            reset = tuple(jnp.asarray(p["reset"][k]) for k in (
                "temperature", "top_k", "top_p", "min_p",
                "repeat_penalty", "freq_penalty", "presence_penalty",
                "repeat_last_n", "seeds", "has_seed",
                "typical_p", "mirostat", "mirostat_tau", "mirostat_eta"))
            args = [self.params, toks, self.cache, pos0, self.sampling,
                    jnp.asarray(p["write_mask"]),
                    jnp.asarray(p["n_chunk"]),
                    jnp.asarray(p["sample_sids"]),
                    jnp.asarray(p["reset_sids"]), jnp.asarray(p["tails"]),
                    jnp.asarray(p["tail_lens"]), masks, reset]
            if self._paged:
                pt, wb = tabs()
                args += [pt, wb]
            fn = self._mixed_fn(p.get("window", self.max_seq))
            cap(fn, *args, soft=soft)
            toks_out, self.cache, self.sampling = fn(*args, soft=soft)
            if self.draft is not None:
                # mirror ONLY the prefill rows into the draft cache
                # (decode rows advance without draft writes, exactly as
                # on the decodek path)
                if self._paged:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0,
                        jnp.asarray(p["prefill_sids"]), pt,
                        jnp.asarray(p["wb_draft"]),
                        jnp.asarray(p["n_chunk"]))
                else:
                    self.draft_cache = self._draft_prefill_fn()(
                        self.draft[1], toks, self.draft_cache, pos0,
                        jnp.asarray(p["prefill_sids"]),
                    )
            return toks_out
        if kind == "decode1":
            masks = _unpack_masks(p["masks"])
            args = [self.params, jnp.asarray(p["tokens"]), self.cache,
                    jnp.asarray(p["pos0"]), self._all_slot_ids,
                    self.sampling, jnp.asarray(p["active"]), masks]
            if self._paged:
                args += list(tabs())
            cap(self._decode_fn, *args)
            toks, self.cache, self.sampling = self._decode_fn(*args)
            return toks
        if kind == "decodek":
            fn = self._decode_k_fn(p["k"], p["window"])
            if p["carry"] and self._dev_tokens is not None:
                tok_dev, pos_dev, act_dev = (
                    self._dev_tokens, self._dev_pos, self._dev_active
                )
            else:
                tok_dev = jnp.asarray(p["tokens"])
                pos_dev = jnp.asarray(p["pos0"])
                act_dev = jnp.asarray(p["active"])
            extra = list(tabs()) if self._paged else []
            cap(fn, self.params, tok_dev, self.cache, pos_dev,
                self._all_slot_ids, self.sampling, act_dev, *extra)
            batches = []
            for _ in range(p["depth"]):
                toks, tok_dev, pos_dev, self.cache, self.sampling = fn(
                    self.params, tok_dev, self.cache, pos_dev,
                    self._all_slot_ids, self.sampling, act_dev, *extra,
                )
                batches.append(toks)
            self._dev_tokens, self._dev_pos, self._dev_active = (
                tok_dev, pos_dev, act_dev
            )
            return batches
        if kind == "spec":
            fn = self._spec_decode_fn(p["kd"], p["rounds"])
            extra = list(tabs()) if self._paged else []
            D, Mt, J, _, _, self.cache, self.draft_cache = fn(
                self.params, self.draft[1], self.cache, self.draft_cache,
                jnp.asarray(p["tokens"]), jnp.asarray(p["pos0"]),
                jnp.asarray(p["active"]), *extra,
            )
            return D, Mt, J
        if kind == "spec_s":
            import dataclasses

            fn = self._spec_sampled_fn(p["kd"], p["rounds"])
            extra = list(tabs()) if self._paged else []
            D, Fin, J, rng, self.cache, self.draft_cache = fn(
                self.params, self.draft[1], self.sampling, self.cache,
                self.draft_cache, jnp.asarray(p["tokens"]),
                jnp.asarray(p["pos0"]), jnp.asarray(p["active"]), *extra,
            )
            self.sampling = dataclasses.replace(self.sampling, rng=rng)
            return D, Fin, J
        if kind == "kvcopy":
            # cross-slot prefix copy: pure device op with a scalar
            # payload, so it broadcasts to multihost followers like any
            # other dispatch record (no KV bytes cross the wire)
            src = jnp.asarray(p["src"], jnp.int32)
            dst = jnp.asarray(p["dst"], jnp.int32)
            fn = self._kv_copy_fn(p["n"], self.draft is not None)
            if self.draft is not None:
                cap(fn, self.cache, self.draft_cache, src, dst)
                self.cache, self.draft_cache = fn(
                    self.cache, self.draft_cache, src, dst)
            else:
                cap(fn, self.cache, src, dst)
                self.cache = fn(self.cache, src, dst)
            return None
        if kind == "embed":
            cache = KVCache.create(self.spec, 1, p["bucket"],
                                   self.cache.k.dtype)
            zeros = jnp.zeros((1,), jnp.int32)
            hidden, _ = self._hidden_fn(
                self.params, jnp.asarray(p["toks"]), cache, zeros, zeros
            )
            return hidden
        raise ValueError(f"unknown dispatch record kind: {kind!r}")

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        if self.follower:
            return  # replay-only: the follower loop drives _dev_exec
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # a closed engine must not leave stale occupancy on /metrics
        tm.ENGINE_SLOTS_BUSY.labels(model=self._mlabel).set(0)
        tm.ENGINE_QUEUE_DEPTH.labels(model=self._mlabel).set(0)
        tm.ENGINE_KV_UTIL.labels(model=self._mlabel).set(0.0)
        tm.ENGINE_KV_RESIDENT_PREFIX.labels(model=self._mlabel).set(0.0)
        tm.ENGINE_MESH_DEVICES.labels(model=self._mlabel).set(0)
        if self._paged:
            tm.ENGINE_KV_PAGES_IN_USE.labels(model=self._mlabel).set(0)
            tm.ENGINE_KV_PAGES_SHARED.labels(model=self._mlabel).set(0)
        if self._tier is not None:
            # land every in-flight tier transfer (pins release, staged
            # fetches abandon) so pool/tier leak checks stay clean
            self._tier.close()
            for tname in ("hbm", "host", "disk"):
                tm.ENGINE_KV_TIER_PAGES.labels(
                    model=self._mlabel, tier=tname).set(0)
        if self._pager is not None:
            # abort any in-flight page move, release the host mirror,
            # deregister from the cross-engine LRU
            self._pager.close()
            for tname in ("hot", "warm"):
                tm.ENGINE_WEIGHT_PAGES.labels(
                    model=self._mlabel, tier=tname).set(0)
        tm.ENGINE_MFU.labels(model=self._mlabel).set(0.0)
        if self._ledger is not None:
            self._ledger.reset_gauges()
        if self.mesh is not None:
            # release the process-wide meshed gate so a later unmeshed
            # engine regains the fused int8 kernel (single-owner rule)
            from ..models import quant

            quant.set_meshed_serving(False)

    def _active_exemplar(self) -> Optional[dict]:
        """Exemplar labels for a batch-level latency sample: the first
        active slot's trace id (a batch observation has no single
        owner; one representative trace is what OM exemplars carry)."""
        for s in self.slots:
            if (s.active and s.request is not None
                    and s.request.trace_id):
                return {"trace_id": s.request.trace_id}
        return None

    def cost_stats(self) -> Optional[dict]:
        """Cost-model summary (MFU, per-kind roofline) for
        /backend/monitor; None when LOCALAI_COSTMODEL=off."""
        return (self._costmodel.stats()
                if self._costmodel is not None else None)

    def hbm_stats(self) -> Optional[dict]:
        """HBM-ledger snapshot for /backend/monitor; None when
        LOCALAI_HBM_LEDGER=off."""
        return (self._ledger.snapshot()
                if self._ledger is not None else None)

    def predicted_drain_s(self) -> Optional[float]:
        """Public, any-thread view of the cost-model queue-drain
        prediction (telemetry/digest.py reads it for the fleet
        heartbeat); None when cost scheduling is off or the predictor
        has no rates yet."""
        with self._lock:
            return self._predicted_drain_s()

    def prefix_summary(self) -> list:
        """Scheduler-cached top-k prefix-hash summary (see
        PrefixIndex.summary) — an atomic tuple swap away from the
        scheduler thread, safe to read from any thread."""
        return [[h, n] for h, n in self._prefix_summary]

    def _warmup_signature(self) -> str:
        """Fingerprint of everything the warmup variant set depends on:
        model geometry, engine shape knobs, backend/device kind. Two
        engines with equal signatures compile the identical HLO set."""
        import hashlib

        mesh_desc = (tuple(sorted(self.mesh.shape.items()))
                     if self.mesh is not None else None)
        dev = jax.devices()[0]
        blob = repr((
            repr(self.spec), self.n_slots, self.max_seq,
            tuple(self.prefill_buckets),
            str(jnp.dtype(self.cache.k.dtype)), self.decode_steps,
            self.latency_target_ms, self.sampling.window,
            self._use_kernel, mesh_desc, jax.default_backend(),
            getattr(dev, "device_kind", ""), jax.__version__,
            self._mixed,  # the mixed dispatcher adds its own variants
            # the paged pool changes every variant's cache geometry
            self._paged, self._page, self.kv_pages,
            # ragged mode collapses the window ladder to one full-width
            # variant per shape — a different compile set entirely
            self._ragged,
        ))
        return hashlib.sha256(blob.encode()).hexdigest()[:20]

    def _warmup_marker_path(self) -> Optional[str]:
        """Marker file recording a COMPLETED warmup of this signature in
        the persistent compilation cache dir (None when no persistent
        cache is configured — skipping warmup is only safe when a
        mid-request 'compile' would be a fast cache load, not a real
        compile)."""
        import os

        try:
            cache_dir = jax.config.jax_compilation_cache_dir
        except AttributeError:
            cache_dir = None
        if not cache_dir:
            return None
        return os.path.join(
            cache_dir, f"warmup-{self._warmup_signature()}.ok")

    def warmup(self) -> None:
        """Compile the serving dispatch-variant set up front.

        At 8B scale one jit variant costs ~13s to compile; a cold
        variant landing mid-request is a 13-second TTFT outlier
        (measured through the HTTP bench: ragged arrivals hit group
        sizes the first admission wave never used). All-pad dispatches
        — every row pointing at the out-of-bounds sentinel slot id, or
        an all-inactive scan — exercise the identical jit shapes
        without touching engine state, so this is safe before serving.
        With the persistent compilation cache the cost after a code
        change is one cold pass; afterwards seconds.

        Even cache-hit warmups are not free at 8B scale: every variant
        still TRACES its python graph and round-trips the cache
        (seconds apiece across dozens of variants — load wall time the
        r5 bench measured but could not attribute). When a previous
        load of the IDENTICAL signature completed a warmup into the
        configured persistent cache (marker file), the whole pass is
        skipped: any variant a request later touches jit-compiles as a
        fast persistent-cache load instead of a cold compile. Kill
        switch: LOCALAI_WARMUP_REUSE=off (e.g. after pruning the cache
        dir without removing the warmup markers)."""
        import os

        t0 = time.perf_counter()
        marker = self._warmup_marker_path()
        reuse_ok = knobs.flag("LOCALAI_WARMUP_REUSE")
        if marker is not None and reuse_ok and os.path.exists(marker):
            # the capture pass rode the skipped warmup, so reload the
            # cost rows the original warmup exported — same signature,
            # same HLO set, same XLA cost rows. A marker without its
            # sidecar (written before sidecars existed, or pruned) would
            # leave the predictor blind for the whole process, so fall
            # through to a full pass ONCE — under the populated compile
            # cache that pass is trace + cache loads, and completing it
            # rewrites marker + sidecar.
            cm = self._costmodel
            restored = -1
            if cm is not None:
                try:
                    with open(marker + ".cost.json") as f:
                        restored = cm.import_rows(json.load(f))
                except (OSError, ValueError):
                    restored = -1
            if cm is None or restored >= 0:
                self.warmup_reused = True
                if restored > 0:
                    log.info("warmup reuse: %d cost rows restored",
                             restored)
                tm.ENGINE_WARMUP_SECONDS.labels(
                    model=self._mlabel, mode="reuse").set(
                    time.perf_counter() - t0)
                log.info("warmup skipped: variant set %s already in "
                         "the persistent compile cache",
                         os.path.basename(marker))
                return
            log.info("warmup reuse declined: cost sidecar missing for "
                     "%s — re-capturing", os.path.basename(marker))
        n_variants = 0

        def _warm(kind, payload):
            # every warmup dispatch compiles exactly one (fn, shape)
            # jit variant; the count is the series the ragged unification
            # collapses (engine_dispatch_compile_variants_count).
            # Capture mode rides the pass: _dev_exec records each
            # variant's XLA cost row (telemetry/costmodel.py) while the
            # pad dispatch itself stays unaccounted (it is not traffic)
            nonlocal n_variants
            n_variants += 1
            cm = self._costmodel
            if cm is None:
                return self._run(kind, payload)
            cm.capturing = True
            try:
                return self._run(kind, payload)
            finally:
                cm.capturing = False

        W = self.sampling.window
        pad_reset = self._reset_columns([], 1)
        if self._ragged:
            # ragged paged attention: tables are full-width, so there is
            # NO window ladder — one variant per token-budget shape
            win_ladder = [self.max_seq]
        else:
            win_ladder = []
            w = self._window_bucket(1)
            while w < self.max_seq:
                win_ladder.append(w)
                w *= 2
            win_ladder.append(self.max_seq)
        for bucket in self.prefill_buckets:
            id_capable = (bucket * self.n_slots
                          <= self._prefill_group_tokens)
            # (B, window, identity) variants matching _enqueue's split:
            # bursts -> ONE identity shape per live-context window (no
            # (window, bucket) shape can cold-compile mid-request);
            # trickles -> the small legacy sizes below the identity
            # threshold at the pinned max_seq window
            variants: list[tuple[int, int, bool]] = []
            if id_capable:
                # an identity final dispatch's window covers max(pos0)
                # + bucket + 1, so ladder rungs below
                # _window_bucket(bucket + 1) can never be dispatched —
                # compiling them was pure dead warmup cost (at 8B,
                # seconds per variant)
                min_w = self._window_bucket(bucket + 1)
                variants += [(self.n_slots, w, True) for w in win_ladder
                             if w >= min_w]
            cap = self._prefill_group_cap(bucket)
            sizes = {cap}
            b = 1
            while b < cap:
                sizes.add(b)
                b *= 8
            legacy_cap = (self._legacy_prefill_max if id_capable
                          else cap)
            variants += [(B, self.max_seq, False) for B in sorted(sizes)
                         if B <= legacy_cap]
            for B, win, identity in variants:
                reset = {k: np.repeat(v, B, axis=0)
                         for k, v in pad_reset.items()}
                payload = {
                    "toks": np.zeros((B, bucket), np.int32),
                    "pos0": np.zeros((B,), np.int32),
                    "slot_ids": np.full((B,), self.n_slots,
                                        np.int32),
                    "n_chunk": np.ones((B,), np.int32),
                    "tails": np.zeros((B, W), np.int32),
                    "tail_lens": np.zeros((B,), np.int32),
                    "masks": None, "reset": reset, "soft": None,
                    "window": win,
                    "identity": identity,
                }
                if self._paged:
                    # all-trash tables: garbage reads are masked,
                    # writebacks drop — engine state stays untouched
                    wp = win // self._page
                    payload["pt"] = np.zeros((B, wp), np.int32)
                    payload["wb"] = np.zeros((B, wp), np.int32)
                _warm("prefill_final", payload)
        if self.max_seq > self.prefill_buckets[-1]:
            # long prompts chunk through the "prefill" fn at live-context
            # window buckets — compile those too, or the first long
            # prompt stalls on a mid-request jit. Chunk dispatches are
            # always full-bucket wide, so their windows start at the
            # bucket's own window bucket (window >= n_past + bucket).
            if self._ragged:
                windows = {self.max_seq}
            else:
                w = self._window_bucket(self.prefill_buckets[-1])
                windows = set()
                while w < self.max_seq:
                    windows.add(w)
                    w *= 2
                windows.add(self.max_seq)
            seq_ax = (self.mesh.shape.get("seq", 1)
                      if self.mesh is not None else 1)
            rings = {False}
            if (seq_ax > 1 and not self.spec.sliding_window
                    and self.prefill_buckets[-1] % seq_ax == 0):
                rings.add(True)  # the seq-sharded first-chunk variant
            for w in sorted(windows):
                for ring in sorted(rings):
                    payload = {
                        "toks": np.zeros((1, self.prefill_buckets[-1]),
                                         np.int32),
                        "pos0": np.zeros((1,), np.int32),
                        "slot_ids": np.full((1,), self.n_slots,
                                            np.int32),
                        "soft": None, "window": w, "ring": ring,
                    }
                    if self._paged:
                        wp = w // self._page
                        payload["pt"] = np.zeros((1, wp), np.int32)
                        payload["wb"] = np.zeros((1, wp), np.int32)
                    _warm("prefill", payload)
        if self._mixed:
            # mixed prefill+decode step variants: one per (bucket that
            # fits the identity budget, live-context window). All-pad
            # rows (write_mask False, sentinel sids) exercise the
            # identical jit shapes without touching engine state.
            S = self.n_slots
            prev_bucket = 0
            for bucket in self._mixed_buckets:
                reset = {k: np.repeat(v, S, axis=0)
                         for k, v in pad_reset.items()}
                # a mixed dispatch only selects this bucket when some
                # prefill row's remainder EXCEEDS the previous bucket,
                # so its window covers at least prev_bucket + 2 —
                # smaller ladder rungs can never be dispatched for this
                # bucket (dead compile cost pruned; in ragged mode the
                # ladder is already the single full-width rung)
                min_w = self._window_bucket(prev_bucket + 2)
                prev_bucket = bucket
                for w in [w for w in win_ladder if w >= min_w]:
                    payload = {
                        "toks": np.zeros((S, bucket), np.int32),
                        "pos0": np.zeros((S,), np.int32),
                        "n_chunk": np.ones((S,), np.int32),
                        "write_mask": np.zeros((S,), bool),
                        "sample_sids": np.full((S,), S, np.int32),
                        "reset_sids": np.full((S,), S, np.int32),
                        "tails": np.zeros((S, W), np.int32),
                        "tail_lens": np.zeros((S,), np.int32),
                        "masks": None, "reset": reset, "soft": None,
                        "prefill_sids": np.full((S,), S, np.int32),
                        "window": w,
                    }
                    if self._paged:
                        wp = w // self._page
                        payload["pt"] = np.zeros((S, wp), np.int32)
                        payload["wb"] = np.zeros((S, wp), np.int32)
                        payload["wb_draft"] = np.zeros((S, wp), np.int32)
                    _warm("mixed", payload)
        if self._prefix_enabled:
            # cross-slot KV copy variants (cheap compiles — pure DUS,
            # no matmuls — but a mid-admission stall is still a stall);
            # src == dst == 0 is a self-copy no-op on device state
            if self._paged:
                # paged copies are always whole-page: ONE variant
                _warm("kvcopy", {"src": 0, "dst": 0, "n": self._page})
            else:
                for w in win_ladder:
                    _warm("kvcopy", {"src": 0, "dst": 0, "n": w})
        S = self.n_slots
        inactive = {
            "tokens": np.zeros((S, 1), np.int32),
            "pos0": np.zeros((S,), np.int32),
            "active": np.zeros((S,), bool),
        }
        ks = self._warm_ks
        if self._use_kernel or self._ragged:
            windows_d = {self.max_seq}  # ragged: one variant
        else:
            windows_d = set()
            w = 256
            while w < self.max_seq:
                windows_d.add(w)
                w *= 2
            windows_d.add(self.max_seq)
        for k in sorted(ks):
            if k > 1:
                for w in sorted(windows_d):
                    payload = {
                        "k": k, "window": w, "depth": 1, "carry": False,
                        **inactive,
                    }
                    if self._paged:
                        wp = w // self._page
                        payload["pt"] = np.zeros((S, wp), np.int32)
                        payload["wb"] = np.zeros((S, wp), np.int32)
                    _warm("decodek", payload)
        payload = {**inactive, "masks": None}
        if self._paged:
            wp = self.max_seq // self._page
            payload["pt"] = np.zeros((S, wp), np.int32)
            payload["wb"] = np.zeros((S, wp), np.int32)
        _warm("decode1", payload)
        self._dev_epoch = -1  # warmup carries are not serving state
        # block until every warmup compile retires so the first real
        # request measures serving, not the compiler
        jax.block_until_ready(self.cache.k)
        # the variant-explosion kill made visible: each warmup dispatch
        # compiled exactly one (fn, shape) variant, so this count IS the
        # jit-cache population the ragged unification collapses
        self.warmup_variants = n_variants
        tm.ENGINE_DISPATCH_VARIANTS.labels(model=self._mlabel).set(
            n_variants)
        tm.ENGINE_WARMUP_SECONDS.labels(
            model=self._mlabel, mode="cold").set(time.perf_counter() - t0)
        if marker is not None:
            # record the completed variant set so the next load of this
            # exact signature skips the whole pass (best effort: losing
            # the marker only costs the speedup)
            try:
                # cost rows first: a marker without its sidecar would
                # reuse-skip future warmups with no way to restore the
                # predictor's cost table
                cm = self._costmodel
                if cm is not None:
                    rows = cm.export_rows()
                    if rows:
                        with open(marker + ".cost.json", "w") as f:
                            json.dump(rows, f)
                with open(marker, "w") as f:
                    f.write("ok")
            except OSError:
                pass

    def submit(self, req: GenRequest) -> queue.SimpleQueue:
        """Queue a request; returns the event stream queue."""
        return self.submit_many([req])[0]

    def submit_many(
        self, reqs: list[GenRequest],
        outs: Optional[list[queue.SimpleQueue]] = None,
    ) -> list[queue.SimpleQueue]:
        """Queue a burst of requests under ONE lock acquisition, so the
        scheduler admits them as a single wave. Beyond fairness, this
        makes the batched final-prefill group size deterministic (the
        per-request submit path can race admission into odd-sized groups,
        each a fresh jit shape). ``outs`` lets a caller supply the event
        queues (the DisaggRouter resubmits a migrated request onto the
        client's ORIGINAL stream queue — no forwarding hop per token)."""
        if outs is None:
            outs = [queue.SimpleQueue() for _ in reqs]
        ok: list[tuple[GenRequest, queue.SimpleQueue]] = []
        for req, out in zip(reqs, outs):
            if len(req.prompt_ids) >= self.max_seq:
                out.put(StreamEvent(
                    done=True, finish_reason="error",
                    error=f"prompt ({len(req.prompt_ids)} tokens) exceeds "
                          f"context size {self.max_seq}"))
                # terminal-at-submit requests still get a complete trace
                # entry: the HTTP layer may have opened one at receive
                TRACER.event(req.id, "done", model=self._mlabel)
                TRACER.annotate(req.id, "terminal", outcome="error",
                                detail="prompt exceeds context")
                TRACER.finish(req.id, status="error")
            elif not req.prompt_ids:
                out.put(StreamEvent(done=True, finish_reason="error",
                                    error="empty prompt"))
                TRACER.event(req.id, "done", model=self._mlabel)
                TRACER.annotate(req.id, "terminal", outcome="error",
                                detail="empty prompt")
                TRACER.finish(req.id, status="error")
            else:
                ok.append((req, out))
        if ok:
            # arrival bookkeeping only for ADMITTED work: a stream of
            # rejected requests (empty/over-context prompts) must not
            # engage the burst clamp or the prefill-formation hold —
            # they contribute nothing a prefill could serve (ADVICE
            # r5 #4)
            now = time.perf_counter()
            for req, _ in ok:
                if req.disagg is not None and req.t_submit:
                    # migrated resubmit: the request keeps the t_submit/
                    # deadline the router stamped at ORIGINAL arrival, so
                    # TTFT and deadline enforcement stay end-to-end
                    # across the prefill→migrate→decode relay
                    if req.deadline:
                        self._deadlines_armed = True
                    continue
                req.t_submit = now
                budget = req.timeout_s or self._default_deadline_s
                if budget > 0:
                    req.deadline = now + budget
                    self._deadlines_armed = True
            shed: list[tuple[GenRequest, queue.SimpleQueue]] = []
            with self._lock:
                if self.max_queue > 0:
                    # bounded admission: refuse the overflow NOW with a
                    # terminal shed event + backoff hint, instead of
                    # letting queue latency grow without bound. Newest
                    # arrivals shed first — earlier ones were promised
                    # a place the moment they fit
                    room = self.max_queue - len(self._pending)
                    if room < len(ok):
                        ok, shed = ok[:max(0, room)], ok[max(0, room):]
                    if shed:
                        retry_s = self._retry_after_s()
                self._pending.extend(ok)
                if ok:
                    self._last_arrival = now
                    self._arrivals.append(self._last_arrival)
                depth = len(self._pending)
                self._lock.notify_all()
            for req, out in shed:
                if req.disagg is not None:
                    # a shed migrated resubmit must free its interchange
                    # blocks (idempotent KVHandoff.release)
                    req.disagg.release()
                out.put(StreamEvent(
                    done=True, finish_reason="shed",
                    error=f"admission queue full "
                          f"({self.max_queue} queued); retry later",
                    retry_after_s=retry_s))
                TRACER.event(req.id, "shed", t=now, model=self._mlabel)
                TRACER.annotate(req.id, "terminal", t=now, outcome="shed",
                                retry_after_s=round(retry_s, 3))
                TRACER.finish(req.id, status="shed")
                tm.ENGINE_REQUESTS.labels(model=self._mlabel,
                                          reason="shed").inc()
                tm.ENGINE_REQUESTS_SHED.labels(
                    model=self._mlabel, reason="queue_full").inc()
            for req, _ in ok:
                TRACER.event(req.id, "queue", t=now, model=self._mlabel)
                # adopt the trace's distributed id (minted at the HTTP
                # edge, or just now by the auto-opened trace): dispatch
                # records and follower replays carry it from here on
                if not req.trace_id:
                    req.trace_id = TRACER.trace_id_of(req.id)
            tm.ENGINE_QUEUE_DEPTH.labels(model=self._mlabel).set(depth)
            if self._autostart:
                self.start()
        return outs

    def generate(self, req: GenRequest) -> StreamEvent:
        """Blocking helper: drain the stream, return the final event."""
        q = self.submit(req)
        while True:
            ev = q.get()
            if ev.done:
                return ev

    def cancel(self, request_id: str) -> None:
        """Release a queued or in-flight request (ref: llama.cpp task
        cancel on client disconnect — the slot frees at the next
        scheduler iteration; its stream gets a final "cancelled"
        event). A cancel that RACES AHEAD of submit is retained (with an
        expiry) so the late-arriving request is still dropped."""
        with self._lock:
            self._cancelled[request_id] = time.perf_counter()
            self._lock.notify_all()

    _CANCEL_TTL_S = 300.0  # unmatched cancel ids expire (leak bound)

    def _retry_after_s(self) -> float:
        """Suggested client backoff for a shed request. With cost
        scheduling on, the PREDICTED drain time of the actual queue
        contents (prompt lengths and token budgets the predictor can
        cost) — a hint that tracks what is really queued instead of
        what recently happened. Falls back to the p90 of recently
        observed admission queue waits when the predictor has nothing,
        both clamped to the same sane window. Caller holds self._lock."""
        drain = self._predicted_drain_s()
        if drain is not None:
            return drain
        ws = sorted(self._queue_waits)
        if not ws:
            return 1.0
        p90 = ws[min(len(ws) - 1, int(0.9 * len(ws)))]
        return min(30.0, max(0.5, p90))

    def _predicted_drain_s(self) -> Optional[float]:
        """Predicted seconds until the CURRENT queue drains: per queued
        request, predicted prefill (per-token rate x prompt length)
        plus predicted decode (per-step rate x token budget), spread
        across the slots, clamped to the Retry-After window. None when
        cost scheduling is off or the predictor has no rates yet (the
        caller falls back to historical p90). Caller holds self._lock."""
        if not self._cost_sched_on():
            return None
        cm = self._costmodel
        tok_ms = cm.prefill_token_ms()
        step_ms = (self._step_ms if self._step_ms > 0.0
                   else cm.decode_step_ms())
        if tok_ms is None and step_ms is None:
            return None
        total_ms = 0.0
        for req, _ in self._pending:
            if tok_ms is not None:
                total_ms += tok_ms * len(req.prompt_ids)
            if step_ms is not None:
                total_ms += step_ms * max(0, req.max_tokens)
        return min(30.0, max(0.5, total_ms / 1e3
                             / max(1, self.n_slots)))

    def _purge_expired_cancels(self, now: float) -> int:
        """Drop race-ahead cancel ids older than _CANCEL_TTL_S; returns
        how many expired. Caller holds self._lock. Called from BOTH the
        cancellation sweep and the idle wait in _loop — an idle engine
        never runs step(), so without the idle-path purge a burst of
        unmatched cancels would sit for the engine's lifetime."""
        # lint: holds self._lock
        expired = [r for r, t in self._cancelled.items()
                   if now - t > self._CANCEL_TTL_S]
        for rid in expired:
            del self._cancelled[rid]
        return len(expired)

    def _apply_cancellations(self) -> None:
        with self._lock:
            if not self._cancelled:
                return
            now = time.perf_counter()
            n_expired = self._purge_expired_cancels(now)
            cancelled = self._cancelled
            # queued requests: drop before admission
            still = []
            dropped = []
            for req, out in self._pending:
                if req.id in cancelled:
                    del cancelled[req.id]
                    self._deferred.pop(req.id, None)
                    out.put(StreamEvent(done=True,
                                        finish_reason="cancelled"))
                    dropped.append(req.id)
                else:
                    still.append((req, out))
            self._pending = still
        if n_expired:
            tm.ENGINE_CANCELLATIONS.labels(
                model=self._mlabel, reason="expired").inc(n_expired)
        for rid in dropped:
            TRACER.event(rid, "done")
            TRACER.annotate(rid, "terminal", outcome="cancelled",
                            stage="queued")
            TRACER.finish(rid, status="cancelled")
            tm.ENGINE_REQUESTS.labels(model=self._mlabel,
                                      reason="cancelled").inc()
            tm.ENGINE_CANCELLATIONS.labels(model=self._mlabel,
                                           reason="client").inc()
        hit = [s for s in self.slots
               if s.active and s.request is not None
               and s.request.id in cancelled]
        for s in hit:
            with self._lock:
                cancelled.pop(s.request.id, None)
            self._finish(s, "cancelled")

    def _apply_deadlines(self) -> None:
        """Terminate requests whose deadline has passed: queued ones get
        an immediate terminal event (no slot was ever held), decoding
        ones finish through the normal slot path with whatever partial
        text they produced. With cost scheduling on, queued requests
        whose PREDICTED completion already exceeds their deadline are
        rejected early (stage="queued_predicted") instead of burning
        prefill on work that cannot land in time. Gated on the sticky
        _deadlines_armed flag so deadline-free serving skips the sweep
        entirely."""
        if not self._deadlines_armed:
            return
        now = time.perf_counter()
        expired: list[tuple[str, str]] = []  # (request id, stage)
        # predicted-completion rejection: with cost scheduling on, a
        # queued request whose PREDICTED first token already falls past
        # its deadline is rejected now instead of wasting prefill on it.
        # The prediction is the optimistic bound (prefill alone, as if
        # a slot were free this instant), so a request this rejects
        # could never have produced a token in time.
        tok_ms = (self._costmodel.prefill_token_ms()
                  if self._cost_sched_on() else None)
        with self._lock:
            still = []
            for req, out in self._pending:
                if req.deadline and now >= req.deadline:
                    self._deferred.pop(req.id, None)
                    if req.disagg is not None:
                        req.disagg.release()
                    out.put(StreamEvent(
                        done=True, finish_reason="deadline_exceeded",
                        error="deadline exceeded while queued"))
                    expired.append((req.id, "queued"))
                elif (req.deadline and tok_ms is not None
                      and req.disagg is None
                      and now + tok_ms * len(req.prompt_ids) / 1e3
                      >= req.deadline):
                    # (migrated resubmits are exempt: their prompt is
                    # already in pages — pricing a re-prefill against
                    # the deadline would reject work that needs none)
                    self._deferred.pop(req.id, None)
                    out.put(StreamEvent(
                        done=True, finish_reason="deadline_exceeded",
                        error="predicted completion exceeds deadline "
                              "(prefill alone overruns it)"))
                    expired.append((req.id, "queued_predicted"))
                else:
                    still.append((req, out))
            self._pending = still
        for rid, stage in expired:
            TRACER.event(rid, "done")
            TRACER.annotate(rid, "terminal", outcome="deadline_exceeded",
                            stage=stage)
            TRACER.finish(rid, status="deadline_exceeded")
            tm.ENGINE_REQUESTS.labels(model=self._mlabel,
                                      reason="deadline_exceeded").inc()
            tm.ENGINE_DEADLINE_EXCEEDED.labels(
                model=self._mlabel, stage=stage).inc()
        hit = [s for s in self.slots
               if s.active and s.request is not None
               and s.request.deadline and now >= s.request.deadline]
        for s in hit:
            tm.ENGINE_DEADLINE_EXCEEDED.labels(
                model=self._mlabel, stage=self._deadline_stage).inc()
            self._finish(s, "deadline_exceeded")

    # ------------------------------------------------------------- scheduler

    def _loop(self) -> None:
        while True:
            if not self._has_work():
                # TRUE idle transition: step() will not run again until
                # new work arrives, so publish pending prefix-index
                # changes now — the final harvest of a wave would
                # otherwise never reach the gossiped prefix summary
                # and the member's digest would advertise the
                # PREVIOUS request's residency until the next
                # admission. (Unlocked peek: this thread is the only
                # mutator of slots/flights; a submit racing in merely
                # makes the refresh redundant, never wrong.)
                self._refresh_prefix_summary(force=True)
            with self._lock:
                while not self._stop and not self._has_work():
                    self._lock.wait(timeout=0.5)
                    if self._cancelled:
                        # idle-path purge: step() never runs while idle,
                        # so race-ahead cancels must age out here
                        n = self._purge_expired_cancels(
                            time.perf_counter())
                        if n:
                            tm.ENGINE_CANCELLATIONS.labels(
                                model=self._mlabel,
                                reason="expired").inc(n)
                if self._stop:
                    return
            try:
                self.step()
            except Exception as e:  # engine must survive; fail active slots
                self._flights.clear()
                if hbm_ledger.looks_like_oom(e):
                    # device allocation failure: write the forensics
                    # file BEFORE failing the slots, so the autopsy
                    # captures the state that OOMed (best-effort — dump
                    # never raises)
                    hbm_ledger.dump_post_mortem(
                        self.state_dir, self._mlabel, e,
                        ledger=self._ledger,
                        pool_stats=(self._pool.stats()
                                    if self._pool is not None else None),
                        tier_stats=(self._tier.stats()
                                    if self._tier is not None else None),
                        weight_stats=(self._pager.stats()
                                      if self._pager is not None
                                      else None))
                self._fail_all(f"engine step error: {e!r}")

    def _has_work(self) -> bool:
        return (bool(self._pending) or bool(self._flights)
                or any(s.active for s in self.slots))

    def _fail_all(self, msg: str) -> None:
        for s in self.slots:
            if s.active and s.out is not None:
                if s.request is not None:
                    TRACER.event(s.request.id, "done")
                    # the step error (a real device failure or an
                    # injected fault — the message says which) becomes
                    # a span event on every trace it terminated; the
                    # trace commits BEFORE the terminal stream event so
                    # a consumer woken by it observes the final status
                    TRACER.annotate(s.request.id, "terminal",
                                    outcome="error", detail=msg)
                    TRACER.finish(s.request.id, status="error")
                    tm.ENGINE_REQUESTS.labels(model=self._mlabel,
                                              reason="error").inc()
                    tm.ENGINE_PREEMPTIONS.labels(model=self._mlabel).inc()
                s.out.put(StreamEvent(done=True, finish_reason="error",
                                      error=msg))
                self._release(s)

    # lint: region hot_path
    def step(self) -> None:
        """One scheduler iteration (ref: update_slots, grpc-server.cpp:1639).

        Async pipeline shape: every device dispatch is ENQUEUED without
        waiting for its results — JAX dispatch, the device work, and
        the host<->device transfer all pipeline — and results are
        harvested when their device arrays turn ready. Admission
        therefore never waits behind an in-flight prefill's download,
        and a deep burst's prefill groups overlap: TTFT for group N is
        the device compute of groups 1..N plus one transfer, not N
        serialized (compute + transfer) blocks. (r5 measurement note:
        the tunnel's dispatch/readiness floor is ~0.1 ms — flight
        latency is real device-queue time, so the pipelining hides
        QUEUE time, and keeping the queue clean around latency-critical
        dispatches matters more than wire round trips.)"""
        self._apply_cancellations()
        self._apply_deadlines()
        self._admit()
        harvested = self._harvest()
        dispatched = self._dispatch()
        self._update_gauges()
        if not (harvested or dispatched):
            self._wait_for_event()

    def _refresh_prefix_summary(self, force: bool = False) -> None:
        """Recompute the gossiped prefix top-k when the refresh
        interval elapsed (or ``force``, on the idle transition).
        Registrations otherwise update only on admission waves, so the
        summary first syncs the index against the live slot tokens;
        the rehash itself is revision-gated, so an unchanged index
        costs only the (vectorized, usually early-out) sync diff."""
        nowp = time.monotonic()
        if not force and nowp - self._prefix_summary_t < knobs.float_(
                "LOCALAI_PREFIX_SUMMARY_S"):
            return
        if self._prefix_enabled:
            self._prefix_index.sync(
                (s.idx, s.cache_tokens) for s in self.slots)
        self._prefix_summary_t = nowp
        if self._prefix_index.revision == self._prefix_summary_rev:
            return
        self._prefix_summary_rev = self._prefix_index.revision
        self._prefix_summary = self._prefix_index.summary(
            knobs.int_("LOCALAI_DIGEST_TOPK"))

    def _update_gauges(self) -> None:
        """Scheduler-state gauges, refreshed once per iteration from
        values the scheduler already holds on the host (no device syncs;
        three lock-guarded stores per ms-scale iteration)."""
        m = self._mlabel
        busy = sum(1 for s in self.slots if s.active)
        tm.ENGINE_SLOTS_BUSY.labels(model=m).set(busy)
        tm.ENGINE_QUEUE_DEPTH.labels(model=m).set(len(self._pending))
        # timeline counter samples: same host scalars, per-iteration
        # cadence (one ring slot each — never per event/per request)
        FLIGHT.sample("queue_depth", "scheduler", len(self._pending))
        FLIGHT.sample("slots_busy", "scheduler", busy)
        FLIGHT.update_gauge()
        used = sum(s.n_past for s in self.slots if s.active)
        tm.ENGINE_KV_UTIL.labels(model=m).set(
            used / float(self.n_slots * self.max_seq))
        # reusable-but-idle KV is real capacity the cross-slot cache can
        # serve: count resident prefix tokens across ALL slots (a free
        # slot's resident prefix is invisible to ENGINE_KV_UTIL)
        live_tokens = sum(len(s.cache_tokens) for s in self.slots)
        tm.ENGINE_KV_RESIDENT_PREFIX.labels(model=m).set(
            float(live_tokens))
        if self._paged:
            st = self._pool.stats()
            tm.ENGINE_KV_PAGES_IN_USE.labels(model=m).set(st.in_use)
            tm.ENGINE_KV_PAGES_SHARED.labels(model=m).set(st.shared)
            FLIGHT.sample("kv_pages_in_use", "scheduler", st.in_use)
            # HBM actually allocated per live (resident) token — the
            # series that shows paging tracking expected instead of
            # worst-case context (dense equivalent: max_seq / mean ctx
            # x this value)
            c = self.cache
            tok_bytes = 2 * c.k.dtype.itemsize * c.k.shape[0] \
                * c.k.shape[-1]
            if c.quantized:
                tok_bytes += 2 * 4 * c.k.shape[0]  # f32 row scales
            tm.ENGINE_KV_HBM_PER_TOKEN.labels(model=m).set(
                float(st.in_use * self._page * tok_bytes)
                / max(live_tokens, 1))
            # allocator outcome counters (fresh/shared/cow) sync from
            # the pool's host tallies; reclaimed/exhausted increment at
            # their call sites
            for outcome, v in self._pool.allocs.items():
                prev = self._alloc_sync.get(outcome, 0)
                if v > prev:
                    tm.ENGINE_KV_PAGE_ALLOC.labels(
                        model=m, outcome=outcome).inc(v - prev)
                    self._alloc_sync[outcome] = v
            if self._tier is not None:
                # tier residency gauges: host scalars the tier already
                # tallies (no device syncs, one store per tier)
                tp = self._tier.tier_pages(st.in_use)
                for tname, v in tp.items():
                    tm.ENGINE_KV_TIER_PAGES.labels(
                        model=m, tier=tname).set(v)
                FLIGHT.sample("kv_host_pages", "scheduler", tp["host"])
        if self._pager is not None:
            # weight-tier residency: host scalars the pager tallies
            # under its own lock (a promotion's hot count climbs with
            # the commit cursor)
            wp = self._pager.tier_pages()
            for tname, v in wp.items():
                tm.ENGINE_WEIGHT_PAGES.labels(model=m, tier=tname).set(v)
        if not any(s.state is SlotState.DECODE for s in self.slots):
            # decode-stall gaps are only meaningful while a slot
            # decodes; reset the clock when the decode set drains
            self._last_decode_adv = 0.0
        # fleet-digest prefix gossip: recompute the top-k summary every
        # LOCALAI_PREFIX_SUMMARY_S on the scheduler thread (the index
        # has no locking); host hashing only, published by atomic
        # tuple swap
        self._refresh_prefix_summary()
        if self._ledger is not None:
            # ledger reconcile + device/host memory gauges: host dict
            # math and a memory_stats() host call, rate-limited to ~1/s
            # so a ms-scale scheduler iteration never pays it
            now = time.monotonic()
            if now - self._ledger_t >= 1.0:
                self._ledger_t = now
                self._ledger.reconcile()
                from ..utils import sysinfo

                sysinfo.update_memory_gauges()

    def _dispatch(self) -> bool:
        """Enqueue device work for the current slot states. Returns
        whether anything was enqueued.

        Budget-based mixed scheduler (default): whenever prefill AND
        decode work coexist, ONE fused mixed dispatch advances both —
        decode rows first (they cost one token each), the remaining
        token budget filled with prefill chunk tokens — so an
        admission wave never stalls active streams and decode ITL is
        bounded by the budget, not by prefill-group round trips. The
        mixed step needs current host state (decode input tokens,
        grammar masks), so it waits for in-flight dispatches to
        harvest; a landing wave's requests keep joining the NEXT mixed
        dispatch while one is in flight, which preserves the burst-
        coalescing TTFT wins the legacy sleep-holds bought.

        Single-phase work keeps the specialized paths: pure prefill
        uses the grouped final/chunk dispatches (without the legacy
        formation hold), pure decode the pipelined k-step scans.
        LOCALAI_MIXED_DISPATCH=off restores the legacy alternating
        scheduler, sleep-holds included."""
        did = False
        prefilling = [s for s in self.slots if s.state is SlotState.PREFILL]
        decoding = [s for s in self.slots if s.state is SlotState.DECODE]
        if self._mixed and prefilling and decoding and self._mixed_buckets:
            if self._flights:
                return False  # host state is current only once every
                # in-flight dispatch harvests; _wait_for_event blocks
                # on the oldest flight's readiness (no sleep-hold)
            self._enqueue_mixed(prefilling, decoding)
            return True
        if prefilling:
            # batch final chunks of the same bucket together (one
            # dispatch per admission wave); long prompts chunk ahead
            finals: dict[int, list[_Slot]] = {}
            for s in prefilling:
                rem = s.n_prompt - s.n_past
                if rem <= self.prefill_buckets[-1]:
                    finals.setdefault(self._bucket(rem), []).append(s)
                else:
                    self._prefill_step(s)  # enqueue-only, no result
                    did = True
            if finals and not self._mixed and self._prefill_hold():
                # LEGACY-ONLY formation hold: the mixed dispatcher
                # coalesces at dispatch granularity instead
                finals = {}
                did = True  # keep the loop spinning through the hold
            for bucket in sorted(finals, key=lambda b: -len(finals[b])):
                group = finals[bucket]
                cap = self._prefill_group_cap(bucket)
                while group:
                    self._enqueue_prefill_final(group[:cap], bucket)
                    group = group[cap:]
                    did = True
        if decoding:
            did = self._dispatch_decode(decoding) or did
        return did

    def _prefill_hold(self) -> bool:
        """Delay prefill dispatch while an admission burst is STILL
        LANDING, so the burst forms one wide group instead of
        fragmenting. Without a gate, a 64-deep HTTP wave fragments
        into ~10 ragged serialized groups (p50 first-token past two
        seconds, measured r5); the r5 harvest-window variant of this
        gate (gather behind an in-flight flight until ITS harvest) left
        a premature 2-request group in the air and made the other 62
        wait out its whole ~230 ms round trip (tools/profile_http.py:
        big-group prefill at t+118 ms of a burst fully submitted by
        t+53).

        "Still landing" is evidence-based: requests queued but not yet
        admitted, >=2 distinct submit EVENTS with the newest <12 ms
        old (loop-serialized HTTP arrivals land ~0.6 ms apart and keep
        refreshing this; a submit_many wave is ONE event however large,
        so a lone wave dispatches immediately — two separate waves
        inside 12 ms pay a short bounded hold), or a single <3 ms-old
        first arrival (grace while its burst-mates are still on the
        wire). The total hold is bounded so a steady drip can never
        starve prefill."""
        now = time.perf_counter()
        with self._lock:
            # prefix-deferred requests are waiting ON a forming prefill,
            # not waiting to JOIN the group being held — they must not
            # hold their own donor's dispatch hostage
            pending = any(r.id not in self._deferred
                          for r, _ in self._pending)
            recent = [t for t in self._arrivals if now - t < 0.04]
        if pending and not any(not s.active for s in self.slots):
            # a queued request with ZERO free slots can never join the
            # group being held — under sustained saturation the pending
            # clause would otherwise tax every occupied slot's final
            # chunk with the full hold for no coalescing gain
            pending = False
        landing = pending or (
            # >=2 DISTINCT submit events in the window: concurrent
            # arrivals (a submit_many wave is ONE event regardless of
            # size, so it never trips this — loop-serialized HTTP
            # arrivals land ~0.6 ms apart and do)
            len(recent) >= 2 and now - recent[-1] < 0.012
        ) or (
            # first-arrival grace: the very first submit of a burst has
            # no spread evidence yet, and its premature 1-2 row group
            # cost the other 62 a full extra round trip (profile_http:
            # p50 292 with the split vs ~255 one-group). A lone steady
            # arrival pays only these 3 ms on its ~245 ms TTFT.
            len(recent) == 1 and now - recent[-1] < 0.003)
        if landing:
            if self._prefill_hold0 == 0.0:
                self._prefill_hold0 = now
            if now - self._prefill_hold0 < 0.06:
                time.sleep(1e-3)
                return True
        self._prefill_hold0 = 0.0
        return False

    def _wait_for_event(self) -> None:
        """Nothing to enqueue and nothing ready: block until the oldest
        flight's arrays land, an ADMITTABLE request arrives (pending
        alone is not an event — with every slot busy a queued request
        can't be dispatched, and returning on it would hot-spin the
        scheduler for the length of every in-flight scan), or a cancel
        fires."""
        while True:
            with self._lock:
                if self._stop or self._cancelled:
                    return
                if self._pending and any(not s.active for s in self.slots):
                    return
            if not self._flights:
                return
            if self._flights[0].ready():
                return
            time.sleep(5e-4)

    def _harvest(self) -> bool:
        """Complete ready flights in FIFO order (device execution is
        serialized by the donated state buffers, so readiness is
        monotone along the queue)."""
        did = False
        while self._flights and self._flights[0].ready():
            fl = self._flights.popleft()
            # flight-recorder sample: enqueue→ready wall time, stamped
            # from host clocks AFTER ready() returned true — the sample
            # never blocks on the device (hot-path-sync stays clean)
            dur = time.perf_counter() - fl.t_enqueue
            tm.ENGINE_DEVICE_STEP.labels(
                model=self._mlabel, kind=fl.kind).observe(dur)
            rec = fl.meta.get("rec")
            pred = fl.meta.get("pred_ms")
            if pred is not None and rec is not None:
                # predicted-vs-measured rides the timeline span, so
                # Perfetto shows calibration error per dispatch
                rec = dict(rec, predicted_ms=round(pred, 3),
                           measured_ms=round(dur * 1e3, 3))
            FLIGHT.span("step:" + fl.kind, "device", fl.t_enqueue, dur,
                        rec)
            if self._costmodel is not None:
                # cost accounting + MFU sample + predictor calibration
                # against the flight's span — host dict math on
                # already-harvested scalars
                self._costmodel.on_harvest(
                    fl.kind, fl.meta.get("cost"), dur, predicted_ms=pred)
            if fl.kind == "prefill_final":
                self._complete_prefill_final(fl)
            elif fl.kind == "mixed":
                self._complete_mixed(fl)
            else:
                self._complete_decodek(fl)
            did = True
        return did

    # lint: endregion hot_path

    # admission + prefix reuse (ref: grpc-server.cpp:1749-1900; extended
    # to a GLOBAL prefix cache: radix index over every slot's resident
    # prefix + on-device cross-slot row copies)
    def _admit(self) -> None:
        if self._pager is not None:
            # weight-pager hook: work arriving while a demotion's D2H
            # stream is aloft flips its abort flag — never blocks
            self._pager.tick()
        if self._tier is not None:
            # tier policy tick rides the admission pass: harvest landed
            # spill/fetch DMAs, apply background IO results, expire
            # stale stages, run the watermark demotion scan. Entirely
            # non-blocking (TransferWindow.reap + is_ready polling).
            self._tier.tick()
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        if self._pager is not None and not self._pager.poll_admission():
            # weights not device-resident: the poll kicked the warm->hot
            # promotion (layer-streamed, on its own thread); requeue the
            # wave untouched and retry next pass. The brief sleep keeps
            # this retry loop from busy-spinning the scheduler while the
            # stream lands — promotion completion notifies _lock.
            with self._lock:
                self._pending[:0] = pending
            time.sleep(0.002)
            return
        if self._prefix_enabled:
            # lazy re-register: decode appends / window clamps since the
            # last wave are diffed in (extension is the common case)
            self._prefix_index.sync(
                (s.idx, s.cache_tokens) for s in self.slots)
        # prompts admitted but whose prefill has NOT yet dispatched:
        # their KV is uncommitted, so the index cannot serve them yet —
        # same-wave sharers defer one iteration behind them instead
        # (one prefix prefill + N copies serves the whole wave)
        forming = [s.request.prompt_ids for s in self.slots
                   if s.state is SlotState.PREFILL
                   and s.request is not None
                   and s.request.soft_embeds is None]
        requeue: list[tuple[GenRequest, queue.SimpleQueue]] = []
        now = time.perf_counter()
        for req, out in pending:
            with self._lock:
                cancelled = req.id in self._cancelled
                if cancelled:  # cancel raced ahead
                    del self._cancelled[req.id]
                    self._deferred.pop(req.id, None)
                    if req.disagg is not None:
                        req.disagg.release()
                    out.put(StreamEvent(done=True,
                                        finish_reason="cancelled"))
            if cancelled:
                # this terminal previously bypassed the trace recorder
                # entirely, stranding the request's trace in the active
                # table until cap eviction — every terminal must land a
                # complete entry in the ring
                TRACER.event(req.id, "done")
                TRACER.annotate(req.id, "terminal", outcome="cancelled",
                                stage="admit")
                TRACER.finish(req.id, status="cancelled")
                tm.ENGINE_REQUESTS.labels(model=self._mlabel,
                                          reason="cancelled").inc()
                tm.ENGINE_CANCELLATIONS.labels(model=self._mlabel,
                                               reason="client").inc()
                continue
            if req.disagg is None and self._defer_for_prefix(
                    req, forming, now):
                requeue.append((req, out))
                continue
            if (self._tier is not None and req.soft_embeds is None
                    and req.disagg is None
                    and self._tier.plan(req, now)):
                # the session's KV is in the cold tier and its disk
                # load is inside the deadline window: hold admission
                # (overlapped with queue wait) instead of re-prefilling
                requeue.append((req, out))
                continue
            slot = self._pick_slot(req)
            if slot is None:
                requeue.append((req, out))  # no free slot
                continue
            if self._paged and not self._page_headroom(req):
                requeue.append((req, out))  # pool full of ACTIVE state:
                # wait for a release instead of admit-then-kill thrash
                continue
            self._deferred.pop(req.id, None)
            if req.disagg is not None and self._migrator is not None:
                # migrated resubmit: stage the prefill engine's pages
                # into this pool and adopt them by reference — the slot
                # wakes in DECODE with the whole prompt resident and
                # re-prefills ZERO tokens. Spill the slot's resident
                # prefix first (same demote-on-reuse as the tier path:
                # the gather lands before any overwrite in device
                # order). On staging failure (fault injection, pool
                # pressure) the handoff is dropped and the request
                # falls through to _assign below — an ordinary
                # re-prefill, correct just slower.
                if self._tier is not None and req.soft_embeds is None:
                    self._tier.capture(slot, req)
                if self._migrator.assign_migrated(slot, req, out):
                    continue
                req.disagg = None
            if self._tier is not None and req.soft_embeds is None:
                # demote-on-reuse: spill the resident prefix this
                # assignment is about to discard (gather enqueued
                # before any overwrite — device-order keeps it
                # coherent), THEN adopt a staged promotion: the slot's
                # resident prefix becomes the fetched session (share by
                # reference), so _assign's ordinary prefix-reuse path
                # skips those tokens — a prefetch hit re-prefills zero
                self._tier.capture(slot, req)
                self._tier.adopt(slot, req)
            self._assign(slot, req, out)
            if req.soft_embeds is None:
                forming.append(req.prompt_ids)
        if requeue:
            with self._lock:  # preserve arrival order over new arrivals
                self._pending[:0] = requeue

    def _defer_for_prefix(self, req: GenRequest, forming: list,
                          now: float) -> bool:
        """Same-wave prefix grouping: when requests in one admission
        wave share a >= _prefix_defer_min-token prefix the index cannot
        yet serve, the FIRST prefills it and the rest defer until that
        prefill's KV commits (its dispatch extends the donor's
        cache_tokens), then admit as copy + tail-prefill. Bounded by a
        deadline so a stalled/cancelled donor can never strand its
        sharers (they admit normally and re-prefill)."""
        if not self._prefix_enabled or req.soft_embeds is not None:
            return False
        cap = min(len(req.prompt_ids) - 1, self.max_seq - 1)
        state = self._deferred.get(req.id)
        if state is not None:
            deadline, want = state
            if now > deadline:
                self._deferred.pop(req.id, None)
                return False  # donor stalled: admit normally
            have, _ = self._prefix_index.match(req.prompt_ids)
            if min(have, cap) >= want:
                self._deferred.pop(req.id, None)
                return False  # shared prefix committed: admit w/ copy
            if not any(_common_prefix(p, req.prompt_ids) >= want
                       for p in forming):
                self._deferred.pop(req.id, None)
                return False  # donor vanished: admit normally
            return True
        share = max((_common_prefix(p, req.prompt_ids)
                     for p in forming), default=0)
        share = min(share, cap)
        have, _ = self._prefix_index.match(req.prompt_ids)
        have = min(have, cap)
        if share >= have + self._prefix_defer_min:
            self._deferred[req.id] = (now + 0.25, share)
            tm.ENGINE_PREFIX_EVENTS.labels(
                model=self._mlabel, event="deferred").inc()
            return True
        return False

    def _reset_columns(self, group: list[_Slot], pad_to: int,
                       rows: Optional[list[int]] = None) -> dict:
        """Per-slot sampler-reset columns for a prefill_final group. The
        reset rides the prefill dispatch (a separate reset_batch dispatch
        cost one extra tunnel RTT per admission wave — measured directly
        on burst TTFT). ``rows`` places each group member at an explicit
        batch row (the identity dispatch, where row == slot idx); without
        it members occupy the leading rows. Unoccupied rows pad with
        zeros; their scatter targets the out-of-bounds sentinel slot, so
        the writes are dropped."""
        W = self.sampling.window
        cols: dict[str, list] = {k: [] for k in (
            "temperature", "top_k", "top_p", "min_p",
            "repeat_penalty", "freq_penalty", "presence_penalty",
            "repeat_last_n", "seeds", "has_seed",
            "typical_p", "mirostat", "mirostat_tau", "mirostat_eta")}
        pad = _PadReq()
        layout: list[Optional[_Slot]] = [None] * pad_to
        for i, s in enumerate(group):
            layout[rows[i] if rows is not None else i] = s
        for s in layout:
            r = s.request if s is not None else pad
            assert r is not None
            cols["temperature"].append(r.temperature)
            cols["top_k"].append(r.top_k)
            cols["top_p"].append(r.top_p)
            cols["min_p"].append(r.min_p)
            cols["repeat_penalty"].append(r.repeat_penalty)
            cols["freq_penalty"].append(r.frequency_penalty)
            cols["presence_penalty"].append(r.presence_penalty)
            cols["repeat_last_n"].append(
                min(r.repeat_last_n if r.repeat_last_n > 0 else 64, W))
            # wrap to the int32 bit pattern: 64-bit seeds are legal in the
            # API and np.asarray(np.int32) raises on >= 2**31
            seed = (r.seed if r.seed is not None else 0) & 0xFFFFFFFF
            cols["seeds"].append(seed - (1 << 32) if seed >= (1 << 31)
                                 else seed)
            cols["has_seed"].append(r.seed is not None)
            cols["typical_p"].append(r.typical_p)
            cols["mirostat"].append(r.mirostat)
            cols["mirostat_tau"].append(r.mirostat_tau)
            cols["mirostat_eta"].append(r.mirostat_eta)
        return {
            "temperature": np.asarray(cols["temperature"], np.float32),
            "top_k": np.asarray(cols["top_k"], np.int32),
            "top_p": np.asarray(cols["top_p"], np.float32),
            "min_p": np.asarray(cols["min_p"], np.float32),
            "repeat_penalty": np.asarray(cols["repeat_penalty"], np.float32),
            "freq_penalty": np.asarray(cols["freq_penalty"], np.float32),
            "presence_penalty": np.asarray(
                cols["presence_penalty"], np.float32),
            "repeat_last_n": np.asarray(cols["repeat_last_n"], np.int32),
            "seeds": np.asarray(cols["seeds"], np.int32),
            "has_seed": np.asarray(cols["has_seed"], bool),
            "typical_p": np.asarray(cols["typical_p"], np.float32),
            "mirostat": np.asarray(cols["mirostat"], np.int32),
            "mirostat_tau": np.asarray(cols["mirostat_tau"], np.float32),
            "mirostat_eta": np.asarray(cols["mirostat_eta"], np.float32),
        }

    def _pick_slot(self, req: GenRequest) -> Optional[_Slot]:
        free = [s for s in self.slots if not s.active]
        if not free:
            return None
        if not self._prefix_enabled:
            return max(free, key=lambda s: _common_prefix(
                s.cache_tokens, req.prompt_ids))
        # value-destroyed placement: admitting onto a slot overwrites
        # its resident prefix beyond the overlap, so the right victim
        # is the slot whose UNSHARED tail is worth the least (reuse
        # value scaled by the fraction overwritten) — NOT the
        # max-overlap slot. Scoring by overlap alone steers every new
        # conversation that shares a trivial opening with a hot
        # resident (chat-template header, "You are a ..." boilerplate)
        # onto that resident and evicts it while a near-worthless slot
        # sits free; and _maybe_prefix_copy serves the same overlap
        # from ANY donor row, so in-place placement saves only the
        # copy, never the prefill. Ties (e.g. two empty slots) prefer
        # the larger overlap: in-place reuse skips the donor copy.
        now = time.monotonic()

        def cost(s: _Slot) -> tuple:
            overlap = _common_prefix(s.cache_tokens, req.prompt_ids)
            n = self._prefix_index.registered_len(s.idx)
            destroyed = 0.0
            if n:
                keep = min(overlap, n)
                destroyed = (self._prefix_index.value(s.idx, now)
                             * (n - keep) / n)
            return (destroyed, -overlap)

        return min(free, key=cost)

    def _maybe_prefix_copy(self, slot: _Slot, req: GenRequest,
                           common: int) -> tuple[int, int]:
        """Cross-slot prefix reuse: when another slot's committed
        resident prefix beats this slot's by >= _prefix_min_copy
        tokens, enqueue an on-device row-to-row KV copy (donor row ->
        this row) and start prefill from the copied length. The donor
        may be ACTIVE — its committed prefix [0, n_past) is immutable
        (decode/prefill writes land at or beyond n_past, and device
        execution is serialized behind everything already enqueued) —
        so an admitted request reuses the best prefix held by ANY
        slot, not just its own. Returns (new common, tokens gained)."""
        if not self._prefix_enabled:
            return common, 0
        m = self._mlabel
        best, donors = self._prefix_index.match(req.prompt_ids)
        best = min(best, len(req.prompt_ids) - 1, self.max_seq - 1)
        if best >= common + self._prefix_min_copy:
            donors = donors - {slot.idx}
        else:
            donors = set()
        if not donors:
            tm.ENGINE_PREFIX_EVENTS.labels(
                model=m,
                event="hit_resident" if common > 0 else "miss").inc()
            return common, 0
        now = time.monotonic()
        # most-valuable donor: longest registration is implied (all
        # cover >= best); prefer the most recently useful row
        donor = max(donors,
                    key=lambda i: self._prefix_index.value(i, now))
        if self._paged:
            # zero-copy share: the donor's FULL pages covering [0, best)
            # transfer by reference (refcount bump — no device work);
            # only the sub-page tail is row-copied into a fresh private
            # page, so whole-page prefixes admit with ZERO copy
            # dispatches — this supersedes most dense kvcopy traffic.
            P = self._page
            full = best // P
            self._pool.share(slot.idx, donor, full)
            tail = best - full * P
            if tail > 0:
                src_pg = self._pool.table(donor)[full]
                if self._pool_ensure(slot, best):  # the tail page
                    dst_pg = self._pool.table(slot.idx)[full]
                    # whole-page copy (rows past `tail` are rewritten
                    # by prefill or causally invisible): ONE jit variant
                    self._run("kvcopy", {"src": src_pg, "dst": dst_pg,
                                         "n": P})
                    self.metrics.prefix_copies += 1
                    tm.ENGINE_PREFIX_COPIES.labels(model=m).inc()
                else:
                    best = full * P  # no page for the tail: share-only
        else:
            # static-shape length bucket: copying past `best` is
            # harmless (dst positions beyond its valid prefix are
            # rewritten by prefill or causally invisible) and keeps the
            # jit set tiny
            self._run("kvcopy", {"src": donor, "dst": slot.idx,
                                 "n": self._window_bucket(best)})
            self.metrics.prefix_copies += 1
            tm.ENGINE_PREFIX_COPIES.labels(model=m).inc()
        self._prefix_index.touch(donor, now)
        gain = max(0, best - common)
        tm.ENGINE_PREFIX_EVENTS.labels(model=m, event="hit_copy").inc()
        slot.cache_tokens = list(req.prompt_ids[:best])
        slot.n_past = best
        return best, gain

    # ------------------------------------------------- on-disk prompt cache

    def _try_load_prompt_cache(self, slot: _Slot, req: GenRequest) -> str:
        """Restore a saved prompt's KV rows into the slot when the file's
        token prefix beats the slot's resident prefix (ref: llama.cpp
        prompt cache restore via PromptCachePath). Every outcome is
        counted (engine_prompt_cache_restores_total{result=...}) and
        traced, so a corrupt on-disk cache silently re-prefilling every
        request is visible instead of invisible. Returns the result
        string ("unset" when the request carries no cache path)."""
        import os

        path = req.prompt_cache_path
        if not path:
            return "unset"  # the common no-cache case: not counted

        def done(result: str) -> str:
            tm.ENGINE_PROMPT_CACHE_RESTORES.labels(
                model=self._mlabel, result=result).inc()
            TRACER.event(req.id, f"prompt_cache:{result}")
            return result

        if self.channel is not None:
            # multihost: a row restore would need the KV payload
            # broadcast to every follower. CROSS-SLOT copies still work
            # (pure device ops); only the disk path stays off.
            return done("skipped_multihost")
        if self.draft is not None:
            # restored rows would leave the draft cache stale
            return done("skipped_draft")
        if not os.path.exists(path):
            return done("no_file")
        try:
            from .kv_tier import read_cache_file

            data = read_cache_file(path)
            cached_tokens = [int(t) for t in data["tokens"]]
            L, _, _, F = self.cache.k.shape
            k_all, v_all = data["k"], data["v"]
            # a cache written by a different model/dtype config must be
            # ignored, not crash the scheduler or corrupt KV
            if (k_all.shape[0] != L or k_all.shape[2] != F
                    or v_all.shape != k_all.shape):
                return done("shape_mismatch")
            if self.cache.quantized != (k_all.dtype == np.int8):
                return done("dtype_mismatch")
            if self.cache.quantized and "k_scale" not in data:
                return done("dtype_mismatch")
            common = _common_prefix(cached_tokens, req.prompt_ids)
            if common <= _common_prefix(slot.cache_tokens, req.prompt_ids):
                return done("stale")
            n = min(common, len(cached_tokens), self.max_seq - 1,
                    k_all.shape[1])
            if self._paged:
                # replace the slot's rows wholesale: fresh private
                # pages, file rows scattered page by page (the on-disk
                # format stays slot-contiguous [L, n, F], so caches are
                # portable across paged and dense engines)
                self._pool.drop(slot.idx)
                slot.cache_tokens = []
                slot.n_past = 0
                if not self._pool_ensure(slot, n):
                    return done("error")
                P = self._page
                table = self._pool.table(slot.idx)
                npg = len(table)
                pad = npg * P - n

                def paged_rows(a):
                    a = np.asarray(a[:, :n])
                    if pad:
                        a = np.concatenate([a, np.zeros(
                            (a.shape[0], pad) + a.shape[2:], a.dtype)],
                            axis=1)
                    return a.reshape((a.shape[0], npg, P) + a.shape[2:])

                tbl = jnp.asarray(np.asarray(table, np.int32))
                ck = self.cache.k.at[:, tbl].set(
                    jnp.asarray(paged_rows(k_all)).astype(
                        self.cache.k.dtype))
                cv = self.cache.v.at[:, tbl].set(
                    jnp.asarray(paged_rows(v_all)).astype(
                        self.cache.v.dtype))
                ks, vs = self.cache.k_scale, self.cache.v_scale
                if self.cache.quantized:
                    ks = ks.at[:, tbl].set(
                        jnp.asarray(paged_rows(data["k_scale"])))
                    vs = vs.at[:, tbl].set(
                        jnp.asarray(paged_rows(data["v_scale"])))
            else:
                ck = self.cache.k.at[:, slot.idx, :n].set(
                    jnp.asarray(k_all[:, :n]).astype(self.cache.k.dtype))
                cv = self.cache.v.at[:, slot.idx, :n].set(
                    jnp.asarray(v_all[:, :n]).astype(self.cache.v.dtype))
                ks, vs = self.cache.k_scale, self.cache.v_scale
                if self.cache.quantized:
                    ks = ks.at[:, slot.idx, :n].set(
                        jnp.asarray(data["k_scale"][:, :n]))
                    vs = vs.at[:, slot.idx, :n].set(
                        jnp.asarray(data["v_scale"][:, :n]))
        except Exception as e:
            # unreadable/incompatible cache: prefill normally — but
            # say so, a corrupt file re-prefilling forever is a real
            # cost someone is paying
            log.warning("prompt cache %s unusable: %r", path, e)
            return done("error")
        self.cache = KVCache(k=ck, v=cv, k_scale=ks, v_scale=vs)
        slot.cache_tokens = cached_tokens[:n]
        slot.n_past = n
        slot.cache_loaded = (path, n)
        if self._prefix_enabled:
            self._prefix_index.set_tokens(slot.idx, slot.cache_tokens)
        self._epoch += 1
        return done("restored")

    def _maybe_save_prompt_cache(self, slot: _Slot) -> None:
        """Persist the slot's prefix rows (ref: llama.cpp prompt cache
        save; PromptCacheAll includes the generation)."""
        req = slot.request
        if req is None or not req.prompt_cache_path or req.prompt_cache_ro \
                or self.channel is not None:
            return
        n = slot.n_past if req.prompt_cache_all else min(
            slot.n_past, slot.n_prompt)
        if n <= 0:
            return
        if slot.cache_loaded == (req.prompt_cache_path, n):
            return  # the file already holds exactly this prefix
        # snapshot the (immutable) device arrays now; the transfer +
        # write happens OFF the scheduler thread so a finishing request
        # never stalls other slots' decoding
        if self._paged:
            # gather the slot's page run into contiguous rows — the
            # on-disk format stays [L, n, F] either way
            P = self._page
            tbl = jnp.asarray(np.asarray(
                self._pool.table(slot.idx)[: -(-n // P)], np.int32))
            L = self.cache.k.shape[0]
            F = self.cache.k.shape[-1]
            k_rows = self.cache.k[:, tbl].reshape(L, -1, F)[:, :n]
            v_rows = self.cache.v[:, tbl].reshape(L, -1, F)[:, :n]
            scales = ((self.cache.k_scale[:, tbl].reshape(L, -1)[:, :n],
                       self.cache.v_scale[:, tbl].reshape(L, -1)[:, :n])
                      if self.cache.quantized else None)
        else:
            k_rows = self.cache.k[:, slot.idx, :n]
            v_rows = self.cache.v[:, slot.idx, :n]
            scales = ((self.cache.k_scale[:, slot.idx, :n],
                       self.cache.v_scale[:, slot.idx, :n])
                      if self.cache.quantized else None)
        tokens = np.asarray(slot.cache_tokens[:n], np.int32)
        path = req.prompt_cache_path

        def persist():
            # the writer is the cold tier's format code (kv_tier.py):
            # np.asarray here blocks on the gathered rows OFF the
            # scheduler thread, then the same atomic savez the tier's
            # background demotion uses
            from .kv_tier import write_cache_file

            try:
                write_cache_file(path, tokens, k_rows, v_rows, scales)
            except OSError:
                pass  # cache persistence is best-effort

        threading.Thread(target=persist, daemon=True,
                         name="prompt-cache-save").start()

    def _assign(self, slot: _Slot, req: GenRequest,
                out: queue.SimpleQueue) -> None:
        now = time.perf_counter()
        TRACER.event(req.id, "admit", t=now, model=self._mlabel)
        if req.t_submit:
            wait = max(0.0, now - req.t_submit)
            tm.ENGINE_QUEUE_WAIT.labels(model=self._mlabel).observe(wait)
            with self._lock:
                self._queue_waits.append(wait)
        slot.cache_loaded = None
        copy_gain = disk_gain = 0
        if req.soft_embeds is not None:
            common = 0  # image-conditioned K/V: no token-id prefix reuse
        else:
            common = _common_prefix(slot.cache_tokens, req.prompt_ids)
            common, copy_gain = self._maybe_prefix_copy(slot, req, common)
            # the on-disk cache can still beat a live resident/copied
            # prefix (it persists across restarts); it checks the
            # slot's CURRENT tokens, so it only applies when longer
            before_disk = common
            if self._try_load_prompt_cache(slot, req) == "restored":
                common = _common_prefix(slot.cache_tokens, req.prompt_ids)
                disk_gain = common - before_disk
            if common == len(req.prompt_ids):
                common -= 1  # reprocess last token for logits (ref :1882-1890)
        if self._paged:
            # the write frontier (position `common`) must be privately
            # writable: a SHARED boundary page (this slot donated its
            # full pages, or the relogit -1 stepped back into a shared
            # page) is copy-on-write swapped for a private copy before
            # any prefill write can land in it
            cow = self._pool.prepare_write(slot.idx, common)
            if cow is not None:
                self._run("kvcopy", {"src": cow[0], "dst": cow[1],
                                     "n": self._page})
        slot.request = req
        slot.out = out
        slot.state = SlotState.PREFILL
        slot.n_past = common
        slot.n_prompt = len(req.prompt_ids)
        slot.cache_tokens = list(req.prompt_ids[:common])
        slot.n_reused = common
        if self._prefix_enabled:
            # eager re-register: the row now holds (only) this truncated
            # prefix — later admissions in the SAME wave must not match
            # the stale longer registration
            self._prefix_index.set_tokens(slot.idx, slot.cache_tokens)
            self._prefix_index.touch(slot.idx)
            self._prefix_index.set_chain(
                slot.idx, req.prefix_chain, len(req.prompt_ids))
        if common > 0:
            # attribute reuse by source; clamp so the three sources sum
            # exactly to `common` even across the relogit -1 adjustment
            disk_gain = min(disk_gain, common)
            copy_gain = min(copy_gain, common - disk_gain)
            resident = common - disk_gain - copy_gain
            m = self._mlabel
            self.metrics.prefix_reused_tokens += common
            for src_name, val in (("resident", resident),
                                  ("copy", copy_gain),
                                  ("disk", disk_gain)):
                if val > 0:
                    tm.ENGINE_PREFIX_REUSED_TOKENS.labels(
                        model=m, source=src_name).inc(val)
        slot.generated = []
        slot.decoder = StreamDecoder(self.tokenizer)
        slot.pending_text = ""
        slot.t_start = now
        slot.t_first = 0.0
        slot.t_prefill_ms = 0.0
        slot.t_prefill_enq_ms = 0.0
        slot.t_prefill_t0 = 0.0
        slot.t_decode_ms = 0.0
        slot.constraint_state = (
            req.constraint.initial_state() if req.constraint else None
        )
        self._epoch += 1  # sampler reset rides the slot's prefill_final
        # dispatch (_reset_columns), before its first sample

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def _prefill_step(self, slot: _Slot) -> None:
        """Process one prompt chunk for one slot (chunked prefill,
        ref: grpc-server.cpp:1993-2002 n_batch chunking)."""
        req = slot.request
        assert req is not None
        t0 = time.perf_counter()
        remaining = req.prompt_ids[slot.n_past:]
        chunk = remaining[: self.prefill_buckets[-1]]
        bucket = self._bucket(len(chunk))
        toks = np.zeros((1, bucket), np.int32)
        toks[0, : len(chunk)] = chunk
        # first chunk of a long prompt on a seq-sharded mesh: ring
        # attention (the chunk attends only to itself at pos0 == 0, pad
        # included — padded columns sit beyond the valid prefix and get
        # overwritten, same invariant as the dense path)
        seq_ax = (self.mesh.shape.get("seq", 1)
                  if self.mesh is not None else 1)
        ring = (seq_ax > 1 and slot.n_past == 0
                and not self.spec.sliding_window
                and bucket % seq_ax == 0
                and req.soft_embeds is None)
        # note: positions beyond len(chunk) write garbage K/V at
        # [n_past+len(chunk), n_past+bucket) — harmless: they're beyond the
        # valid prefix and get overwritten when real tokens arrive (causal
        # mask keeps them invisible to attention reads at these positions).
        # Ragged mode pins the table width to max_seq: the kernel walks
        # only the live pages anyway, and one jit variant serves every
        # live-context size.
        window = (self.max_seq if self._ragged
                  else self._window_bucket(slot.n_past + bucket))
        payload = {
            "toks": toks,
            "pos0": np.asarray([slot.n_past], np.int32),
            "slot_ids": np.asarray([slot.idx], np.int32),
            "soft": self._soft_payload([slot], [slot.n_past], bucket),
            "window": window,
            "ring": ring,
        }
        if self._paged:
            if not self._pool_ensure(slot, slot.n_past + len(chunk)):
                self._finish(slot, "length")
                return
            payload["pt"] = self._phys_rows([slot.idx], window)
            payload["wb"] = self._wb_rows(
                [(slot.idx, (slot.n_past, slot.n_past + len(chunk)))],
                window)
        self._run("prefill", payload)
        slot.n_past += len(chunk)
        slot.cache_tokens.extend(chunk)
        if slot.t_prefill_t0 == 0.0:
            slot.t_prefill_t0 = t0
        # _run only ENQUEUES: charging its wall time to t_prefill_ms
        # made chunked prompts report near-zero prompt processing.
        # Device time is attributed at harvest of the covering flight
        # (_complete_prefill_final / _complete_mixed); the host-side
        # enqueue cost is tracked as its own phase component.
        slot.t_prefill_enq_ms += (time.perf_counter() - t0) * 1e3
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel, composition="prefill_only").inc()
        self._note_ragged_rows("prefill", 1)

    @property
    def _group_cap(self) -> int:
        return min(64, max(self.n_slots, 1))

    @property
    def _half_k(self) -> int:
        """The half-length scan the steady-state arrival clamp snaps to:
        the largest power of two <= decode_steps // 2 (floor 4). MUST be
        in warmup()'s decode ks — a never-warmed k here would cold-jit
        ~13 s on the latency path the clamp protects."""
        h = max(self.decode_steps // 2, 4)
        while h & (h - 1):
            h &= h - 1
        return h

    @property
    def _warm_ks(self) -> set:
        """Every scan length warmup() precompiles — the ONLY values any
        runtime k decision may produce (a cold k jits ~13 s mid-request
        at 8B scale). The {2,4,8,16} rungs give _latency_k a dense
        ladder to snap to across model scales."""
        return {k for k in (1, 2, 4, 8, 16) if k <= self.decode_steps} | {
            self._half_k, self.decode_steps}

    # the shortest scan worth dispatching: device work per scan should
    # cover the dispatch round trip (~100 ms through the tunnel; a few
    # ms PCIe-attached) or the device idles between scans — measured as
    # the 1B drain collapsing to 1/4 throughput under a flat k=4 clamp
    _LAT_TARGET_MS = 90.0

    def _latency_k(self, lat_mode: bool = False) -> int:
        """Scan length for open-capacity periods, from the
        harvest-measured per-step EWMA.

        Balanced (lat_mode False): the smallest WARMED k whose device
        time still covers the dispatch RTT — an unpredicted arrival
        waits behind short scans (steady p50 404 -> ~320 ms measured at
        8B, k snaps to 4 at 32 ms/step) and open-capacity throughput
        stays roofline across scales (the 1B config, 9 ms/step, keeps
        k=16 and its drain throughput).

        Latency mode (lat_mode True: latency_target_ms set, open
        capacity, not a drain tail): the LARGEST warmed k that fits the
        budget — combined with the depth-1 gate in the scan decision,
        total queued decode work stays under the budget, so steady TTFT
        rides the dispatch floor (p50 404 -> 255 ms, min at the ~145 ms
        tunnel floor, measured by tools/profile_steady.py). Open-
        capacity decode deliberately stops covering the RTT: that is
        the knob."""
        if self._step_ms <= 0.0:
            return self.decode_steps  # no samples yet: don't throttle
        if lat_mode and self.latency_target_ms is not None:
            best = 0
            for k in sorted(self._warm_ks):
                if k > 1 and k * self._step_ms <= self.latency_target_ms:
                    best = k
            return best or min(k for k in self._warm_ks if k > 1)
        for k in sorted(self._warm_ks):
            if k > 1 and k * self._step_ms >= self._LAT_TARGET_MS:
                return k
        return self.decode_steps

    @property
    def _legacy_prefill_max(self) -> int:
        """Identity/legacy prefill split point. warmup() precompiles
        exactly the legacy shapes below it and _enqueue_prefill_final
        dispatches identity at or above it — ONE definition, or a
        trickle group lands on a never-warmed shape and eats a ~13 s
        mid-request compile."""
        return min(8, self.n_slots)

    def _prefill_group_cap(self, bucket: int) -> int:
        return max(1, min(self._group_cap,
                          self._prefill_group_tokens // max(bucket, 1)))

    # lint: region hot_path
    def _enqueue_prefill_final(self, group: list[_Slot],
                               bucket: int) -> None:
        """Enqueue a batch of same-bucket final prompt chunks: one fused
        dispatch runs the chunks, seeds the penalty windows, and samples
        each slot's first token — harvested later as a _Flight (the
        scheduler never blocks on the result). The group is padded UP
        with sentinel rows pointing at the out-of-bounds slot id
        ``n_slots``: JAX drops out-of-bounds scatter updates and clamps
        out-of-bounds gathers, so a pad row is pure discarded compute
        that never touches engine state. (Rounding DOWN and deferring
        the remainder turned one ragged 63-request wave into SIX
        dispatches of six distinct jit shapes; under HTTP arrival
        raggedness that compile churn collapsed endpoint throughput.)
        Group sizes come from powers of 8 {1, 8, 64} capped at
        min(64, n_slots) — a non-member n_slots cap introduces ONE
        extra variant (ADVICE r3 #3). At 8B-class sizes one compile
        costs ~13s, so the variant set must stay tiny (Engine.warmup
        precompiles it) — these sizes cover any admission pattern at
        <=8x padded compute, and padded rows are bandwidth-free (no new
        weights are read).

        Small buckets instead dispatch IDENTITY full-batch (row b ==
        slot b, every slot a row): the cross-slot K/V scatter was ~35%
        of the whole [64, 4] 8B dispatch (microbench r5: 234 -> 153 ms
        with the per-row-DUS identity path), and one [n_slots, bucket]
        shape replaces the {1, 8, 64}-row variant zoo. Non-member rows
        park their K/V write beyond the valid prefix, exactly like
        decode's inactive rows.

        Slot bookkeeping that later dispatches read (n_past,
        cache_tokens) advances HERE — device execution order equals
        enqueue order, so the chunk is on device before anything
        enqueued after it. The first-token emission happens at
        harvest."""
        cap = self._prefill_group_cap(bucket)
        group = group[:cap]
        if self._paged:
            # page capacity for each member's full prompt; a member the
            # pool cannot serve even after reclaim ends here (the paged
            # counterpart of the dense context wall)
            kept = []
            for s in group:
                if self._pool_ensure(s, s.n_prompt):
                    kept.append(s)
                else:
                    self._finish(s, "length")
            group = kept
            if not group:
                return
        # identity full-batch pays the whole [n_slots, bucket] forward —
        # a huge win for burst groups (no cross-slot scatter, one jit
        # shape) but a ~75 ms steady-state TTFT tax on a LONE arrival,
        # whose [1, bucket] legacy dispatch reads the same weights with
        # a fraction of the attention/sampler traffic. Split by group
        # size at the largest warmed legacy shape: trickles stay small,
        # a group reaching it is a genuine burst and goes identity.
        identity = (bucket * self.n_slots <= self._prefill_group_tokens
                    and len(group) >= self._legacy_prefill_max)
        if identity:
            B = self.n_slots
            rows = [s.idx for s in group]
        else:
            B = 1
            while B < len(group):
                B *= 8
            B = min(B, cap)
            rows = list(range(len(group)))
        t0 = time.perf_counter()
        W = self.sampling.window
        toks = np.zeros((B, bucket), np.int32)
        pos0 = np.zeros((B,), np.int32)
        slot_ids = np.full((B,), self.n_slots, np.int32)  # OOB sentinel
        n_chunk = np.ones((B,), np.int32)
        tails = np.zeros((B, W), np.int32)
        tail_lens = np.zeros((B,), np.int32)
        # identity non-member rows stay at pos0 == 0 with a no-op write
        # (write_mask False re-writes what is already there), so their
        # prefixes survive untouched and the window below is free to
        # follow the members' live context
        for r, s in zip(rows, group):
            req = s.request
            chunk = req.prompt_ids[s.n_past:]
            toks[r, : len(chunk)] = chunk
            pos0[r] = s.n_past
            slot_ids[r] = s.idx
            n_chunk[r] = len(chunk)
            tail = req.prompt_ids[-W:]
            tails[r, : len(tail)] = tail
            tail_lens[r] = len(tail)
        masks = self._constraint_mask_rows(group)
        if masks is not None:
            full = np.ones((B, masks.shape[1]), bool)
            for r, m in zip(rows, masks):
                full[r] = m
            masks = full
        if self._ragged or not identity:
            # ragged: ONE full-width variant per (B, bucket) shape —
            # the kernel (or full-width gather fallback) is ragged over
            # live context, so no window ladder exists to pick from
            window = self.max_seq
        else:
            # window follows the MEMBERS' live context (parked rows are
            # no-op writes at pos 0, so they place no demand on it):
            # 1024 -> 256 on a fresh wave cuts the dispatch's attention
            # traffic 4x. Prefer an already-compiled window >= need —
            # max_seq is always warmed, so nothing compiles mid-request.
            need = max(int(pos0[r]) for r in rows) + bucket + 1
            window = self._window_bucket(need)
            compiled = [k[1] for k in self._decode_k_fns
                        if k[0] == "prefill_final" and len(k) > 2
                        and k[2] and window <= k[1]]
            if compiled:
                window = min(compiled)
            else:
                window = self.max_seq
        payload = {
            "toks": toks, "pos0": pos0, "slot_ids": slot_ids,
            "n_chunk": n_chunk, "tails": tails, "tail_lens": tail_lens,
            "masks": masks,
            "reset": self._reset_columns(group, B, rows),
            "soft": self._soft_payload(group, pos0, bucket, rows),
            "window": window,
            "identity": identity,
        }
        if self._paged:
            # batch row -> slot mapping: identity rows ARE slot indices;
            # legacy rows are the leading group members, pads get trash
            row_slots: list = ([i for i in range(B)] if identity
                               else [None] * B)
            spans: list = [(si, None) for si in row_slots]
            for r, s in zip(rows, group):
                row_slots[r] = s.idx
                spans[r] = (s.idx, (int(pos0[r]),
                                    int(pos0[r]) + int(n_chunk[r])))
            payload["pt"] = self._phys_rows(row_slots, window)
            payload["wb"] = self._wb_rows(spans, window)
        toks_out = self._run("prefill_final", payload)
        try:
            toks_out.copy_to_host_async()
        except AttributeError:
            pass  # not all backends expose it; harvest still works
        t_disp = time.perf_counter()
        enq_ms = (t_disp - t0) * 1e3
        for s in group:
            req = s.request
            chunk_len = len(req.prompt_ids) - s.n_past
            s.cache_tokens.extend(req.prompt_ids[s.n_past:])
            s.n_past += chunk_len
            s.state = SlotState.PENDING_FIRST
            if s.t_prefill_t0 == 0.0:
                s.t_prefill_t0 = t0
            s.t_prefill_enq_ms += enq_ms
            TRACER.event(req.id, "prefill_dispatch", t=t_disp)
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel, composition="prefill_only").inc()
        self._note_ragged_rows("final", len(group))
        ckey = costmodel.dispatch_key("prefill_final", payload)
        self._flights.append(_Flight(
            kind="prefill_final", arrays=[toks_out],
            meta={"pairs": [(s, s.request) for s in group], "rows": rows,
                  # cost-model variant key: accounted at harvest, where
                  # the flight's span is known
                  "cost": ckey,
                  "pred_ms": (self._costmodel.predict_ms(
                      "prefill_final", ckey)
                      if self._costmodel is not None else None),
                  # timeline args for the flight recorder's harvest span
                  "rec": {"rows": len(group), "bucket": bucket,
                          "window": window}},
            t_enqueue=t0,
        ))

    def _complete_prefill_final(self, fl: _Flight) -> None:
        """Harvest a prefill flight: emit each slot's first token and
        move it into the decode set."""
        # lint: ignore[hot-path-sync] _harvest only hands over flights whose ready() is true — this host read is transfer-complete, not a sync
        toks_host = np.asarray(fl.arrays[0])
        now = time.perf_counter()
        rows = fl.meta.get("rows") or range(len(fl.meta["pairs"]))
        prompt_toks = first_toks = 0
        for r, (s, req) in zip(rows, fl.meta["pairs"]):
            if s.request is not req:  # cancelled mid-flight
                continue
            # device+queue prefill time from the slot's FIRST prefill
            # dispatch (chunk dispatches have no flight of their own;
            # device execution is serialized, so this flight's harvest
            # bounds when every earlier chunk retired)
            s.t_prefill_ms += (now - (s.t_prefill_t0
                                      or fl.t_enqueue)) * 1e3
            self.metrics.prompt_tokens_processed += s.n_prompt
            # the Prometheus counter reports tokens that actually went
            # THROUGH prefill — reused (resident/copied/restored)
            # tokens are counted in engine_prefix_reused_tokens_total,
            # so reused + prefilled == submitted prompt tokens
            actual = max(0, s.n_prompt - s.n_reused)
            self.metrics.prefill_tokens += actual
            prompt_toks += actual
            first_toks += 1
            s.state = SlotState.DECODE
            s.t_last = now
            self._epoch += 1
            self._emit_token(s, int(toks_host[r]))
        if prompt_toks:
            tm.ENGINE_PROMPT_TOKENS.labels(model=self._mlabel).inc(
                prompt_toks)
        if first_toks:
            tm.ENGINE_GENERATED_TOKENS.labels(model=self._mlabel).inc(
                first_toks)

    def _enqueue_mixed(self, prefilling: list[_Slot],
                       decoding: list[_Slot]) -> None:
        """Enqueue ONE fused mixed prefill+decode step (_mixed_fn).

        Budget policy: the dispatch is always [n_slots, bucket], so the
        per-dispatch token budget (LOCALAI_PREFILL_GROUP_TOKENS) bounds
        bucket to _mixed_buckets. Decode rows ride every dispatch (one
        token each — decode priority, so their inter-token gap is
        bounded by one budget's worth of device work); the bucket then
        grows just enough to cover the largest remaining prompt, capped
        by the budget — rows whose remainder exceeds it take a
        bucket-wide non-final chunk and continue next dispatch.

        Cost scheduling (LOCALAI_COST_SCHED + LOCALAI_ITL_BUDGET_MS):
        when decode rows are riding and an explicit ITL budget is set,
        the bucket is instead the LARGEST candidate whose PREDICTED
        device time (costmodel.predict_ms over the exact variant this
        composition would dispatch) fits the budget — the token budget
        stays as the cap (candidates never exceed the warmed variant
        set) and as the fallback when no candidate has a prediction.
        Under a long-prompt flood this shrinks the chunk below the
        token-budget choice, bounding decode ITL in milliseconds
        instead of tokens.

        Prefill bookkeeping (n_past/cache_tokens) advances HERE, like
        _enqueue_prefill_final: device execution order equals enqueue
        order, so anything enqueued later (kvcopy from a same-wave
        prefix sharer included) sees this chunk committed. Decode rows
        advance at harvest (_complete_mixed), exactly like the decode
        scan path."""
        t0 = time.perf_counter()
        S = self.n_slots
        W = self.sampling.window
        buckets = self._mixed_buckets
        if self._paged:
            # page capacity up front: decode rows append one token,
            # prefill rows at most one bucket-wide chunk
            for s in list(decoding):
                if not self._pool_ensure(s, s.n_past + 1):
                    self._finish(s, "length")
                    decoding.remove(s)
            for s in list(prefilling):
                rem = s.n_prompt - s.n_past
                if not self._pool_ensure(
                        s, s.n_past + min(rem, buckets[-1])):
                    self._finish(s, "length")
                    prefilling.remove(s)
            if not prefilling or not decoding:
                return  # composition changed: next iteration re-plans
        need = min(max(s.n_prompt - s.n_past for s in prefilling),
                   buckets[-1])
        bucket = next(b for b in buckets if b >= need)
        budget_ms = self._itl_budget_ms()
        if budget_ms > 0.0 and decoding:
            # ms-budget packing: decode rows ride regardless (their
            # cost is inside every candidate's prediction); the bucket
            # shrinks until the whole composition's predicted device
            # time fits the ITL budget
            bucket = self._cost_bucket(prefilling, decoding, bucket,
                                       budget_ms)
        toks = np.zeros((S, bucket), np.int32)
        pos0 = np.zeros((S,), np.int32)
        n_chunk = np.ones((S,), np.int32)
        write_mask = np.zeros((S,), bool)
        sample_sids = np.full((S,), S, np.int32)  # OOB sentinel
        reset_sids = np.full((S,), S, np.int32)
        prefill_sids = np.full((S,), S, np.int32)
        tails = np.zeros((S, W), np.int32)
        tail_lens = np.zeros((S,), np.int32)
        rows: list[tuple] = []  # (role, slot, request, aux)
        finals: list[_Slot] = []
        chunk_tokens = 0
        for s in decoding:
            last_tok = (s.generated[-1] if s.generated
                        else s.request.prompt_ids[-1])
            toks[s.idx, 0] = last_tok
            pos0[s.idx] = s.n_past
            write_mask[s.idx] = True
            sample_sids[s.idx] = s.idx
            rows.append(("decode", s, s.request, last_tok))
        for s in prefilling:
            req = s.request
            rem = s.n_prompt - s.n_past
            chunk = req.prompt_ids[s.n_past: s.n_past + min(rem, bucket)]
            toks[s.idx, : len(chunk)] = chunk
            pos0[s.idx] = s.n_past
            n_chunk[s.idx] = len(chunk)
            write_mask[s.idx] = True
            prefill_sids[s.idx] = s.idx
            chunk_tokens += len(chunk)
            if rem <= bucket:  # final chunk: reset+seed+sample ride
                finals.append(s)
                sample_sids[s.idx] = s.idx
                reset_sids[s.idx] = s.idx
                tail = req.prompt_ids[-W:]
                tails[s.idx, : len(tail)] = tail
                tail_lens[s.idx] = len(tail)
                rows.append(("final", s, req, None))
            else:
                rows.append(("chunk", s, req, None))
        # parked (FREE) rows keep the zero defaults: pos0 == 0 with
        # write_mask False is a pure no-op — their resident prefixes
        # survive untouched (no tail clamping, unlike the decode scan)
        masks = self._constraint_mask_rows(self.slots)
        # ragged pins full width (the kernel's page walk — or the
        # fallback's full-width gather — is ragged already); otherwise
        # the smallest compiled window covering every advancing row.
        # Shared with the cost-packing candidate scan above, so the
        # predicted variant is the dispatched variant.
        window = self._mixed_window(prefilling, decoding, bucket)
        payload = {
            "toks": toks, "pos0": pos0, "n_chunk": n_chunk,
            "write_mask": write_mask, "sample_sids": sample_sids,
            "reset_sids": reset_sids, "tails": tails,
            "tail_lens": tail_lens, "masks": masks,
            "reset": self._reset_columns(finals, S,
                                         [s.idx for s in finals]),
            "soft": self._soft_payload(prefilling, pos0, bucket,
                                       [s.idx for s in prefilling]),
            "prefill_sids": prefill_sids,
            "window": window,
        }
        if self._paged:
            spans: list = [(i, None) for i in range(S)]
            dspans: list = [(i, None) for i in range(S)]
            for s in decoding:
                spans[s.idx] = (s.idx, (s.n_past, s.n_past + 1))
            for s in prefilling:
                span = (s.n_past, s.n_past + int(n_chunk[s.idx]))
                spans[s.idx] = (s.idx, span)
                dspans[s.idx] = (s.idx, span)  # draft mirrors prefill
                # rows only — decode rows keep trash in the draft wb
            payload["pt"] = self._phys_rows(list(range(S)), window)
            payload["wb"] = self._wb_rows(spans, window)
            payload["wb_draft"] = self._wb_rows(dspans, window)
        toks_out = self._run("mixed", payload)
        try:
            toks_out.copy_to_host_async()
        except AttributeError:
            pass  # not all backends expose it; harvest still works
        t_disp = time.perf_counter()
        enq_ms = (t_disp - t0) * 1e3
        for s in prefilling:
            chunk_len = min(s.n_prompt - s.n_past, bucket)
            s.cache_tokens.extend(
                s.request.prompt_ids[s.n_past: s.n_past + chunk_len])
            s.n_past += chunk_len
            if s.t_prefill_t0 == 0.0:
                s.t_prefill_t0 = t0
            s.t_prefill_enq_ms += enq_ms
        for s in finals:
            s.state = SlotState.PENDING_FIRST
            TRACER.event(s.request.id, "prefill_dispatch", t=t_disp)
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel,
            composition="mixed" if decoding else "prefill_only").inc()
        self._note_ragged_rows("decode", len(decoding))
        self._note_ragged_rows("final", len(finals))
        self._note_ragged_rows("prefill", len(prefilling) - len(finals))
        if decoding:
            self._note_decode_advance(t_disp)
        ckey = costmodel.dispatch_key("mixed", payload)
        self._flights.append(_Flight(
            kind="mixed", arrays=[toks_out],
            meta={"rows": rows, "chunk_tokens": chunk_tokens,
                  "cost": ckey,
                  "pred_ms": (self._costmodel.predict_ms("mixed", ckey)
                              if self._costmodel is not None else None),
                  # timeline args for the flight recorder's harvest span
                  "rec": {"decode": len(decoding),
                          "prefill": len(prefilling) - len(finals),
                          "finals": len(finals),
                          "chunk_tokens": chunk_tokens}},
            t_enqueue=t0,
        ))

    def _complete_mixed(self, fl: _Flight) -> None:
        """Harvest a mixed flight: decode rows emit their sampled token
        (and commit the consumed input token, like the scan harvest),
        final-chunk rows emit their first token and join the decode
        set, non-final chunk rows only collect prefill-time
        attribution."""
        # lint: ignore[hot-path-sync] flight ready() verified by _harvest; the transfer already landed
        toks_host = np.asarray(fl.arrays[0])  # [S]
        now = time.perf_counter()
        dt_ms = (now - fl.t_enqueue) * 1e3
        # exemplar BEFORE the emit loop: a finishing slot deactivates
        # below, and its trace id is exactly the one worth linking
        exemplar = self._active_exemplar()
        decode_emitted = first_toks = prompt_toks = 0
        for role, s, req, aux in fl.meta["rows"]:
            if s.request is not req:  # cancelled mid-flight
                continue
            if role == "decode":
                if s.state is not SlotState.DECODE:
                    continue
                s.cache_tokens.append(aux)
                s.n_past += 1
                s.t_decode_ms += dt_ms
                decode_emitted += 1
                self._emit_token(s, int(toks_host[s.idx]), defer=True)
                if s.state is SlotState.DECODE:
                    self._flush_emit(s)
            elif role == "final":
                s.t_prefill_ms += (now - (s.t_prefill_t0
                                          or fl.t_enqueue)) * 1e3
                self.metrics.prompt_tokens_processed += s.n_prompt
                actual = max(0, s.n_prompt - s.n_reused)
                self.metrics.prefill_tokens += actual
                prompt_toks += actual
                first_toks += 1
                s.state = SlotState.DECODE
                s.t_last = now
                self._emit_token(s, int(toks_host[s.idx]))
            # role == "chunk": bookkeeping advanced at enqueue; device
            # time lands at the covering final's harvest (t_prefill_t0)
        # decode rows advanced: any saved decodek device carry is stale
        self._epoch += 1
        m = self._mlabel
        if prompt_toks:
            tm.ENGINE_PROMPT_TOKENS.labels(model=m).inc(prompt_toks)
        if decode_emitted + first_toks:
            tm.ENGINE_GENERATED_TOKENS.labels(model=m).inc(
                decode_emitted + first_toks)
        if decode_emitted:
            tm.ENGINE_INTER_TOKEN.labels(model=m).observe(
                dt_ms / 1e3, exemplar=exemplar)
            self._note_tokens_per_second(decode_emitted, dt_ms / 1e3)
        self.metrics.slots_busy = sum(1 for s in self.slots if s.active)
    # lint: endregion hot_path

    def _note_decode_advance(self, now: float) -> None:
        """Stall accounting: observe the gap between consecutive
        decode-advancing dispatches while >=1 slot decodes
        (engine_decode_stall_seconds — the series the legacy holds
        spiked and the mixed dispatcher bounds). _update_gauges resets
        the clock whenever no slot is decoding."""
        if self._last_decode_adv:
            tm.ENGINE_DECODE_STALL.labels(model=self._mlabel).observe(
                max(0.0, now - self._last_decode_adv))
        self._last_decode_adv = now

    def _note_ragged_rows(self, kind: str, n: int) -> None:
        """Rows advanced through the unified ragged path by kind
        (decode / prefill chunk / prefill final / spec verify) —
        engine_ragged_rows_total, the series proving every row kind
        actually flows through the one-kernel dispatch discipline."""
        if self._ragged and n > 0:
            tm.ENGINE_RAGGED_ROWS.labels(
                model=self._mlabel, kind=kind).inc(n)

    _TPS_ALPHA = 0.3

    def _note_tokens_per_second(self, emitted: int, dt_s: float) -> None:
        """ONE EWMA for metrics.tokens_per_second across every decode
        flavor (k-scan harvest, blocking single-step, speculative,
        mixed). The previous per-site stores each stomped the value
        with a single-dispatch instantaneous rate, so /backend/monitor
        flapped between k-step and blocking-path numbers."""
        if emitted <= 0 or dt_s <= 0:
            return
        inst = emitted / dt_s
        cur = self.metrics.tokens_per_second
        self.metrics.tokens_per_second = (
            inst if cur <= 0.0
            else (1.0 - self._TPS_ALPHA) * cur + self._TPS_ALPHA * inst)

    def _soft_payload(self, group: list[_Slot], pos0: Any,
                      bucket: int,
                      rows: Optional[list[int]] = None) -> Optional[list]:
        """Compact multimodal rows for a prefill dispatch: [(batch row,
        chunk-relative positions, embeds [k, D])] for every slot whose
        soft tokens fall inside this chunk; None when text-only (the
        common case pays nothing). ``rows`` maps group member i to its
        batch row (identity dispatches); default: leading rows."""
        out = []
        for i, s in enumerate(group):
            r = rows[i] if rows is not None else i
            req = s.request
            if req is None or req.soft_embeds is None:
                continue
            sp = np.asarray(req.soft_positions)
            sel = (sp >= int(pos0[r])) & (sp < int(pos0[r]) + bucket)
            if not sel.any():
                continue
            out.append((r, (sp[sel] - int(pos0[r])).astype(np.int32),
                        np.asarray(req.soft_embeds)[sel]
                        .astype(np.float32)))
        return out or None

    def _soft_dense(self, rows: Optional[list], B: int,
                    T: int) -> Optional[tuple]:
        """Compact soft payload -> padded device arrays (emb [Rp, D],
        brow [Rp], bpos [Rp]) for _soft_expand inside the jitted prefill.
        Rp is the token count rounded to a power of two (bounded jit
        cache); padding rows point at batch row B, which the scatter
        drops."""
        if not rows:
            return None
        R = sum(len(idxs) for _, idxs, _ in rows)
        Rp = 1 << max(R - 1, 0).bit_length()
        D = self.spec.d_model
        emb = np.zeros((Rp, D), np.float32)
        brow = np.full((Rp,), B, np.int32)
        bpos = np.zeros((Rp,), np.int32)
        off = 0
        for r, idxs, vals in rows:
            n = len(idxs)
            emb[off:off + n] = vals
            brow[off:off + n] = r
            bpos[off:off + n] = idxs
            off += n
        return jnp.asarray(emb), jnp.asarray(brow), jnp.asarray(bpos)

    def _constraint_mask_rows(self, slots: list[_Slot]) -> Optional[np.ndarray]:
        """Build [B, V] bool masks for grammar-constrained slots (host-side
        automaton, mask shipped to device — SURVEY.md §7 hard part #3)."""
        rows = []
        any_mask = False
        V = self.spec.vocab_size
        for s in slots:
            req = s.request
            mask = None
            if req is not None and req.constraint is not None:
                raw = np.asarray(
                    req.constraint.next_mask(s.constraint_state), dtype=bool
                )
                if raw.shape[0] != V:  # tokenizer/model vocab mismatch
                    mask = np.zeros(V, bool)
                    mask[: min(raw.shape[0], V)] = raw[:V]
                else:
                    mask = raw
                any_mask = True
            if req is not None and req.logit_bias:
                if mask is None:
                    mask = np.ones(V, bool)
                else:
                    # next_mask returns cached/shared arrays — mutating
                    # in place would ban these tokens for every later
                    # request sharing the constraint
                    mask = mask.copy()
                for tid, bias in req.logit_bias.items():
                    if 0 <= int(tid) < V and bias <= -100:
                        mask[int(tid)] = False
                any_mask = True
            rows.append(mask if mask is not None else np.ones(V, bool))
        if not any_mask:
            return None
        return np.stack(rows)

    def _multi_step_k(
        self, decoding: list[_Slot]
    ) -> tuple[int, int, int]:
        """(k, room, need): on-device step count — no grammar/logit-bias
        slot (those need a host-side mask per token), no slot may cross
        the end of its context row mid-scan, and k is capped by ``need``
        (the largest remaining token budget). ``room`` is the shared
        context headroom that also gates pipeline depth."""
        room = min(self.max_seq - 1 - s.n_past for s in decoding)
        need = 1
        for s in decoding:
            req = s.request
            if req is not None and (req.constraint or req.logit_bias):
                return 1, room, need
            if req is not None:
                need = max(need, req.max_tokens - len(s.generated))
        if self.decode_steps <= 1:
            return 1, room, need
        # cap by the largest remaining budget: a short request must not
        # pay (or make the NEXT request wait behind) a full-length scan
        # of discarded overshoot tokens
        k = min(self.decode_steps, max(room, 1), max(need, 1))
        if k & (k - 1):  # round UP to a power of two (tiny jit cache)
            k = 1 << k.bit_length()
        k = min(k, self.decode_steps, max(room, 1))
        while k & (k - 1):  # room may not be a power of two: round down
            k &= k - 1
        k = max(k, 1)
        # prefer an already-compiled k in [k, room] over cold-compiling
        # the exact smaller variant (same trick as the window buckets:
        # overshoot is discarded host-side anyway)
        compiled = [key[1] for key in self._decode_k_fns
                    if key[0] == "decode" and k < key[1] <= room
                    and key[1] <= self.decode_steps]
        if compiled and ("decode", k) not in {
                (key[0], key[1]) for key in self._decode_k_fns
                if len(key) > 1}:  # 1-tuple keys: ("draft_prefill",)
            k = min(compiled)
        return k, room, need

    # lint: region hot_path
    def _dispatch_decode(self, decoding: list[_Slot]) -> bool:
        """Enqueue (or, for the host-interactive paths, run) decode work
        (ref: grpc-server.cpp:1688-1726 batching ongoing tokens). The
        normal path enqueues one k-step scan as a _Flight and keeps up
        to ``_pipeline_depth`` scans in flight, chained on the
        device-resident carry — the device never idles waiting for a
        download, and downloads never serialize behind each other.
        Tokens generated past a slot's EOS/stop are discarded host-side
        at harvest (the over-written tail K/V sits beyond the valid
        prefix, so it is never attended to)."""
        spec_mode, spec_slots = self._spec_mode(decoding)
        if spec_mode and not self._flights and min(
                self.max_seq - 1 - s.n_past for s in decoding
        ) >= self.n_draft:
            # near the context wall the kd-token verify forward would
            # clamp its KV writes onto valid rows; normal path instead.
            # Eligible slots advance speculatively; the rest (penalties/
            # grammar/bias/mm) fall through to the normal dispatch below
            # — PER-SLOT eligibility, not whole-batch. Spec decoding is
            # a host-interactive (blocking) path, so it runs only with
            # an empty pipeline.
            self._spec_decode_step(spec_slots, spec_mode)
            decoding = [s for s in decoding
                        if s.state is SlotState.DECODE
                        and s not in spec_slots]
            if not decoding:
                return True
        now = time.perf_counter()
        waiting = sum(1 for s in self.slots
                      if s.state in (SlotState.PREFILL,
                                     SlotState.PENDING_FIRST))
        if self._mixed:
            if any(f.kind == "mixed" for f in self._flights):
                # a mixed step's sampled tokens are still in flight:
                # decode rows' next input tokens are unknown host-side,
                # and a scan enqueued now would replay stale tokens
                return False
        else:
            # LEGACY-ONLY burst hold (LOCALAI_MIXED_DISPATCH=off). The
            # mixed dispatcher replaces this prefill/decode mutual
            # exclusion with fusion: decode rows advance INSIDE the
            # wave's dispatches, so there is nothing to hold against.
            #
            # A prefill flight serving MORE waiters than there are
            # decoders counts as a burst even after the arrival window
            # lapses: the flight's ~200ms round trip outlives the 0.15s
            # freshness test, and a decode scan slipping into that gap
            # queues ~450ms of device work between the flight and its
            # harvest detection — measured r5: the 63-slot gathered
            # group's observed latency went 497ms with scans trailing
            # it vs 174ms clean. In steady state (decoders >> waiters)
            # decode proceeds: holding every scan behind each lone
            # arrival's prefill would halve throughput under
            # continuous load.
            gathering = (
                waiting > len(decoding)
                and any(f.kind == "prefill_final" for f in self._flights))
            burst = bool(self._pending) or now - self._last_arrival < 0.15
            if gathering or (burst and any(not s.active
                                           or s.state is SlotState.PREFILL
                                           for s in self.slots)):
                # an admission burst is landing (free slots await
                # requests, or assigned slots await their prefill — a
                # gathered group held behind an in-flight prefill
                # counts: r5 flight traces showed a 23-slot group
                # queueing behind 900 ms of decode scans that slipped
                # in the moment every slot was assigned): hold decode
                # enqueues so the burst's prefill groups pipeline
                # back-to-back on the device instead of each queueing
                # behind hundreds of ms of scan work — under a
                # 64-stream HTTP wave this is the difference between
                # ~0.4 s and ~1.7 s p50 TTFT. Bounded from the hold's
                # START so a steady trickle cannot starve decode.
                if self._hold_start == 0.0:
                    self._hold_start = now
                if now - self._hold_start < 0.5:
                    time.sleep(1e-3)
                    return False
            else:
                self._hold_start = 0.0
        dflights = [f for f in self._flights if f.kind == "decodek"]
        in_flight = sum(f.meta["k"] for f in dflights)
        k, room, need_tokens = self._multi_step_k(decoding)
        room -= in_flight
        if k <= 1:
            # grammar/logit-bias slots need a host mask per token: the
            # blocking single-step path, and it needs the true current
            # tokens — drain the pipeline first
            if self._flights:
                return False
            self._decode1_step(decoding)
            return True
        free = any(not s.active for s in self.slots)
        depth = self._pipeline_depth
        lat_mode = (self.latency_target_ms is not None and free
                    and not self._pending
                    and now - self._last_arrival >= 1.0
                    # a wave's drain tail (every stream within ONE full
                    # scan of its budget) finishes at full k: throttling
                    # it only delays the wall clock, no arrival benefits.
                    # Kept at one scan, not more: continuous short-
                    # generation service must still engage the clamp
                    and need_tokens > self.decode_steps)
        if lat_mode:
            # latency mode at open capacity: ONE short scan in flight at
            # a time, so total queued decode work stays under the
            # budget. The device idles the dispatch RTT between scans —
            # the throughput half of the knob's tradeoff.
            depth = 1
        if len(dflights) >= depth or room < k:
            return False
        if need_tokens <= in_flight:
            return False  # everything already covered by in-flight scans
        if (self._pending or now - self._last_arrival < 1.0) and free:
            # arrivals active with admissible room: a late request's
            # prefill dispatch queues on the device BEHIND this scan —
            # keep it short so burst TTFT is not hostage to a long
            # scan. (A flat k=4 on free slots ALONE throttled the 1B
            # drain to 1/4 throughput; the open-capacity case below
            # sizes k from measured step time instead.)
            k = min(k, 4)
        elif waiting and now - self._last_arrival < 1.0:
            # a fresh arrival's prefill is pending/in flight with every
            # slot taken (so the clamp above is off): keep scans at half
            # length so its first token is not hostage to a full k-scan
            # already queued ahead — the steady-state TTFT counterpart
            # of the burst clamp, at half the dispatch-overhead cost
            # (_half_k is always in warmup's variant set)
            k = min(k, self._half_k)
        elif free:
            # open capacity, no arrival in sight: an UNPREDICTED
            # arrival's prefill queues behind whatever scans are in
            # flight when it lands, so bound that queue in TIME (see
            # _latency_k for the balanced/latency-mode policies and
            # their measured effect).
            k = min(k, self._latency_k(lat_mode))
        itl_budget = self._itl_budget_ms()
        if itl_budget > 0.0:
            # explicit ms ITL budget: a k-scan's tokens surface only at
            # harvest, so the scan's whole device time IS the stream's
            # inter-token gap — clamp k to the largest warmed length
            # whose predicted time fits. Per-step time comes from the
            # measured EWMA when it has samples, else the cost-model
            # prediction (the fallback-before-warm contract); floor at
            # the smallest warmed multi-step scan: progress beats
            # stalling even over budget.
            step = (self._step_ms if self._step_ms > 0.0
                    else (self._costmodel.decode_step_ms() or 0.0))
            if step > 0.0:
                fits = [kk for kk in self._warm_ks
                        if kk > 1 and kk * step <= itl_budget]
                kb = (max(fits) if fits
                      else min(kk for kk in self._warm_ks if kk > 1))
                k = min(k, kb)

        S = self.n_slots
        if self._use_kernel or self._ragged:
            # the fused Pallas kernel is ragged (reads only valid
            # pages) and ragged mode pins tables to full width even on
            # the XLA fallback: one compiled variant for all contexts
            window = self.max_seq
        else:
            # live-context window bucket for this dispatch (_decode_k_fn)
            # window must cover EVERY non-free slot position plus the
            # tokens already in flight
            need = max(s.n_past for s in self.slots
                       if s.state in (SlotState.DECODE,
                                      SlotState.PENDING_FIRST)) \
                + in_flight + k + 1
            window = self._window_bucket(need)
            # prefer an already-compiled window >= need over compiling a
            # new exact bucket (a cold jit costs seconds; reading a
            # slightly larger window costs microseconds)
            compiled = [key[2] for key in self._decode_k_fns
                        if key[0] == "decode" and key[1] == k
                        and window <= key[2]]
            if compiled:
                window = min(compiled)

        if self._paged:
            # page capacity for the scan's write span ([n_past +
            # in_flight, + k) per advancing row) BEFORE the table
            # snapshots below
            for s in list(decoding):
                if not self._pool_ensure(s, s.n_past + in_flight + k):
                    self._finish(s, "length")
                    decoding.remove(s)
            if not decoding:
                return True
        advancing = {s.idx for s in decoding}
        tokens = np.zeros((S, 1), np.int32)
        pos0 = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for s in self.slots:
            if s.idx in advancing:
                last_tok = (s.generated[-1] if s.generated
                            else s.request.prompt_ids[-1])
                tokens[s.idx, 0] = last_tok
                pos0[s.idx] = s.n_past
                active[s.idx] = True
            elif s.state in (SlotState.DECODE, SlotState.PENDING_FIRST):
                # spec-advanced or first-token-pending slots ride
                # inactive; window covers their positions (see `need`),
                # so no trimming
                pos0[s.idx] = s.n_past
            else:
                # park inactive rows at their own tail: K/V write lands past
                # the valid prefix, preserving it for prefix reuse. In the
                # windowed path, a row whose prefix out-sizes the window
                # gets clamped: its reusable prefix is truncated to what
                # the window keeps. Paged rows never write back (their wb
                # pages are trash), so the resident prefix survives at
                # full length — only the in-dispatch position is clamped.
                if s.n_past >= window and not self._paged:
                    s.n_past = window - 1
                    s.cache_tokens = s.cache_tokens[: window - 1]
                pos0[s.idx] = min(s.n_past, window - 1, self.max_seq - 1)

        akey = active.tobytes()
        carry_ok = (self._dev_epoch == self._epoch
                    and self._dev_akey == akey)
        if dflights and not carry_ok:
            # scans in flight but the active set changed (a slot
            # finished/joined at harvest): fresh host tokens would be
            # stale until those scans land — wait for them
            return False
        payload = {
            "k": k, "window": window, "depth": 1, "carry": carry_ok,
            "tokens": tokens, "pos0": pos0, "active": active,
        }
        if self._paged:
            payload["pt"] = self._phys_rows(list(range(S)), window)
            payload["wb"] = self._wb_rows(
                [(i, ((self.slots[i].n_past + in_flight,
                       self.slots[i].n_past + in_flight + k)
                      if i in advancing else None)) for i in range(S)],
                window)
        self._note_ragged_rows("decode", len(decoding))
        batches = self._run("decodek", payload)
        toks = batches[0]
        try:
            toks.copy_to_host_async()
        except AttributeError:
            pass  # not all backends expose it; harvest still works
        self._dev_epoch = self._epoch
        self._dev_akey = akey
        dckey = costmodel.dispatch_key("decodek", payload)
        self._flights.append(_Flight(
            kind="decodek", arrays=[toks],
            meta={
                "k": k,
                "cost": dckey,
                "pred_ms": (self._costmodel.predict_ms("decodek", dckey)
                            if self._costmodel is not None else None),
                "pairs": [(s, s.request) for s in decoding],
                # None for a chained scan: its predecessor's last tokens
                # are unknown until that flight harvests (_harvest_last)
                "prev_last": (None if dflights else
                              {s.idx: int(tokens[s.idx, 0])
                               for s in decoding}),
                # enqueued behind another DECODE scan: its harvest-to-
                # harvest gap measures decode device time (the step
                # EWMA's input). A scan enqueued onto an idle device
                # measures device time + dispatch RTT, and one behind a
                # prefill_final measures prefill time too (_last_harvest_t
                # only advances on decode harvests) — neither may
                # pollute the EWMA, so a prefill anywhere in the
                # pipeline disqualifies the sample even when another
                # decode scan is also in flight (ADVICE r5 #1: the 8x
                # outlier guard alone let prefill-inflated samples
                # through and mis-sized the k clamps)
                "saturated": bool(dflights) and not any(
                    f.kind == "prefill_final" for f in self._flights),
                # timeline args for the flight recorder's harvest span
                "rec": {"rows": len(decoding), "k": k, "window": window},
            },
            t_enqueue=time.perf_counter(),
        ))
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel, composition="decode_only").inc()
        self._note_decode_advance(time.perf_counter())
        return True

    def _complete_decodek(self, fl: _Flight) -> None:
        """Harvest one k-step scan: emit tokens per slot, discarding
        overshoot past a finish (EOS/stop/limit)."""
        k = fl.meta["k"]
        # lint: ignore[hot-path-sync] flight ready() verified by _harvest; the transfer already landed
        toks_host = np.asarray(fl.arrays[0])  # [S, k]
        now = time.perf_counter()
        dt_ms = (now - max(fl.t_enqueue, self._last_harvest_t)) * 1e3
        self._last_harvest_t = now
        step = dt_ms / k
        if (fl.meta.get("saturated") and 0.0 < step
                and (self._step_ms == 0.0
                     or step < 8.0 * self._step_ms)):
            # EWMA per-step device time, from SATURATED samples only: a
            # scan enqueued onto an idle device (latency mode's depth-1
            # cadence) measures step + RTT, and feeding that back into
            # _latency_k collapses k to the floor and then mis-sizes
            # the balanced clamp too. Saturated samples keep flowing
            # whenever all slots are busy (full k, depth 2), which is
            # exactly when step time is cleanly observable. The 8x
            # outlier guard drops compile/transfer stalls.
            self._step_ms = (step if self._step_ms == 0.0
                             else 0.8 * self._step_ms + 0.2 * step)
            tm.ENGINE_DECODE_STEP.labels(model=self._mlabel).observe(
                step / 1e3)
        prev_last = fl.meta["prev_last"]
        if prev_last is None:
            prev_last = self._harvest_last
        # exemplar BEFORE the emit loop: a finishing slot deactivates
        # below, and its trace id is exactly the one worth linking
        exemplar = self._active_exemplar()
        emitted = 0
        next_last: dict[int, int] = {}
        for s, req in fl.meta["pairs"]:
            next_last[s.idx] = int(toks_host[s.idx, k - 1])
            if s.request is not req or s.state is not SlotState.DECODE:
                continue  # finished/cancelled in an earlier flight
            consumed = [prev_last[s.idx]] + [
                int(t) for t in toks_host[s.idx, : k - 1]
            ]
            s.t_decode_ms += dt_ms
            for j in range(k):
                if s.state is not SlotState.DECODE:
                    break  # finished: discard overshoot tokens
                s.cache_tokens.append(consumed[j])
                s.n_past += 1
                emitted += 1
                self._emit_token(s, int(toks_host[s.idx, j]),
                                 defer=True)
            if s.state is SlotState.DECODE:
                self._flush_emit(s)  # one event per slot per harvest
        self._harvest_last = next_last
        if dt_ms > 0 and emitted:
            self._note_tokens_per_second(emitted, dt_ms / 1e3)
            tm.ENGINE_GENERATED_TOKENS.labels(model=self._mlabel).inc(
                emitted)
            tm.ENGINE_INTER_TOKEN.labels(model=self._mlabel).observe(
                dt_ms / 1e3 / k, exemplar=exemplar)
        self.metrics.slots_busy = sum(1 for s in self.slots if s.active)

    def _decode1_step(self, decoding: list[_Slot]) -> None:
        """Blocking single-step decode for host-interactive slots
        (grammar masks / logit_bias need fresh host work every token)."""
        t0 = time.perf_counter()
        S = self.n_slots
        if self._paged:
            for s in list(decoding):
                if not self._pool_ensure(s, s.n_past + 1):
                    self._finish(s, "length")
                    decoding.remove(s)
            if not decoding:
                return
        tokens = np.zeros((S, 1), np.int32)
        pos0 = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for s in self.slots:
            if s.state is SlotState.DECODE:
                tokens[s.idx, 0] = (s.generated[-1] if s.generated
                                    else s.request.prompt_ids[-1])
                pos0[s.idx] = s.n_past
                active[s.idx] = True
            else:
                pos0[s.idx] = min(s.n_past, self.max_seq - 1)
        masks = self._constraint_mask_rows(self.slots)
        payload = {
            "tokens": tokens, "pos0": pos0, "active": active,
            "masks": masks,
        }
        if self._paged:
            payload["pt"] = self._phys_rows(list(range(S)), self.max_seq)
            payload["wb"] = self._wb_rows(
                [(s.idx, ((s.n_past, s.n_past + 1)
                          if s.state is SlotState.DECODE else None))
                 for s in self.slots], self.max_seq)
        toks = self._run("decode1", payload)
        # lint: ignore[hot-path-sync] decode1 IS the blocking path: grammar masks / logit bias need every token on host before the next dispatch
        toks_host = np.asarray(toks)
        dt_ms = (time.perf_counter() - t0) * 1e3
        emitted = 0
        for s in decoding:
            s.cache_tokens.append(int(tokens[s.idx, 0]))
            s.n_past += 1
            s.t_decode_ms += dt_ms
            emitted += 1
            self._emit_token(s, int(toks_host[s.idx]))
        self._epoch += 1  # device carry (if any) is now stale
        if dt_ms > 0 and emitted:
            self._note_tokens_per_second(emitted, dt_ms / 1e3)
            tm.ENGINE_GENERATED_TOKENS.labels(model=self._mlabel).inc(
                emitted)
        tm.ENGINE_MIXED_DISPATCH.labels(
            model=self._mlabel, composition="decode_only").inc()
        self._note_ragged_rows("decode", len(decoding))
        self._note_decode_advance(t0)
        self.metrics.slots_busy = sum(1 for s in self.slots if s.active)

    # lint: endregion hot_path

    # ---------------------------------------------------- token → stream

    def _emit_token(self, slot: _Slot, token_id: int,
                    defer: bool = False) -> None:
        """Per-sampled-token bookkeeping (ref: process_token,
        grpc-server.cpp:1069-1160: stop words, EOS, limits).

        ``defer=True`` (harvest loops): per-token semantics (stops, EOS,
        limits, grammar advance) run exactly as before, but the text
        spans buffer on the slot and flush as ONE StreamEvent per
        harvest (_flush_emit) — per-token queue puts woke 64 consumer
        threads 1024 times per k=16 scan, a measured multi-hundred-ms
        GIL pile-up at burst time."""
        req = slot.request
        assert req is not None and slot.decoder is not None
        if req.constraint is not None:
            slot.constraint_state = req.constraint.advance(
                slot.constraint_state, token_id
            )
        if not slot.generated:
            # first token of the request: TTFT and prefill attribution
            # (host timestamps only; guarded so the per-token path pays
            # one list check)
            slot.t_first = time.perf_counter()
            TRACER.event(req.id, "first_token", t=slot.t_first)
            if req.t_submit:
                # OpenMetrics exemplar: the trace id links this bucket
                # sample to its /debug/traces entry
                tm.ENGINE_TTFT.labels(model=self._mlabel).observe(
                    slot.t_first - req.t_submit,
                    exemplar=({"trace_id": req.trace_id}
                              if req.trace_id else None))
            tm.ENGINE_PREFILL.labels(model=self._mlabel).observe(
                slot.t_prefill_ms / 1e3)
        slot.generated.append(token_id)
        self.metrics.tokens_generated += 1

        if (not req.ignore_eos) and token_id in self.tokenizer.eos_ids:
            self._finish(slot, "stop")
            return

        text = slot.decoder.push(token_id)
        slot.pending_text += text

        # stop-string scan with partial-match withholding
        emit, stop_hit = _scan_stops(slot.pending_text, req.stop)
        if stop_hit:
            if slot.out is not None:
                self._flush_emit(slot)
                slot.out.put(StreamEvent(text=emit, token_id=token_id))
            slot.pending_text = ""
            self._finish(slot, "stop")
            return
        if defer:
            if emit:
                slot.emit_buf.append(emit)
            if slot.emit_tok is None:
                slot.emit_tok = token_id
        elif slot.out is not None:
            slot.out.put(StreamEvent(text=emit, token_id=token_id))
        if emit:
            slot.pending_text = slot.pending_text[len(emit):]

        if len(slot.generated) >= req.max_tokens:
            self._finish(slot, "length")
        elif slot.n_past + 1 >= self.max_seq:
            # context exhausted: end generation (ref: grpc-server.cpp
            # :1673-1683 — no context shift)
            self._finish(slot, "length")

    def _flush_emit(self, slot: _Slot) -> None:
        """Put the buffered text spans as one stream event. A harvest
        whose text was fully withheld (partial stop-string match /
        multi-byte tail) puts NOTHING — an empty event would wake the
        consumer thread for a no-op, re-creating the wakeup storm this
        buffering removes."""
        if not slot.emit_buf:
            slot.emit_tok = None
            return
        if slot.out is not None:
            slot.out.put(StreamEvent(text="".join(slot.emit_buf),
                                     token_id=slot.emit_tok))
        slot.emit_buf = []
        slot.emit_tok = None

    def _finish(self, slot: _Slot, reason: str) -> None:
        req = slot.request
        self._flush_emit(slot)  # buffered text precedes the done event
        self._maybe_save_prompt_cache(slot)
        if self._migrator is not None and req is not None:
            # disaggregated prefill side: a finishing prefill-probe
            # slot's pages are captured into the migration bus HERE,
            # before release can recycle them (the gather lands first
            # in device order, so later overwrites are safe). No-op
            # for ordinary requests.
            self._migrator.on_finish(slot, reason)
        full = slot.decoder.text if slot.decoder else ""
        if req is not None and req.stop:
            for st in req.stop:
                i = full.find(st)
                if i >= 0:
                    full = full[:i]
        # strip trailing eos token artifacts is tokenizer-dependent; decoder
        # already excludes eos because we finish before pushing it
        if slot.pending_text and reason != "stop":
            if slot.out is not None and slot.pending_text:
                slot.out.put(StreamEvent(text=slot.pending_text))
        dt_decode = slot.t_decode_ms
        now = time.perf_counter()
        queue_ms = ttft_ms = 0.0
        if req is not None and req.t_submit:
            queue_ms = max(0.0, (slot.t_start - req.t_submit) * 1e3)
            if slot.t_first:
                ttft_ms = (slot.t_first - req.t_submit) * 1e3
        if req is not None and req.disagg is not None:
            # migrated request: queue time is what the request spent
            # QUEUED on either engine (original wait on the prefill
            # side + re-admission wait here), not the whole relay —
            # prefill device time and migration wall already live in
            # timing_prompt_processing_ms (stamped at adoption)
            h = req.disagg
            queue_ms = h.queued_ms + max(
                0.0, (slot.t_start - h.t_resubmit) * 1e3)
            tm.ENGINE_DISAGG_STAGE.labels(
                model=self._mlabel, stage="decode").observe(
                max(0.0, now - h.t_resubmit))
        ev = StreamEvent(
            done=True,
            finish_reason=reason,
            full_text=full,
            prompt_tokens=slot.n_prompt,
            completion_tokens=len(slot.generated),
            timing_prompt_processing_ms=slot.t_prefill_ms,
            timing_token_generation_ms=dt_decode,
            timing_queue_ms=queue_ms,
            timing_first_token_ms=ttft_ms,
            timing_prefill_enqueue_ms=slot.t_prefill_enq_ms,
        )
        if slot.out is not None:
            slot.out.put(ev)
        self.metrics.requests_completed += 1
        tm.ENGINE_REQUESTS.labels(model=self._mlabel, reason=reason).inc()
        if reason == "cancelled":
            tm.ENGINE_CANCELLATIONS.labels(model=self._mlabel,
                                           reason="client").inc()
        if req is not None:
            TRACER.event(req.id, "done", t=now)
            TRACER.annotate(req.id, "terminal", t=now, outcome=reason)
            TRACER.finish(req.id, status=reason)
        self._release(slot)

    def _release(self, slot: _Slot) -> None:
        # cache_tokens stay: they describe this row's reusable prefix.
        # Exception: multimodal rows — soft tokens share one id across
        # DIFFERENT images, so their K/V must never be prefix-matched
        if slot.request is not None and slot.request.soft_embeds is not None:
            slot.cache_tokens = []
            slot.n_past = 0
            if self._paged:
                self._pool.drop(slot.idx)
        self._epoch += 1
        slot.state = SlotState.FREE
        slot.request = None
        slot.out = None
        slot.decoder = None
        slot.pending_text = ""
        slot.emit_buf = []
        slot.emit_tok = None
        slot.constraint_state = None

    # ------------------------------------------------------------- extras

    def tokenize(self, text: str) -> list[int]:
        return self.tokenizer.encode(text)

    def embed(self, text: str) -> np.ndarray:
        """Mean-pooled final hidden state (ref: transformers backend
        mean-pool embeddings, backend/python/transformers/backend.py
        :286-324; served via /v1/embeddings). Uses a throwaway 1-slot cache;
        does not touch the serving slots."""
        ids = self.tokenizer.encode(text, add_bos=True) or [0]
        ids = ids[: self.max_seq]
        bucket = self._bucket(len(ids))
        toks = np.zeros((1, bucket), np.int32)
        toks[0, : len(ids)] = ids
        hidden = self._run("embed", {"toks": toks, "bucket": bucket})
        h = np.asarray(hidden[0, : len(ids)], dtype=np.float32)
        return h.mean(axis=0)


def _scan_stops(pending: str, stops: list[str]) -> tuple[str, bool]:
    """Return (text safe to emit, hit). Withholds any tail that is a prefix
    of a stop string (ref: stop-word partial matching in process_token)."""
    if not stops:
        return pending, False
    for st in stops:
        i = pending.find(st)
        if i >= 0:
            return pending[:i], True
    # find longest suffix of pending that is a prefix of some stop
    hold = 0
    for st in stops:
        for k in range(min(len(st) - 1, len(pending)), 0, -1):
            if pending.endswith(st[:k]):
                hold = max(hold, k)
                break
    return pending[: len(pending) - hold] if hold else pending, False
