"""Layer-granular weight paging: HBM-hot weights, host-RAM warm tier.

A gallery deployment keeps dozens of models registered but only a few
in flight at once; the reference handles that with whole-process
lifecycle (per-model backend spawn, watchdog idle reap — pkg/model
watchdog.go) so an idle model's next request pays a full checkpoint
load. Here weights page instead: every engine owns a
:class:`WeightPager` that can move its parameter tree between

- HOT: the ordinary device-resident stacked tree (``eng.params``) —
  the serving path is untouched; a hot model's dispatches see the same
  arrays they would without paging (``LOCALAI_WEIGHT_PAGING=off`` is
  byte-identical by construction).
- WARM: the same tree mirrored to host RAM as numpy leaves (int8 ``q``
  planes and their f32 scale planes both — a round trip is bit-exact),
  device copy dropped. A warm model's engine, tokenizer, dispatch
  cache and KV state all survive; only the weights left the chip.

Both moves are layer-granular thanks to the stacked-scan layout
(models/hf_loader.py ``layer_pages``): a "page" is row ``li`` of every
stacked ``[L, ...]`` leaf, plus one globals page (embed / final norm /
lm head). Granularity buys the two properties the whole design exists
for:

- DEMOTION never blocks a device step. It runs on its own background
  thread through the same ``copy_to_host_async`` + ``TransferWindow``
  discipline as the KV tier's spill (models/staging.py) — the
  scheduler thread never waits on the D2H stream; the demote thread
  does all the blocking. The thread only fires while the engine is
  quiescent and abandons itself the moment work arrives
  (``tick`` sets the abort flag from the scheduler's admission pass).
- PROMOTION streams layers ahead of a commit cursor: layer ``i``
  commits into the growing stacked tree (donated
  ``dynamic_update_index_in_dim``, one jitted scatter reused across
  layers) while layers ``i+1..i+k`` ride the H2D link
  (``LOCALAI_WEIGHT_PREFETCH_AHEAD`` deep). A warm model's first
  token costs one overlapped weight stream — hundreds of ms — not an
  ``hf_loader`` ingest.

Cross-engine policy lives in the process-global :data:`COORD`: every
pager registers (weakly — an unclosed test engine must stay
collectable) and ``pressure()`` demotes least-recently-used hot
victims whenever hot bytes would exceed ``LOCALAI_WEIGHT_HBM_MB``.
The warm mirror is RETAINED after promotion (and seeded by the quant
artifact's ``keep_host`` on first load), so a clean model's next
demotion is a zero-DMA bookkeeping drop ("seed" outcome).

Meshed, follower, draft-carrying and disagg engines force paging off:
sharded trees don't round-trip through one host mirror, and disagg
prefill/decode pairs share one tree by reference.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import knobs
from ..models.hf_loader import layer_pages
from ..models.quant import QTensor
from ..models.staging import TransferWindow
from ..telemetry import metrics as tm
from ..telemetry.flightrec import FLIGHT, WEIGHTS_TRACK
from ..utils import faultinject

log = logging.getLogger("localai.weights")

__all__ = ["WeightPager", "PagerCoordinator", "COORD"]


# one jitted scatter shared by every promotion: donating the stacked
# buffer makes each layer commit an in-place row write, and passing the
# layer index as a traced scalar keeps it ONE compile per (shape,
# dtype), not one per layer
_scatter_fns: dict = {}


def _scatter(stack, row, li):
    key = (stack.shape, str(stack.dtype))
    fn = _scatter_fns.get(key)
    if fn is None:
        fn = jax.jit(
            lambda s, r, l: jax.lax.dynamic_update_index_in_dim(
                s, r, l, 0),
            donate_argnums=(0,))
        _scatter_fns[key] = fn
    return fn(stack, row, jnp.int32(li))


def _leaf_nbytes(leaf) -> int:
    if isinstance(leaf, QTensor):
        return int(leaf.q.nbytes) + int(leaf.scale.nbytes)
    return int(getattr(leaf, "nbytes", 0))


def _tree_nbytes(tree: Optional[dict]) -> int:
    if not tree:
        return 0
    return sum(_leaf_nbytes(v) for v in tree.values())


class PagerCoordinator:
    """Process-global LRU across every live pager.

    Holds WEAK references: a pager pins its engine's parameter tree,
    so the coordinator must never keep a closed/leaked engine's pager
    (and its multi-GB host mirror) alive. ``pressure()`` reads the
    ``LOCALAI_WEIGHT_HBM_MB`` budget at call time (0 = unlimited) and
    asks least-recently-used hot victims to demote until the hot set
    fits — demotion is asynchronous, so the budget is a target the
    fleet converges to, not a hard admission gate.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pagers: list = []  # weakrefs  # lint: guarded-by self._lock
        self.counters = {"pressure_demotes": 0}

    def register(self, pager: "WeightPager") -> None:
        with self._lock:
            self._pagers.append(weakref.ref(pager))

    def unregister(self, pager: "WeightPager") -> None:
        with self._lock:
            self._pagers = [r for r in self._pagers
                            if r() is not None and r() is not pager]

    def _live(self) -> list:
        with self._lock:
            live = [p for p in (r() for r in self._pagers)
                    if p is not None and not p.closed]
            self._pagers = [weakref.ref(p) for p in live]
        return live

    def pressure(self, requester: Optional["WeightPager"] = None) -> int:
        """Demote LRU hot victims until hot bytes (plus the requester's
        incoming tree) fit the budget. Returns victims asked."""
        budget = int(knobs.float_("LOCALAI_WEIGHT_HBM_MB") * (1 << 20))
        if budget <= 0:
            return 0
        live = self._live()
        need = (requester.tree_bytes()
                if requester is not None else 0)
        hot = [p for p in live
               if p.state == "hot" and p is not requester]
        total = need + sum(p.tree_bytes() for p in hot)
        asked = 0
        for victim in sorted(hot, key=lambda p: p.last_used):
            if total <= budget:
                break
            if victim.request_demote(reason="pressure"):
                total -= victim.tree_bytes()
                asked += 1
                with self._lock:
                    self.counters["pressure_demotes"] += 1
        self.update_residency()
        return asked

    def residency(self) -> dict:
        counts = {"hot": 0, "warm": 0, "transitioning": 0}
        for p in self._live():
            st = p.state
            counts["hot" if st == "hot" else
                   "warm" if st == "warm" else "transitioning"] += 1
        return counts

    def update_residency(self) -> None:
        for state, n in self.residency().items():
            tm.ENGINE_MODEL_RESIDENCY.labels(state=state).set(n)


COORD = PagerCoordinator()


class WeightPager:
    """Weight residency state machine for one single-chip engine.

    States: ``hot`` (tree on device, engine serves normally) ->
    ``demoting`` (background D2H) -> ``warm`` (host mirror only,
    ``eng.params is None``) -> ``promoting`` (layer-streamed H2D) ->
    ``hot``. All transitions happen under ``self._plock``; the engine's
    scheduler only ever calls :meth:`tick` / :meth:`poll_admission`,
    which never block on a transfer. ``self._plock`` must never be
    held while taking ``eng._lock`` (the promote thread notifies the
    engine OUTSIDE the pager lock) — the reverse order is what the
    scheduler uses.
    """

    def __init__(self, eng) -> None:
        self.eng = eng
        self._mlabel = eng._mlabel
        self.n_layers = int(eng.spec.n_layers)
        self.n_pages = self.n_layers + 1  # + the globals page
        self.ahead = max(1, knobs.int_("LOCALAI_WEIGHT_PREFETCH_AHEAD"))
        self._plock = threading.RLock()
        self.state = "hot"  # lint: guarded-by self._plock
        self._host: Optional[dict] = None  # lint: guarded-by self._plock
        self._host_src: Optional[int] = None  # id() of mirrored tree  # lint: guarded-by self._plock
        self._abort = False  # lint: guarded-by self._plock
        self._thread: Optional[threading.Thread] = None  # lint: guarded-by self._plock
        self._cursor = 0  # committed layer pages while promoting  # lint: guarded-by self._plock
        self._device_bytes = _tree_nbytes(eng.params)
        self._hot_event = threading.Event()
        self._hot_event.set()
        self.last_used = time.monotonic()
        self.closed = False
        self.counters = {
            "demotes": 0, "promotes": 0, "seed_demotes": 0,
            "cold_fallbacks": 0, "aborted_demotes": 0,
            "faulted_demotes": 0, "faulted_fetches": 0,
        }
        COORD.register(self)
        # a new model arriving hot is itself HBM pressure: ask the
        # fleet's LRU members to yield before this engine's first step
        COORD.pressure(self)
        COORD.update_residency()

    # ------------------------------------------------------ scheduler API

    def tick(self) -> None:
        """Scheduler-thread hook (top of the admission pass): work
        arriving while a demotion is in flight aborts it — serving
        latency always wins over paging progress. Never blocks."""
        with self._plock:
            if self.state == "demoting" and self.eng._has_work():
                self._abort = True

    def poll_admission(self) -> bool:
        """May the scheduler admit work right now? Hot -> yes (and the
        touch feeds the cross-engine LRU). Warm -> kick a promotion and
        say no; the caller requeues its poured requests and retries
        next pass. Transitioning -> no (demotions self-abort via
        :meth:`tick`; promotions finish on their own thread)."""
        self.last_used = time.monotonic()
        with self._plock:
            if self.state == "hot":
                return True
            if self.state == "warm":
                self._start_promote_locked()
            return False

    # --------------------------------------------------------- demotion

    def request_demote(self, reason: str = "explicit") -> bool:
        """Begin an async demotion (hot engines only). Returns whether
        a demote thread was started; completion is asynchronous — the
        engine keeps serving until the final quiescent drop."""
        with self._plock:
            if self.closed or self.state != "hot":
                return False
            if self.eng._has_work():
                return False
            self.state = "demoting"
            self._abort = False
            self._hot_event.clear()
            t = threading.Thread(target=self._demote, daemon=True,
                                 name="weights-demote")
            self._thread = t
        log.info("weight demotion (%s): %s", reason, self._mlabel)
        t.start()
        COORD.update_residency()
        return True

    def _abandon_demote(self, outcome: str) -> None:
        with self._plock:
            self.state = "hot"
            self._hot_event.set()
        self.counters["aborted_demotes" if outcome == "aborted"
                      else "faulted_demotes"] += 1
        tm.ENGINE_WEIGHT_PAGE_MOVES.labels(
            model=self._mlabel, direction="demote",
            outcome=outcome).inc()
        COORD.update_residency()

    def _demote(self) -> None:
        """Background D2H page-out. Blocking waits are FINE here — this
        thread owns them, the scheduler never joins it. The device tree
        is dropped only at the very end, under the pager lock, after a
        final quiescence check; any abandonment leaves the engine
        exactly hot."""
        eng = self.eng
        params = eng.params
        if params is None:  # raced a close/reload
            self._abandon_demote("aborted")
            return
        if eng._has_work():
            self._abandon_demote("aborted")
            return
        try:
            if faultinject.ACTIVE:
                faultinject.fire("weights.demote")
        except faultinject.InjectedFault:
            # abandoned BEFORE any copy or bookkeeping: the model stays
            # hot and serves; chaos tests assert exactly this
            self._abandon_demote("fault")
            return
        with self._plock:
            seeded = (self._host is not None
                      and self._host_src == id(params))
        outcome = "seed" if seeded else "ok"
        host: Optional[dict] = None
        if not seeded:
            t0 = time.perf_counter()
            budget = int(
                knobs.float_("LOCALAI_WEIGHT_INFLIGHT_MB") * (1 << 20))
            window = TransferWindow(budget)
            flying: list[tuple[str, Any]] = []
            nbytes_total = 0
            aborted = False
            for name, leaf in params.items():
                with self._plock:
                    aborted = self._abort
                if aborted:
                    break
                handles = ((leaf.q, leaf.scale)
                           if isinstance(leaf, QTensor) else (leaf,))
                nbytes = _leaf_nbytes(leaf)
                if window.over(nbytes):
                    window.drain(nbytes)
                for h in handles:
                    h.copy_to_host_async()
                window.add(name, nbytes, handles)
                flying.append((name, leaf))
                nbytes_total += nbytes
            if aborted:
                window.forget()  # DMAs land on their own; stop tracking
                self._abandon_demote("aborted")
                return
            window.flush()
            # handles already on host: these asarray calls copy from
            # the cached host buffer, they do not sync the device
            host = {}
            for name, leaf in flying:
                if isinstance(leaf, QTensor):
                    host[name] = QTensor(q=np.asarray(leaf.q),
                                         scale=np.asarray(leaf.scale))
                else:
                    host[name] = np.asarray(leaf)
            FLIGHT.transfer("demote", t0, time.perf_counter() - t0,
                            self.n_pages, nbytes_total, blocking=False,
                            track=WEIGHTS_TRACK, prefix="w")
        with self._plock:
            if self._abort or self.eng._has_work():
                # work arrived during the copy: keep serving hot. The
                # mirror we just paid for stays valid, so the NEXT
                # demotion is a free seed drop
                if host is not None:
                    self._host = host
                    self._host_src = id(params)
                self.state = "hot"
                self._hot_event.set()
                aborted = True
            else:
                if host is not None:
                    self._host = host
                    self._host_src = id(params)
                self.eng.params = None
                self._device_bytes = 0
                self.state = "warm"
                aborted = False
        del params
        if aborted:
            self.counters["aborted_demotes"] += 1
            tm.ENGINE_WEIGHT_PAGE_MOVES.labels(
                model=self._mlabel, direction="demote",
                outcome="aborted").inc()
        else:
            self.counters["demotes"] += 1
            if seeded:
                self.counters["seed_demotes"] += 1
            tm.ENGINE_WEIGHT_PAGE_MOVES.labels(
                model=self._mlabel, direction="demote",
                outcome=outcome).inc(self.n_pages)
        COORD.update_residency()

    # -------------------------------------------------------- promotion

    def _start_promote_locked(self) -> None:
        # lint: holds self._plock
        if self.state != "warm" or self._host is None:
            return
        self.state = "promoting"
        self._cursor = 0
        t = threading.Thread(target=self._promote, daemon=True,
                             name="weights-promote")
        self._thread = t
        t.start()

    def ensure_hot(self, timeout_s: float = 60.0) -> bool:
        """Block (caller's thread — never the scheduler) until the tree
        is device-resident. Kicks a promotion when warm, aborts an
        in-flight demotion, and returns whether hot was reached."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._plock:
                if self.state == "hot":
                    return True
                if self.state == "warm":
                    self._start_promote_locked()
                elif self.state == "demoting":
                    self._abort = True
            if self._hot_event.wait(timeout=min(
                    0.1, max(0.0, deadline - time.monotonic()))):
                with self._plock:
                    if self.state == "hot":
                        return True
        return self.state == "hot"

    def _promote(self) -> None:
        """Layer-streamed H2D promotion. Double-buffered: while layer
        ``i`` commits into the stacked tree (donated scatter), layers
        up to ``i + ahead`` are already riding the link. Installs the
        finished tree itself — the engine is either idle (nothing to
        race) or spinning in the requeue gate waiting for exactly
        this."""
        eng = self.eng
        t_all = time.perf_counter()
        with self._plock:
            host = self._host
        if host is None:  # invalidated under us
            with self._plock:
                if self.state == "promoting":
                    self.state = "warm"
            return
        COORD.pressure(self)  # make room before bytes start landing
        try:
            assembled = self._stream_in(host)
            result = "warm"
        except faultinject.InjectedFault:
            # fault on the streamed path: fall back to one plain
            # blocking load of the full host mirror — no fire() on this
            # leg, the request must still serve
            self.counters["faulted_fetches"] += 1
            self.counters["cold_fallbacks"] += 1
            tm.ENGINE_WEIGHT_PREFETCH.labels(
                model=self._mlabel, result="fault").inc()
            assembled = jax.device_put(host)
            jax.block_until_ready(assembled)
            result = "cold"
        except Exception:
            log.exception("weight promotion failed; model stays warm")
            with self._plock:
                if self.state == "promoting":
                    self.state = "warm"
            COORD.update_residency()
            return
        with self._plock:
            if self.state != "promoting":  # closed under us
                return
            eng.params = assembled
            self._device_bytes = _tree_nbytes(assembled)
            self._cursor = self.n_pages
            self.state = "hot"
            # the host mirror still bit-matches the tree we just built
            # from it: re-point the seed marker at the new params object
            # so the NEXT demotion is a zero-DMA drop
            self._host_src = id(assembled)
            self._hot_event.set()
        self.counters["promotes"] += 1
        tm.ENGINE_WEIGHT_PREFETCH.labels(
            model=self._mlabel, result=result).inc()
        tm.ENGINE_WEIGHT_PAGE_MOVES.labels(
            model=self._mlabel, direction="promote",
            outcome="ok").inc(self.n_pages)
        FLIGHT.transfer("promote", t_all,
                        time.perf_counter() - t_all, self.n_pages,
                        self._device_bytes, blocking=False,
                        track=WEIGHTS_TRACK, prefix="w")
        COORD.update_residency()
        # wake the scheduler's admission wait OUTSIDE the pager lock
        with eng._lock:
            eng._lock.notify_all()

    def _stream_in(self, host: dict) -> dict:
        """The streamed promotion body; raises InjectedFault through to
        the caller's cold-fallback leg."""
        L = self.n_layers
        layered, globals_, page = layer_pages(host, L)
        # growing stacked tree: zeros now, one donated row-scatter per
        # layer as each page's H2D lands
        stacked: dict = {}
        for k, v in layered.items():
            if isinstance(v, QTensor):
                stacked[k] = QTensor(
                    q=jnp.zeros(v.q.shape, v.q.dtype),
                    scale=jnp.zeros(v.scale.shape, v.scale.dtype))
            else:
                stacked[k] = jnp.zeros(v.shape, v.dtype)

        def commit(li: int, rows: dict, t0: float, nbytes: int) -> None:
            for k, r in rows.items():
                if isinstance(r, QTensor):
                    stacked[k] = QTensor(
                        q=_scatter(stacked[k].q, r.q, li),
                        scale=_scatter(stacked[k].scale, r.scale, li))
                else:
                    stacked[k] = _scatter(stacked[k], r, li)
            with self._plock:
                self._cursor = li + 1
            FLIGHT.transfer("fetch", t0, time.perf_counter() - t0, 1,
                            nbytes, blocking=False,
                            track=WEIGHTS_TRACK, prefix="w")

        flight: deque = deque()  # (li, device rows, t0, nbytes)
        for li in range(L):
            if faultinject.ACTIVE:
                faultinject.fire("weights.fetch")
            t0 = time.perf_counter()
            rows = page(li)
            dev = jax.device_put(rows)  # async H2D enqueue
            flight.append(
                (li, dev, t0, sum(_leaf_nbytes(r)
                                  for r in rows.values())))
            while len(flight) > self.ahead:
                commit(*flight.popleft())
        while flight:
            commit(*flight.popleft())
        out = dict(stacked)
        for k, v in globals_.items():
            out[k] = jax.device_put(v)
        jax.block_until_ready(out)
        return out

    # ------------------------------------------------------- host mirror

    def seed_host(self, host: dict, params_obj: Any) -> None:
        """Adopt a ready-made host mirror of ``params_obj`` (the quant
        artifact's ``keep_host`` capture): the model's first demotion
        becomes a zero-DMA drop."""
        if not host:
            return
        with self._plock:
            self._host = dict(host)
            self._host_src = id(params_obj)

    def invalidate_host(self) -> None:
        """The engine's tree was reassigned in place (LoRA apply /
        remove): the mirror no longer matches — drop it so the next
        demotion re-copies."""
        with self._plock:
            self._host = None
            self._host_src = None
            self._device_bytes = _tree_nbytes(self.eng.params)

    # ------------------------------------------------------ diagnostics

    def tree_bytes(self) -> int:
        """Size of the full tree (device bytes when hot, the host
        mirror's when not — same dtypes, same total)."""
        if self._device_bytes:
            return self._device_bytes
        with self._plock:
            return _tree_nbytes(self._host)

    def device_bytes(self) -> int:
        """Ledger source for ``weights_hot``: device-resident weight
        bytes right now (the commit cursor's fraction while a
        promotion streams)."""
        with self._plock:
            if self.state in ("hot", "demoting"):
                return self._device_bytes
            if self.state == "promoting":
                full = _tree_nbytes(self._host)
                return int(full * self._cursor / max(1, self.n_pages))
            return 0

    def host_bytes(self) -> int:
        """Ledger source for ``weights_warm`` (host=True): bytes held
        by the warm mirror, including while it backs a hot tree."""
        with self._plock:
            return _tree_nbytes(self._host)

    def tier_pages(self) -> dict:
        """{"hot": pages, "warm": pages} for the gauge family; a
        promotion reports its committed cursor, so the hot count
        climbs layer by layer."""
        with self._plock:
            if self.state in ("hot", "demoting"):
                hot = self.n_pages
            elif self.state == "promoting":
                hot = self._cursor
            else:
                hot = 0
            warm = self.n_pages if self._host is not None else 0
        return {"hot": hot, "warm": warm}

    def stats(self) -> dict:
        with self._plock:
            return {
                "state": self.state,
                "pages": self.n_pages,
                "device_bytes": self.device_bytes(),
                "host_bytes": _tree_nbytes(self._host),
                "seeded": self._host is not None,
                **self.counters,
            }

    def leak_check(self) -> None:
        """State-machine invariants; raises AssertionError."""
        with self._plock:
            st = self.state
            if st == "hot" and self.eng.params is None \
                    and not self.closed:
                raise AssertionError("hot pager with no device tree")
            if st == "warm":
                if self.eng.params is not None:
                    raise AssertionError(
                        "warm pager but eng.params still set")
                if self._host is None:
                    raise AssertionError(
                        "warm pager with no host mirror (weights lost)")
                if self._device_bytes != 0:
                    raise AssertionError(
                        "warm pager still accounting device bytes")
            if self._host is not None:
                n_host = len(self._host)
                if st == "hot" and self.eng.params is not None \
                        and n_host != len(self.eng.params):
                    raise AssertionError(
                        "host mirror leaf count diverged from tree")

    # -------------------------------------------------------- lifecycle

    def settle(self, timeout_s: float = 30.0) -> bool:
        """Wait for any in-flight transition to land (tests/tools only;
        the scheduler never calls this). Returns settled."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._plock:
                t = self._thread
                if self.state in ("hot", "warm") and (
                        t is None or not t.is_alive()):
                    return True
            if t is not None:
                t.join(timeout=0.05)
            else:
                time.sleep(0.01)
        return False

    def close(self) -> None:
        """Engine teardown: abort anything in flight, wait for the
        worker thread, release the mirror and deregister."""
        with self._plock:
            self.closed = True
            self._abort = True
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        with self._plock:
            # a promotion that lost the race to closed leaves state
            # "promoting"; normalize so residency gauges read sanely
            if self.state == "demoting":
                self.state = "hot"
            elif self.state == "promoting":
                self.state = "warm" if self._host is not None else "hot"
            self._host = None
            self._host_src = None
            self._hot_event.set()
        COORD.unregister(self)
        COORD.update_residency()
