"""Minimal Go text/template interpreter for LocalAI model templates.

The reference renders model YAML templates with Go text/template (+ a
sprig function subset) — pkg/templates/evaluator.go:95-117. Round-3
review (VERDICT weak #5) flagged the old regex→Jinja transpile as
covering only ``{{.Field}}/{{if}}``: gallery templates also use ``eq``,
``range``, ``index``, ``toJson``, ``$variables``, trim markers and
sprig helpers, and silently mis-rendered. This module evaluates that
dialect directly — the constructs observed across the reference's
gallery YAMLs and evaluator tests:

    {{.Field.Chain}}  {{- trim markers -}}
    {{if pipeline}} … {{else if pipeline}} … {{else}} … {{end}}
    {{range $k, $v := pipeline}} … {{else}} … {{end}}
    {{$var := pipeline}}  {{$var = pipeline}}
    functions: eq ne lt le gt ge and or not index len print printf
               toJson add1 add sub trim contains hasPrefix hasSuffix
               default empty upper lower title join quote replace

Semantics follow Go text/template where they matter for prompts: zero
values are falsy, ``range`` over maps iterates in sorted key order
(text/template sorts string map keys), pipelines feed the previous
value as the LAST argument of the next command.
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

__all__ = ["GoTemplate", "GoTemplateError", "looks_like_go_template"]


class GoTemplateError(ValueError):
    pass


_ACTION = re.compile(r"\{\{(-)?((?:[^}\"`]|\"(?:\\.|[^\"\\])*\"|`[^`]*`)*?)(-)?\}\}",
                     re.S)

_GO_HINT = re.compile(
    r"\{\{-|\{\{\s*(\.|\$|if\s|else\b|end\b|range\s|with\s)"
    r"|\{\{\s*\w+\s+[.$\"]"
)


def looks_like_go_template(src: str) -> bool:
    """Heuristic: Go actions present and no Jinja statement blocks."""
    return bool(_GO_HINT.search(src)) and "{%" not in src


# ------------------------------------------------------------ tokenizing

_EXPR_TOK = re.compile(
    r'"(?:\\.|[^"\\])*"'  # interpreted string
    r"|`[^`]*`"  # raw string
    r"|:=|=(?!=)|\(|\)|\||,"
    r"|[^\s()|,:=\"`]+"
)


def _lex_expr(src: str) -> list[str]:
    return _EXPR_TOK.findall(src)


def _split_actions(src: str):
    """Yield ("text", s) / ("action", body) with trim markers applied
    (Go: ``{{- `` trims whitespace before the action, `` -}}`` after).
    A chunk between `` -}}`` and ``{{- `` gets BOTH strips (the rtrim is
    deferred so a following ltrim can still reach the same chunk)."""
    parts: list[tuple[str, str]] = []
    pos = 0
    pending_rtrim = False
    for m in _ACTION.finditer(src):
        text = src[pos:m.start()]
        if pending_rtrim:
            text = text.lstrip()
        if m.group(1):  # left trim
            text = text.rstrip()
        parts.append(("text", text))
        parts.append(("action", m.group(2).strip()))
        pending_rtrim = bool(m.group(3))
        pos = m.end()
    text = src[pos:]
    if pending_rtrim:
        text = text.lstrip()
    parts.append(("text", text))
    return [(k, v) for k, v in parts if not (k == "text" and v == "")]


# --------------------------------------------------------------- parsing
# node forms:
#   ("text", s)
#   ("out", expr_tokens)
#   ("assign", varname, expr_tokens, declare: bool)
#   ("if", [(cond_tokens, body), ...], else_body | None)
#   ("range", kvar, vvar, expr_tokens, body, else_body | None)


def _parse(parts, i=0, *, stop=()):
    nodes = []
    while i < len(parts):
        kind, val = parts[i]
        if kind == "text":
            nodes.append(("text", val))
            i += 1
            continue
        word = val.split(None, 1)[0] if val else ""
        if word in stop:
            return nodes, i
        if word == "if":
            arms = []
            cond = _lex_expr(val[2:])
            body, i = _parse(parts, i + 1, stop=("else", "end"))
            arms.append((cond, body))
            else_body = None
            while True:
                _, ctl = parts[i]
                if ctl.startswith("else"):
                    rest = ctl[4:].strip()
                    if rest.startswith("if"):
                        cond = _lex_expr(rest[2:])
                        body, i = _parse(parts, i + 1, stop=("else", "end"))
                        arms.append((cond, body))
                        continue
                    else_body, i = _parse(parts, i + 1, stop=("end",))
                    continue
                break  # at "end"
            nodes.append(("if", arms, else_body))
            i += 1
            continue
        if word == "range":
            decl = val[5:].strip()
            kvar = vvar = None
            if ":=" in decl:
                vars_part, expr_part = decl.split(":=", 1)
                names = [v.strip() for v in vars_part.split(",")]
                if len(names) == 1:
                    vvar = names[0]
                elif len(names) == 2:
                    kvar, vvar = names
                else:
                    raise GoTemplateError(f"bad range declaration: {decl}")
            else:
                expr_part = decl
            body, i = _parse(parts, i + 1, stop=("else", "end"))
            else_body = None
            if parts[i][1].startswith("else"):
                else_body, i = _parse(parts, i + 1, stop=("end",))
            nodes.append(("range", kvar, vvar, _lex_expr(expr_part), body,
                          else_body))
            i += 1
            continue
        if word in ("end", "else"):
            raise GoTemplateError(f"unexpected {{{{{word}}}}}")
        toks = _lex_expr(val)
        if toks and toks[0].startswith("$") and len(toks) > 1 \
                and toks[1] in (":=", "="):
            nodes.append(("assign", toks[0], toks[2:], toks[1] == ":="))
        elif toks:
            nodes.append(("out", toks))
        i += 1
    if stop:
        raise GoTemplateError(f"missing {{{{end}}}} (wanted one of {stop})")
    return nodes, i


# ------------------------------------------------------------- functions


def _truthy(v: Any) -> bool:
    """Go zero values are falsy."""
    return not (v is None or v is False or v == "" or v == 0
                or (isinstance(v, (list, tuple, dict)) and not v))


def _num(v):
    if isinstance(v, bool):
        raise GoTemplateError("number expected")
    if isinstance(v, (int, float)):
        return v
    try:
        f = float(v)
        return int(f) if f.is_integer() else f
    except (TypeError, ValueError):
        raise GoTemplateError(f"number expected, got {v!r}")


def _go_index(coll, *keys):
    for k in keys:
        if coll is None:
            return None
        if isinstance(coll, dict):
            coll = coll.get(k)
        elif isinstance(coll, (list, tuple, str)):
            i = int(_num(k))
            coll = coll[i] if 0 <= i < len(coll) else None
        else:
            coll = getattr(coll, str(k), None)
    return coll


def _printf(fmt, *args):
    # the Go verbs that appear in prompt templates
    out, ai = [], 0
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            v = fmt[i + 1]
            if v == "%":
                out.append("%")
            elif v in "svd":
                a = args[ai] if ai < len(args) else ""
                ai += 1
                out.append(str(int(_num(a))) if v == "d" else _to_str(a))
            elif v == "q":
                a = args[ai] if ai < len(args) else ""
                ai += 1
                out.append(json.dumps(_to_str(a)))
            else:
                out.append(c + v)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _to_str(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(", ", ": "))
    return str(v)


_FUNCS: dict[str, Any] = {
    "eq": lambda x, *ys: any(x == y for y in ys),
    "ne": lambda x, y: x != y,
    "lt": lambda x, y: _num(x) < _num(y),
    "le": lambda x, y: _num(x) <= _num(y),
    "gt": lambda x, y: _num(x) > _num(y),
    "ge": lambda x, y: _num(x) >= _num(y),
    "and": lambda *vs: next((v for v in vs if not _truthy(v)), vs[-1]),
    "or": lambda *vs: next((v for v in vs if _truthy(v)), vs[-1]),
    "not": lambda v: not _truthy(v),
    "index": _go_index,
    "len": lambda v: len(v) if v is not None else 0,
    "length": lambda v: len(v) if v is not None else 0,  # jinja-ism seen
    # in existing configs; harmless alias
    "print": lambda *vs: "".join(_to_str(v) for v in vs),
    "printf": _printf,
    # Go json.Marshal: compact separators, map keys sorted
    "toJson": lambda v: json.dumps(
        v, separators=(",", ":"), sort_keys=isinstance(v, dict),
        default=lambda o: getattr(o, "__dict__", str(o))),
    "add1": lambda v: _num(v) + 1,
    "add": lambda *vs: sum(_num(v) for v in vs),
    "sub": lambda a, b: _num(a) - _num(b),
    "mul": lambda a, b: _num(a) * _num(b),
    # sprig string helpers (argument order matches sprig)
    "trim": lambda s: _to_str(s).strip(),
    "upper": lambda s: _to_str(s).upper(),
    "lower": lambda s: _to_str(s).lower(),
    "title": lambda s: _to_str(s).title(),
    "quote": lambda *vs: " ".join(json.dumps(_to_str(v)) for v in vs),
    "contains": lambda sub, s: sub in _to_str(s),
    "hasPrefix": lambda p, s: _to_str(s).startswith(p),
    "hasSuffix": lambda p, s: _to_str(s).endswith(p),
    "default": lambda d, v=None: v if _truthy(v) else d,
    "empty": lambda v: not _truthy(v),
    "join": lambda sep, lst: _to_str(sep).join(
        _to_str(v) for v in (lst or [])),
    "replace": lambda old, new, s: _to_str(s).replace(old, new),
}


# ------------------------------------------------------------ evaluation


class _Scope:
    def __init__(self, dot: Any, parent: Optional["_Scope"] = None):
        self.dot = dot
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise GoTemplateError(f"undefined variable {name}")

    def set(self, name: str, value, declare: bool):
        if declare:
            self.vars[name] = value
            return
        s = self
        while s is not None:
            if name in s.vars:
                s.vars[name] = value
                return
            s = s.parent
        self.vars[name] = value  # tolerate assign-without-declare


def _field_chain(base: Any, chain: str):
    for part in chain.split("."):
        if not part:
            continue
        if base is None:
            return None
        if isinstance(base, dict):
            base = base.get(part)
        elif isinstance(base, (list, tuple)):
            return None
        else:
            base = getattr(base, part, None)
    return base


_STR_ESC = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}


def _operand(tok: str, scope: _Scope):
    if tok.startswith('"'):
        body = tok[1:-1]
        return re.sub(r"\\(.)", lambda m: _STR_ESC.get(m.group(1),
                                                       m.group(1)), body)
    if tok.startswith("`"):
        return tok[1:-1]
    if tok == ".":
        return scope.dot
    if tok.startswith("$"):
        name, _, chain = tok.partition(".")
        return _field_chain(scope.get(name), chain) if chain \
            else scope.get(name)
    if tok.startswith("."):
        return _field_chain(scope.dot, tok[1:])
    if tok in ("true", "false"):
        return tok == "true"
    if tok in ("nil", "none"):
        return None
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_.]*", tok):
        # lenient: bare identifier as a dot field (legacy configs written
        # for the old Jinja transpile use `Field` without the dot)
        return _field_chain(scope.dot, tok)
    raise GoTemplateError(f"unknown operand {tok!r}")


def _eval_command(toks: list[str], scope: _Scope, extra=None):
    """One pipeline stage: operand, or function with args. ``extra`` is
    the piped-in value appended as the last argument."""
    i = 0
    head = toks[0]
    if head == "(":
        val, i = _eval_paren(toks, scope)
        if i == len(toks) and extra is None:
            return val
        args, j = [val], i
    elif head in _FUNCS:
        args, j = [], 1
    else:
        val = _operand(head, scope)
        if len(toks) == 1 and extra is None:
            return val
        if head.startswith((".", "$")) and callable(val):
            args, j = [], 1  # method-style: not used in practice
        elif len(toks) == 1:
            return val  # piped into an operand: Go errors; be lenient
        else:
            raise GoTemplateError(f"not a function: {head!r}")
    fn = _FUNCS.get(head) if head in _FUNCS else None
    while j < len(toks):
        if toks[j] == "(":
            val, j2 = _eval_paren(toks[j:], scope)
            args.append(val)
            j += j2
        else:
            args.append(_operand(toks[j], scope))
            j += 1
    if extra is not None:
        args.append(extra)
    if fn is None:
        raise GoTemplateError(f"not a function: {head!r}")
    try:
        return fn(*args)
    except GoTemplateError:
        raise
    except Exception as e:
        raise GoTemplateError(f"error calling {head}: {e}")


def _eval_paren(toks: list[str], scope: _Scope):
    """toks[0] == '(': evaluate the parenthesized pipeline, return
    (value, tokens consumed including both parens)."""
    depth = 0
    for i, t in enumerate(toks):
        if t == "(":
            depth += 1
        elif t == ")":
            depth -= 1
            if depth == 0:
                return _eval_pipeline(toks[1:i], scope), i + 1
    raise GoTemplateError("unbalanced parentheses")


def _eval_pipeline(toks: list[str], scope: _Scope):
    if not toks:
        raise GoTemplateError("empty pipeline")
    stages: list[list[str]] = [[]]
    depth = 0
    for t in toks:
        if t == "(":
            depth += 1
        elif t == ")":
            depth -= 1
        if t == "|" and depth == 0:
            stages.append([])
        else:
            stages[-1].append(t)
    val = _eval_command(stages[0], scope)
    for stage in stages[1:]:
        val = _eval_command(stage, scope, extra=val)
    return val


def _exec(nodes, scope: _Scope, out: list[str]):
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "out":
            out.append(_to_str(_eval_pipeline(node[1], scope)))
        elif kind == "assign":
            scope.set(node[1], _eval_pipeline(node[2], scope), node[3])
        elif kind == "if":
            _, arms, else_body = node
            for cond, body in arms:
                if _truthy(_eval_pipeline(cond, scope)):
                    _exec(body, _Scope(scope.dot, scope), out)
                    break
            else:
                if else_body is not None:
                    _exec(else_body, _Scope(scope.dot, scope), out)
        elif kind == "range":
            _, kvar, vvar, expr, body, else_body = node
            coll = _eval_pipeline(expr, scope)
            if isinstance(coll, dict):
                # text/template iterates string map keys in sorted order
                items = [(k, coll[k]) for k in sorted(coll)]
            elif isinstance(coll, (list, tuple)):
                items = list(enumerate(coll))
            elif coll:
                items = [(0, coll)]
            else:
                items = []
            if not items:
                if else_body is not None:
                    _exec(else_body, _Scope(scope.dot, scope), out)
                continue
            for k, v in items:
                inner = _Scope(v, scope)
                if kvar:
                    inner.vars[kvar[1:]] = k
                    inner.vars[kvar] = k  # $k usable with or without $
                if vvar:
                    inner.vars[vvar[1:]] = v
                    inner.vars[vvar] = v
                _exec(body, inner, out)


class GoTemplate:
    """Parsed Go text/template; render with a dot context."""

    def __init__(self, src: str) -> None:
        self._nodes, _ = _parse(_split_actions(src))

    def render(self, dot: Any) -> str:
        out: list[str] = []
        scope = _Scope(dot)
        _exec(self._nodes, scope, out)
        return "".join(out)
