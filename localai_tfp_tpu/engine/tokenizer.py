"""Tokenizer abstraction for the serving engine.

Counterpart of the reference's tokenization paths: llama.cpp's tokenizer
inside the C++ engine (ref: backend/cpp/llama/grpc-server.cpp
`TokenizeString` :2603) and HF tokenizers in the Python workers
(ref: backend/python/transformers/backend.py, vllm/backend.py:242-243).

Two implementations:
- ``HFTokenizer``: wraps a HuggingFace fast tokenizer from a checkpoint dir
  (the production path; also carries the chat template for Jinja templating).
- ``ByteTokenizer``: dependency-free bytes<->ids codec used by tests and as
  the fallback when a checkpoint ships no tokenizer files.

Both expose incremental, UTF-8-safe streaming detokenization: the engine
emits byte-complete strings only (ref: the Go side's rune-reassembly of
streamed bytes, core/backend/llm.go:128-152 — here it lives next to the
tokenizer instead of the transport).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol


class Tokenizer(Protocol):
    eos_ids: set[int]
    bos_id: Optional[int]

    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...

    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """ids = raw UTF-8 bytes; 256=BOS, 257=EOS. Vocab 258 (tests/fallback)."""

    def __init__(self) -> None:
        self.bos_id: Optional[int] = 256
        self.eos_ids = {257}

    @property
    def vocab_size(self) -> int:
        return 258

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def encode_special(self, text: str) -> list[int]:
        """Counterpart of encoder-model encoding WITH special tokens."""
        return [self.bos_id] + self.encode(text) + [next(iter(self.eos_ids))]

    def encode_pair(self, a: str, b: str) -> tuple[list[int], list[int]]:
        """Returns (ids, segment/type ids) — segment 1 covers b + final sep."""
        sep = next(iter(self.eos_ids))
        ea, eb = self.encode(a), self.encode(b)
        ids = [self.bos_id] + ea + [sep] + eb + [sep]
        types = [0] * (len(ea) + 2) + [1] * (len(eb) + 1)
        return ids, types


class HFTokenizer:
    """HuggingFace fast tokenizer from a local checkpoint directory."""

    def __init__(self, model_dir: str) -> None:
        from transformers import AutoTokenizer

        self._tk = AutoTokenizer.from_pretrained(model_dir)
        self.bos_id = self._tk.bos_token_id
        eos = self._tk.eos_token_id
        self.eos_ids = set()
        if eos is not None:
            self.eos_ids = set(eos) if isinstance(eos, (list, tuple)) else {eos}
        # generation_config may widen eos (llama3: <|eot_id|>)
        import json

        gc = os.path.join(model_dir, "generation_config.json")
        if os.path.exists(gc):
            try:
                with open(gc) as f:
                    g = json.load(f)
                ge = g.get("eos_token_id")
                if isinstance(ge, int):
                    self.eos_ids.add(ge)
                elif isinstance(ge, list):
                    self.eos_ids.update(ge)
            except (ValueError, OSError):
                pass

    @property
    def vocab_size(self) -> int:
        return len(self._tk)

    @property
    def chat_template(self) -> Optional[str]:
        return getattr(self._tk, "chat_template", None)

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tk.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: list[int]) -> str:
        return self._tk.decode(ids, skip_special_tokens=False)

    def encode_special(self, text: str) -> list[int]:
        """Encode WITH special tokens ([CLS] ... [SEP] for BERT-family) —
        required by encoder models whose pooling/classification expects
        them (sentence-transformers / cross-encoder semantics)."""
        return self._tk.encode(text, add_special_tokens=True)

    def encode_pair(self, a: str, b: str) -> tuple[list[int], list[int]]:
        """[CLS] a [SEP] b [SEP] with segment ids — the cross-encoder input
        convention (segment 1 on the b half, as BERT was trained)."""
        out = self._tk(a, b, add_special_tokens=True)
        ids = out["input_ids"]
        types = out.get("token_type_ids") or [0] * len(ids)
        return ids, types

    def apply_chat_template(self, messages: list[dict], *,
                            add_generation_prompt: bool = True,
                            tools: Optional[list] = None) -> str:
        return self._tk.apply_chat_template(
            messages, tokenize=False,
            add_generation_prompt=add_generation_prompt, tools=tools,
        )


class StreamDecoder:
    """Incremental detokenizer emitting only UTF-8-complete text.

    Held per active request. ``push(token_id)`` returns the newly completed
    text (possibly ""). Handles tokenizers whose decode is not prefix-stable
    (sentencepiece space handling) by re-decoding a trailing token window.
    """

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._tk = tokenizer
        self._ids: list[int] = []
        self._emitted = ""

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._tk.decode(self._ids)
        if text.endswith("�"):  # mid-UTF-8-sequence; wait for more bytes
            return ""
        if not text.startswith(self._emitted):
            # non-prefix-stable decode: re-emit from scratch is wrong for a
            # stream; emit the common suffix after the longest common prefix
            common = os.path.commonprefix([text, self._emitted])
            out = text[len(common):]
        else:
            out = text[len(self._emitted):]
        self._emitted = text
        return out

    @property
    def text(self) -> str:
        return self._emitted


def load_tokenizer(model_dir: str) -> Tokenizer:
    for fname in ("tokenizer.json", "tokenizer_config.json", "vocab.json"):
        if os.path.exists(os.path.join(model_dir, fname)):
            return HFTokenizer(model_dir)
    return ByteTokenizer()
