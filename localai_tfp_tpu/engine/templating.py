"""Prompt templating: chat assembly + completion/edit templates.

Capability counterpart of the reference's template evaluator
(ref: pkg/templates/evaluator.go:26-36 ChatMessageTemplateData,
:56-92 template selection, :128+ TemplateMessages; cache.go template
caching; gonja Jinja support evaluator.go:87-89).

TPU-native design choice: Jinja2 is the single template engine (the HF
ecosystem's chat-template dialect), replacing the reference's dual
Go-text/template + gonja stack. For migration, simple Go-template
pipelines (`{{.Field}}`, `{{if .Field}}...{{end}}`) are transpiled to
Jinja on the fly so LocalAI model YAMLs keep working.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

import jinja2

from ..config.model_config import ModelConfig
from .gotmpl import GoTemplate, GoTemplateError, looks_like_go_template

_GO_PIPE = re.compile(r"\{\{\s*(if|else if)?\s*\.([A-Za-z_][A-Za-z0-9_.]*)\s*\}\}")
_GO_ELSE = re.compile(r"\{\{\s*else\s*\}\}")
_GO_END = re.compile(r"\{\{\s*end\s*\}\}")


def go_template_to_jinja(src: str) -> str:
    """Best-effort transpile of simple Go text/templates to Jinja2.

    Covers the forms that appear in LocalAI model galleries:
    ``{{.Input}}``, ``{{ .SystemPrompt }}``, ``{{if .Content}}…{{else}}…
    {{end}}``. Anything richer should be written as Jinja directly.
    """
    def sub(m: re.Match) -> str:
        kw, path = m.group(1), m.group(2)
        expr = path.replace(".", "_")
        if kw is None:
            return "{{ %s }}" % expr
        if kw == "if":
            return "{%% if %s %%}" % expr
        return "{%% elif %s %%}" % expr

    out = _GO_PIPE.sub(sub, src)
    out = _GO_ELSE.sub("{% else %}", out)
    out = _GO_END.sub("{% endif %}", out)
    return out


@dataclass
class ChatMessageData:
    """Per-message template variables (ref: evaluator.go:26-36)."""

    SystemPrompt: str = ""
    Role: str = ""
    RoleName: str = ""
    Content: str = ""
    FunctionCall: Any = None
    FunctionName: str = ""
    LastMessage: bool = False
    Function: bool = False
    MessageIndex: int = 0


@dataclass
class PromptTemplateData:
    """Top-level template variables (ref: evaluator.go chat/completion)."""

    SystemPrompt: str = ""
    Input: str = ""
    Instruction: str = ""
    Functions: list[dict] = field(default_factory=list)
    MessageIndex: int = 0


class Evaluator:
    """Selects and renders the right template per endpoint
    (ref: pkg/templates/evaluator.go Evaluator)."""

    def __init__(self, models_path: str = "") -> None:
        self.models_path = models_path
        self._env = jinja2.Environment(
            loader=jinja2.BaseLoader(), keep_trailing_newline=True,
            trim_blocks=False, lstrip_blocks=False,
        )
        self._env.globals["raise_exception"] = _raise_exception
        self._cache: dict[str, jinja2.Template] = {}

    # -- template resolution (ref: evaluator.go:56-92: explicit template
    #    name, else <model>.tmpl file, else none) --

    def _load_source(self, name_or_text: str) -> str:
        """A template field is inline text if it contains '{{' or '{%';
        otherwise it names a .tmpl/.jinja file under models_path."""
        if "{{" in name_or_text or "{%" in name_or_text:
            return name_or_text
        for ext in ("", ".tmpl", ".jinja", ".jinja2"):
            p = os.path.join(self.models_path, name_or_text + ext)
            if self.models_path and os.path.isfile(p):
                with open(p) as f:
                    return f.read()
        return name_or_text  # literal text without placeholders

    def _compile(self, source: str):
        """Jinja2 for Jinja sources; the Go text/template interpreter
        (engine/gotmpl.py) for Go-dialect sources — gallery YAMLs use
        eq/range/index/toJson/$vars/trim markers, well beyond what a
        textual transpile covers (VERDICT r3 weak #5)."""
        tpl = self._cache.get(source)
        if tpl is None:
            if looks_like_go_template(source):
                try:
                    tpl = GoTemplate(source)
                except GoTemplateError:
                    # unsupported construct: legacy transpile fallback
                    tpl = self._env.from_string(
                        go_template_to_jinja(source))
            else:
                tpl = self._env.from_string(source)
            self._cache[source] = tpl
        return tpl

    def _render(self, source: str, data: Any) -> str:
        tpl = self._compile(self._load_source(source))
        if isinstance(tpl, GoTemplate):
            return tpl.render(data)
        ctx = dict(data.__dict__)
        # expose both Go-style (Field) and snake_case names, plus the
        # transpiler's dotted-path flattening (Function_Name)
        for k, v in list(ctx.items()):
            ctx[_snake(k)] = v
        return tpl.render(**ctx)

    # -- public API --

    def evaluate_completion(self, cfg: ModelConfig, prompt: str) -> str:
        if not cfg.template.completion:
            return prompt
        return self._render(
            cfg.template.completion,
            PromptTemplateData(Input=prompt, SystemPrompt=cfg.system_prompt),
        )

    def evaluate_edit(self, cfg: ModelConfig, input_: str,
                      instruction: str) -> str:
        if not cfg.template.edit:
            return f"{instruction}\n\n{input_}"
        data = PromptTemplateData(
            Input=input_, Instruction=instruction,
            SystemPrompt=cfg.system_prompt,
        )
        return self._render(cfg.template.edit, data)

    def template_messages(
        self,
        cfg: ModelConfig,
        messages: list[dict],
        tokenizer: Any = None,
        functions: Optional[list[dict]] = None,
        use_function_template: bool = False,
        media: Optional[list] = None,  # out-param: image parts collected
        # here get [img-N] markers in the flattened text
    ) -> str:
        """Assemble the full chat prompt (ref: evaluator.go TemplateMessages
        :128+). Precedence: tokenizer chat template (if requested or no
        explicit template), else per-message template + chat template."""
        # ALWAYS flatten part-list contents to strings (tokenizer chat
        # templates choke on raw lists); media controls only whether image
        # parts become [img-N] markers (collected) or are dropped
        messages = [
            {**m, "content": _content_to_text(m.get("content"), media)}
            if not isinstance(m.get("content"), str) else m
            for m in messages
        ]
        use_tok = cfg.template.use_tokenizer_template or not (
            cfg.template.chat or cfg.template.chat_message
        )
        if use_tok and tokenizer is not None and getattr(
            tokenizer, "chat_template", None
        ):
            msgs = list(messages)
            if cfg.system_prompt and not any(
                m.get("role") == "system" for m in msgs
            ):
                msgs = [{"role": "system", "content": cfg.system_prompt}] + msgs
            return tokenizer.apply_chat_template(
                msgs, add_generation_prompt=True, tools=functions or None
            )

        rendered: list[str] = []
        n = len(messages)
        for i, msg in enumerate(messages):
            role = msg.get("role", "user")
            content = _content_to_text(msg.get("content"))
            fcall = msg.get("tool_calls") or msg.get("function_call")
            data = ChatMessageData(
                SystemPrompt=cfg.system_prompt,
                Role=cfg.roles.get(role, role),
                RoleName=role,
                Content=content,
                FunctionCall=fcall,
                FunctionName=msg.get("name", ""),
                LastMessage=i == n - 1,
                Function=bool(fcall) or role in ("tool", "function"),
                MessageIndex=i,
            )
            if cfg.template.chat_message:
                rendered.append(self._render(cfg.template.chat_message, data))
            else:
                prefix = data.Role
                rendered.append(f"{prefix}: {content}" if prefix else content)

        joiner = cfg.template.join_chat_messages_by_character
        if joiner is None:
            joiner = "\n"
        combined = joiner.join(r for r in rendered if r)

        chat_tpl = (
            cfg.template.function
            if use_function_template and cfg.template.function
            else cfg.template.chat
        )
        if chat_tpl:
            return self._render(
                chat_tpl,
                PromptTemplateData(
                    Input=combined,
                    SystemPrompt=cfg.system_prompt,
                    Functions=functions or [],
                ),
            )
        return combined


def _content_to_text(content: Any, media: Optional[list] = None) -> str:
    """OpenAI message content may be a string or multimodal part list
    (ref: core/schema/openai.go content parts; middleware/request.go
    :302-329 media handling). When ``media`` is given, image parts are
    collected into it and replaced by ``[img-N]`` markers in the text —
    the reference's multimodal tag convention (pkg/templates/
    multimodal.go) that the LLM worker later expands into soft tokens."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = []
        for part in content:
            if not isinstance(part, dict):
                continue
            ptype = part.get("type")
            if ptype == "text":
                parts.append(part.get("text", ""))
            elif ptype in ("image_url", "image") and media is not None:
                media.append(part)
                parts.append(f"[img-{len(media) - 1}]")
        return "".join(parts)
    return str(content)


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


def _raise_exception(msg: str):
    raise jinja2.TemplateError(msg)
