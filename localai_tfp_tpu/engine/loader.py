"""Model lifecycle: registry of live backends, load-or-reuse, watchdog.

Capability counterpart of the reference's ModelLoader + WatchDog
(ref: pkg/model/loader.go:20-37,119-188 load-or-reuse with health check;
initializers.go:24-42 backend aliasing, :498-559 ordered auto-try;
watchdog.go:19-156 busy/idle kill; loader.go:469-496 single-active-backend).

TPU-native re-design: backends are in-process objects, not subprocesses —
one Python process owns the TPU runtime, so "respawn" means rebuilding the
backend object (and letting XLA's compilation cache make that cheap). The
busy/idle watchdog semantics are preserved because they guard the same
resource: a wedged or forgotten model holding HBM.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..config import knobs
from ..config.model_config import ModelConfig
from ..telemetry import metrics as tm
from ..utils import faultinject
from ..workers.base import Backend, ModelLoadOptions, Result

log = logging.getLogger(__name__)

# load_timing.py phase keys -> prometheus phase label (other_s is the
# reconciling remainder the breakdown always carries)
_LOAD_PHASES = ("read_s", "dequant_s", "transfer_s", "compile_s",
                "warmup_s", "other_s")

BackendFactory = Callable[[], Backend]

# backend-name aliasing (ref: initializers.go:24-42). Every alias of the
# reference's llama.cpp/vLLM/transformers LLM backends maps to the JAX LLM
# worker; media backends map to their JAX counterparts.
ALIASES = {
    "": "jax-llm",
    "llama": "jax-llm",
    "llama-cpp": "jax-llm",
    "llama-grpc": "jax-llm",
    "vllm": "jax-llm",
    "transformers": "jax-llm",
    "exllama2": "jax-llm",
    "langchain-huggingface": "jax-llm",
    "sentencetransformers": "jax-embeddings",
    "huggingface-embeddings": "jax-embeddings",
    "embeddings": "jax-embeddings",
    "rerankers": "jax-rerank",
    "rerank": "jax-rerank",
    "whisper": "jax-whisper",
    "faster-whisper": "jax-whisper",
    "diffusers": "jax-diffusion",
    "stablediffusion": "jax-diffusion",
    "flux": "jax-diffusion",
    "piper": "jax-tts",
    "coqui": "jax-tts",
    "kokoro": "jax-tts",
    "bark": "jax-tts",
    "bark-cpp": "jax-tts",
    "tts": "jax-tts",
    "silero-vad": "jax-vad",
    "vad": "jax-vad",
    "local-store": "local-store",
    "stores": "local-store",
}


def resolve_backend(name: str) -> str:
    n = (name or "").strip().lower()
    return ALIASES.get(n, n)


class _Registry:
    """Factory registry for backend types (the TPU analogue of the asset-dir
    binary scan, ref: initializers.go:86-179)."""

    def __init__(self) -> None:
        self._factories: dict[str, BackendFactory] = {}

    def register(self, name: str, factory: BackendFactory) -> None:
        self._factories[name] = factory

    def unregister(self, name: str) -> bool:
        return self._factories.pop(name, None) is not None

    def create(self, name: str) -> Backend:
        f = self._factories.get(name)
        if f is None:
            raise KeyError(
                f"no backend '{name}' registered "
                f"(known: {sorted(self._factories)})"
            )
        return f()

    def known(self) -> list[str]:
        return sorted(self._factories)


registry = _Registry()


def register_default_backends() -> None:
    """Register the built-in worker factories (lazy imports so optional
    deps never block startup)."""
    from ..config import knobs

    if knobs.flag("LOCALAI_NATIVE"):
        # compile the native hot-path libraries once at startup so the
        # first grammar/store request never blocks on g++
        from ..native import build

        build()
    from ..workers.llm import JaxLLMBackend

    registry.register("jax-llm", JaxLLMBackend)
    from ..store.backend import LocalStoreBackend
    from ..workers.embeddings import JaxEmbeddingsBackend
    from ..workers.rerank import JaxRerankBackend
    from ..workers.tts import JaxTTSBackend
    from ..workers.vad import JaxVADBackend

    registry.register("local-store", LocalStoreBackend)
    registry.register("jax-embeddings", JaxEmbeddingsBackend)
    registry.register("jax-rerank", JaxRerankBackend)
    registry.register("jax-tts", JaxTTSBackend)
    registry.register("jax-vad", JaxVADBackend)
    from ..workers.subprocess_worker import SubprocessBackend

    registry.register("subprocess", SubprocessBackend)
    # jax-whisper / jax-diffusion register as they land
    try:
        from ..workers.whisper import JaxWhisperBackend

        registry.register("jax-whisper", JaxWhisperBackend)
    except ImportError:
        pass
    try:
        from ..workers.diffusion import JaxDiffusionBackend

        registry.register("jax-diffusion", JaxDiffusionBackend)
    except ImportError:
        pass


class LoadedModel:
    def __init__(self, name: str, backend_type: str, backend: Backend):
        self.name = name
        self.backend_type = backend_type
        self.backend = backend
        self.last_used = time.monotonic()
        self.busy_since: Optional[float] = None
        self.load_s: float = 0.0  # wall time of the backend load that
        # produced this instance (phase breakdown lives on the backend
        # as ``load_breakdown``; /backend/monitor surfaces both)

    def mark_busy(self) -> None:
        self.busy_since = time.monotonic()
        self.last_used = self.busy_since

    def mark_idle(self) -> None:
        self.busy_since = None
        self.last_used = time.monotonic()


class _InFlightLoad:
    """One coalesced load of one model name: the first caller becomes
    the leader and performs the load; concurrent callers for the same
    name park on ``done`` and share the leader's outcome."""

    __slots__ = ("done", "backend", "error")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.backend: Optional[Backend] = None
        self.error: Optional[BaseException] = None


class ModelLoader:
    """Keyed registry of live backends with load-or-reuse semantics
    (ref: pkg/model/loader.go ModelLoader).

    Concurrency contract: ``_lock`` guards ONLY the registry maps and is
    never held across a backend load. A load of model B (checkpoint IO +
    compiles + warmup — minutes at 8B scale) therefore never blocks
    ``get_loaded(A)``/``load(A)`` of an already-loaded model A, and
    duplicate concurrent ``load(B)`` calls coalesce onto one in-flight
    load (``_InFlightLoad``) instead of building two backends."""

    def __init__(
        self,
        models_path: str = "models",
        *,
        single_active_backend: bool = False,
    ) -> None:
        self.models_path = models_path
        self.single_active = single_active_backend
        self._lock = threading.Lock()  # registry map mutations only
        self._models: dict[str, LoadedModel] = {}  # lint: guarded-by self._lock
        self._loads: dict[str, _InFlightLoad] = {}  # lint: guarded-by self._lock
        # single-active mode needs whole-load serialization: two
        # concurrent leaders would each evict the other, then both
        # publish — two live backends in a mode whose point is one
        self._single_gate = threading.Lock()

    # ------------------------------------------------------------- loading

    def get_loaded(self, name: str) -> Optional[Backend]:
        """Fast path: the already-loaded healthy backend, or None.
        Routes call this on the EVENT LOOP to skip the thread-pool hop
        for the common already-loaded case. ``_lock`` only ever guards
        map mutations (loads run OUTSIDE it), so this acquire is
        microseconds even while another model is mid-load."""
        with self._lock:
            lm = self._models.get(name)
            if lm is not None and lm.backend.health():
                lm.last_used = time.monotonic()
                return lm.backend
        return None

    def load(self, cfg: ModelConfig) -> Backend:
        """Load-or-reuse (ref: loader.go:119-188 CheckIsLoaded: health-check
        a cached backend and rebuild it if dead). Concurrent loads of
        the SAME name coalesce onto one backend build; loads of
        DIFFERENT names proceed in parallel (per-model load locks)."""
        while True:
            with self._lock:
                lm = self._models.get(cfg.name)
                if lm is not None and lm.backend.health():
                    lm.last_used = time.monotonic()
                    return lm.backend
                fl = self._loads.get(cfg.name)
                if fl is None:
                    fl = _InFlightLoad()
                    self._loads[cfg.name] = fl
                    break  # we are the leader
            # another caller is already loading this name: share its
            # outcome instead of building a duplicate backend
            fl.done.wait()
            if fl.error is not None:
                raise RuntimeError(
                    f"loading model '{cfg.name}': coalesced onto a "
                    f"concurrent load that failed: {fl.error}"
                ) from fl.error
            if fl.backend is not None:
                return fl.backend
            # leader vanished without outcome (shouldn't happen);
            # re-enter and try to lead
        try:
            backend = self._load_as_leader(cfg)
            fl.backend = backend
            return backend
        except BaseException as e:
            fl.error = e
            tm.MODEL_LOADS.labels(model=cfg.name, result="error").inc()
            raise
        finally:
            with self._lock:
                if self._loads.get(cfg.name) is fl:
                    del self._loads[cfg.name]
            fl.done.set()

    def _load_as_leader(self, cfg: ModelConfig) -> Backend:
        """The actual load, run WITHOUT the registry lock held (only
        brief map mutations take it)."""
        if faultinject.ACTIVE:
            # chaos surface: an injected load failure takes the same
            # path as a backend that failed to build — the in-flight
            # load record propagates it to every coalesced waiter
            faultinject.fire("loader.load")
        if self.single_active:
            self._single_gate.acquire()
        try:
            stale = None
            with self._lock:
                lm = self._models.get(cfg.name)
                if lm is not None:
                    # the pre-leader check saw this entry unhealthy
                    stale = self._models.pop(cfg.name)
            if stale is not None:
                log.warning("backend for %s unhealthy; rebuilding",
                            cfg.name)
                self._shutdown_backend(stale)
            if self.single_active:
                with self._lock:
                    victims = [self._models.pop(n)
                               for n in list(self._models)
                               if n != cfg.name]
                for v in victims:
                    tm.MODEL_EVICTIONS.labels(reason="single_active").inc()
                    self._shutdown_backend(v)
                if victims:
                    self._update_gauges()

            if cfg.isolation == "subprocess":
                # child-process containment (workers/subprocess_worker):
                # the child gets the same yaml minus `isolation`
                btype = "subprocess"
            else:
                btype = resolve_backend(cfg.backend)
            backend = registry.create(btype)
            t0 = time.monotonic()
            res = backend.load_model(self._load_options(cfg))
            if not res.success:
                backend.shutdown()
                raise RuntimeError(
                    f"loading model '{cfg.name}': {res.message}"
                )
            lm = LoadedModel(cfg.name, btype, backend)
            lm.load_s = time.monotonic() - t0
            with self._lock:
                self._models[cfg.name] = lm
            tm.MODEL_LOADS.labels(model=cfg.name, result="success").inc()
            # fold the cold-start phase breakdown (models/load_timing.py,
            # already on the backend) into cumulative per-phase counters
            bd = getattr(backend, "load_breakdown", None) or {}
            for phase in _LOAD_PHASES:
                v = bd.get(phase)
                if v:
                    tm.MODEL_LOAD_PHASE.labels(
                        phase=phase[:-2]).inc(float(v))
            self._update_gauges()
            return backend
        finally:
            if self.single_active:
                self._single_gate.release()

    @staticmethod
    def _shutdown_backend(lm: LoadedModel) -> None:
        try:
            lm.backend.shutdown()
        except Exception as e:
            log.warning("shutdown of %s raised: %s", lm.name, e)

    def _load_options(self, cfg: ModelConfig) -> ModelLoadOptions:
        return ModelLoadOptions(
            model=cfg.model,
            model_path=self.models_path,
            context_size=cfg.context_size or 4096,
            batch_slots=cfg.max_batch_slots,
            dtype=cfg.dtype or cfg.activation_dtype,
            kv_cache_dtype=cfg.kv_cache_dtype,
            quantization=cfg.quantization,
            mesh=cfg.mesh,
            threads=cfg.threads or 0,
            embeddings=cfg.embeddings,
            draft_model=cfg.draft_model,
            n_draft=cfg.n_draft or 0,
            lora_adapters=(
                list(cfg.lora_adapters)
                or ([cfg.lora_adapter] if cfg.lora_adapter else [])
            ),
            lora_scales=list(cfg.lora_scales) or (
                [cfg.lora_scale] if cfg.lora_scale else []
            ),
            options=cfg.options,
            extra=self._extra_for(cfg),
        )

    def _extra_for(self, cfg: ModelConfig) -> dict:
        extra = cfg.extra
        if cfg.diffusers.control_net:
            # forward the canonical diffusers.control_net key so the
            # worker can fail loudly (it is not silently ignorable)
            extra = {**extra, "control_net": cfg.diffusers.control_net}
        if cfg.isolation == "subprocess":
            extra = {**extra, "_cfg_raw": cfg.raw,
                     "_models_path": self.models_path}
        return extra

    # ------------------------------------------------------------ lifecycle

    def get(self, name: str) -> Optional[LoadedModel]:
        with self._lock:
            return self._models.get(name)

    def loaded_names(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def shutdown_model(self, name: str, reason: str = "api") -> bool:
        """Unload one model. The registry entry is removed under the map
        lock; the (potentially slow — engine thread join) backend
        shutdown runs outside it so other models keep serving. A
        shutdown racing a concurrent load of the same name can land
        before the load publishes; the load then wins — callers that
        need the model gone for good should stop issuing loads first.
        ``reason`` labels the eviction metric (api/watchdog_busy/
        watchdog_idle/shutdown/...)."""
        with self._lock:
            lm = self._models.pop(name, None)
        if lm is None:
            return False
        tm.MODEL_EVICTIONS.labels(reason=reason).inc()
        self._update_gauges()
        self._shutdown_backend(lm)
        return True

    def stop_all(self) -> None:
        with self._lock:
            victims = [self._models.pop(n) for n in list(self._models)]
        for lm in victims:
            tm.MODEL_EVICTIONS.labels(reason="shutdown").inc()
            self._shutdown_backend(lm)
        self._update_gauges()

    def _update_gauges(self) -> None:
        with self._lock:
            n = len(self._models)
            busy = sum(1 for lm in self._models.values()
                       if lm.busy_since is not None)
        tm.MODELS_LOADED.set(n)
        tm.MODELS_BUSY.set(busy)

    # ------------------------------------------------- busy/idle accounting

    def mark_busy(self, name: str) -> None:
        lm = self.get(name)
        if lm:
            lm.mark_busy()
            self._update_gauges()

    def mark_idle(self, name: str) -> None:
        lm = self.get(name)
        if lm:
            lm.mark_idle()
            self._update_gauges()


class WatchDog:
    """Kills models busy or idle beyond thresholds on periodic ticks
    (ref: pkg/model/watchdog.go:19-156; 30s ticks, flags run.go:65-68)."""

    def __init__(
        self,
        loader: ModelLoader,
        *,
        busy_timeout: float = 5 * 60,
        idle_timeout: float = 15 * 60,
        enable_busy: bool = False,
        enable_idle: bool = False,
        interval: float = 30.0,
    ) -> None:
        self.loader = loader
        self.busy_timeout = busy_timeout
        self.idle_timeout = idle_timeout
        self.enable_busy = enable_busy
        self.enable_idle = enable_idle
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None and (self.enable_busy or self.enable_idle):
            self._thread = threading.Thread(
                target=self._run, name="watchdog", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.check(time.monotonic())

    def check(self, now: float) -> list[str]:
        """One tick; returns names killed (separated out for tests)."""
        killed = []
        for name in self.loader.loaded_names():
            lm = self.loader.get(name)
            if lm is None:
                continue
            if (
                self.enable_busy
                and lm.busy_since is not None
                and now - lm.busy_since > self.busy_timeout
            ):
                log.warning("watchdog: %s busy > %.0fs, killing",
                            name, self.busy_timeout)
                tm.WATCHDOG_KILLS.labels(kind="busy").inc()
                self.loader.shutdown_model(name, reason="watchdog_busy")
                killed.append(name)
            elif (
                self.enable_idle
                and lm.busy_since is None
                and now - lm.last_used > self.idle_timeout
            ):
                if knobs.flag("LOCALAI_WATCHDOG_DEMOTE"):
                    outcome = self._try_demote(lm)
                    if outcome == "demoted":
                        # demote-to-warm instead of kill: weights page
                        # to host RAM, the engine/tokenizer/KV state
                        # survive, and the idle clock restarts — a model
                        # idle through ANOTHER full timeout (now warm)
                        # falls through to today's shutdown
                        log.warning(
                            "watchdog: %s idle > %.0fs, demoting "
                            "weights to host RAM", name,
                            self.idle_timeout)
                        tm.MODEL_EVICTIONS.labels(
                            reason="watchdog_demote").inc()
                        lm.last_used = now
                        continue
                    if outcome == "busy":
                        continue  # transfer aloft: decide next tick
                log.warning("watchdog: %s idle > %.0fs, killing",
                            name, self.idle_timeout)
                tm.WATCHDOG_KILLS.labels(kind="idle").inc()
                self.loader.shutdown_model(name, reason="watchdog_idle")
                killed.append(name)
        return killed

    @staticmethod
    def _try_demote(lm: LoadedModel) -> Optional[str]:
        """Ask the backend to page its weights out. Returns "demoted"
        (a demotion just started), "busy" (one is already in flight),
        "warm" (nothing hot to demote — the kill timer keeps running),
        or None (backend has no pager: use the kill path)."""
        fn = getattr(lm.backend, "demote_weights", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:
            log.warning("watchdog: demote of %s raised %r; falling "
                        "back to kill", lm.name, e)
            return None
