"""Disaggregated prefill/decode serving: zero-copy KV page migration.

Long prompts and steady decode streams want OPPOSITE engine tunings: a
prefill flood fills the dispatch window with large compute-bound
chunks, and every token a decode stream emits while one is in flight
waits behind it — the inter-token gap balloons exactly when the server
is busiest. The fix here is the single-host form of disaggregated
serving (ref: DistServe/Splitwise; LocalAI runs one backend per model
and has no equivalent): TWO ``LLMEngine`` instances in one process
share one set of weights — a prefill engine tuned for big prompt
dispatches and a decode engine tuned for k-scan decode — joined by the
page-migration protocol in this module.

The relay, per disaggregated request:

1. ``DisaggRouter.submit_many`` routes the request (prompt length >=
   LOCALAI_DISAGG_MIN_PROMPT, priced against the cost model's
   prefill_token_ms when LOCALAI_DISAGG_MIN_MS is set). Local requests
   go straight to the decode engine — LOCALAI_DISAGG=off is
   byte-identical because the router is never constructed.
2. A prefill PROBE (same request, ``max_tokens=1``, id + ":prefill",
   same trace_id) runs on the prefill engine. Its prefill_final
   dispatch samples the first token with the request's own seeded
   sampler columns — identical semantics to the single-engine path —
   and with max_tokens=1 the slot finishes before any decode dispatch,
   so its pages cover EXACTLY the prompt.
3. At the probe's ``_finish`` the prefill-side ``Migrator`` gathers the
   slot's pages (async device->host copy enqueued in device order —
   later page reuse cannot outrun it) plus the slot's post-sample
   sampler ROW (rng, penalty counts, history window), and publishes the
   capture on the ``MigrationBus``.
4. The router's pump thread collects the capture into a content-
   addressed host-RAM interchange (pages dedup'd by token-prefix sha1,
   refcounted — two requests sharing a prompt prefix migrate one copy)
   and resubmits the ORIGINAL request to the decode engine with the
   ``KVHandoff`` attached and its original t_submit/deadline intact.
5. The decode engine's ``_admit`` calls ``Migrator.assign_migrated``:
   pages stage into a reserved pseudo-slot table (scatter in device
   order — never blocking the device step), the slot adopts them by
   reference (``PagePool.share``), the sampler row lands via a donated
   scatter, and the slot wakes in DECODE with the whole prompt resident
   and the probe's first token re-emitted. A migrated request
   re-prefills ZERO prompt tokens and streams from the decode engine
   from its first decode step.

Failure is graceful by construction: any capture/stage fault
(``disagg.migrate`` / ``disagg.handoff`` injection points, pool
pressure, validation) drops the handoff and the request re-prefills on
the decode engine — correct, just slower. Deadlines are enforced per
stage (queued/prefill/migrate/decode) and an overrun terminates with
``deadline_exceeded`` attributed to the stage that overran. Both
engines' pools stay ``leak_check``-clean: host blocks are refcounted on
the bus, pool pages only move by ensure/share/drop.

Transport: the interchange is deliberately a narrow interface —
``publish`` (device gather handles) / ``collect`` (host blocks) /
``blocks`` (stage reads) — so a multihost build can swap the host-RAM
hop for an ICI/DCN transfer without touching either engine's side of
the protocol. Today's single transport is process-local host RAM.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import knobs
from ..telemetry import metrics as tm
from ..telemetry.flightrec import FLIGHT, MIGRATE_TRACK
from ..telemetry.tracing import TRACER
from ..utils import faultinject
from .engine import GenRequest, SlotState, StreamEvent
from .kv_pool import TRASH_PAGE, PagePoolExhausted
from .kv_tier import _gather_pages, _pow2, _scatter_pages
from .tokenizer import StreamDecoder

log = logging.getLogger(__name__)


def _page_key(tokens, end: int) -> bytes:
    # content address of a page-aligned token prefix — same scheme as
    # the KV tier's dedup keys, kept separate so the interchange never
    # binds to a tier manager instance (the prefill engine runs none)
    return hashlib.sha1(
        np.asarray(tokens[:end], np.int64).tobytes()).digest()

# probe-request id suffix: the prefill engine serves "<rid>:prefill",
# the decode engine serves "<rid>" — distinct ids (each engine's
# tracked request lifecycle stays 1:1) on ONE shared trace_id
PREFILL_SUFFIX = ":prefill"

# decode-side staging pseudo-slot ids: kv_tier reserves
# n_slots+0..N_STAGE-1, migration staging starts above them so the two
# subsystems can never collide on a pool table id
_STAGE_BASE = 4
_N_STAGE = 2


@jax.jit
def _gather_row(state, idx):
    # one sampler row [fields...] off the [S, ...] state; every
    # SamplingState field is a registered pytree child so tree_map
    # covers rng/penalty counts/history in one expression
    return jax.tree_util.tree_map(lambda a: a[idx], state)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_row(state, idx, row):
    return jax.tree_util.tree_map(
        lambda a, r: a.at[idx].set(r.astype(a.dtype)), state, row)


@dataclass
class _HostBlock:
    """One migrated KV page in the host-RAM interchange: native-dtype
    planes, refcounted (content-addressed pages shared by several
    in-flight migrations hold one copy)."""

    arrays: dict  # k/v [L, P, F]; k_scale/v_scale [L, P] when int8
    nbytes: int
    ref: int = 1
    key: Optional[bytes] = None


@dataclass
class _Capture:
    """A finished prefill slot's state, published by the prefill-side
    Migrator with device->host copies already in flight."""

    rid: str  # BASE request id (probe suffix stripped)
    tokens: list
    n: int
    first_token: int
    handles: tuple  # gathered page planes, copy_to_host_async'd
    names: tuple  # plane names aligned with handles
    row: Any  # sampler row pytree (device), post-first-sample
    npg: int
    prefill_ms: float
    enq_ms: float
    queued_ms: float
    t0: float  # gather enqueue time (migrate_out span start)


@dataclass
class KVHandoff:
    """The decode side's view of a migrated prompt: host block ids (refs
    held until release), the probe's first sampled token, the sampler
    row, and the timing the original request accrued before resubmit."""

    rid: str
    tokens: list
    n: int
    first_token: int
    hpids: list
    sampler_row: Any  # numpy pytree, scattered into the decode sampler
    nbytes: int
    npg: int
    prefill_ms: float
    enq_ms: float
    queued_ms: float
    migrate_ms: float = 0.0
    t_resubmit: float = 0.0
    _bus: Any = field(default=None, repr=False)
    _released: bool = False

    def release(self) -> None:
        """Drop this handoff's block refs (idempotent). The engine calls
        this on queued-death paths (shed/cancel/deadline while pending)
        so an adopted-never request cannot strand interchange RAM."""
        if self._released or self._bus is None:
            return
        self._released = True
        self._bus._deref(self.hpids, self.npg)


class MigrationBus:
    """The prefill->decode interchange: in-flight captures on one side,
    refcounted content-addressed host pages on the other.

    Unlike the KV tier's warm store this holds ONLY in-flight
    migrations — a handoff's blocks free at release (adoption or
    failure), and warm retention across requests stays the tier's job.
    All methods are thread-safe; ``collect`` runs the blocking
    host-copy finalize on the ROUTER's pump thread, never on either
    engine's scheduler thread."""

    def __init__(self, page: int) -> None:
        self.P = page
        self._cv = threading.Condition()
        self._want: set = set()  # lint: guarded-by self._cv
        self._caps: dict = {}  # lint: guarded-by self._cv
        self._failed: dict = {}  # lint: guarded-by self._cv
        self._blocks: dict = {}  # lint: guarded-by self._cv
        self._dedup: dict = {}  # lint: guarded-by self._cv
        self._next_id = 1  # lint: guarded-by self._cv
        self._bytes = 0  # lint: guarded-by self._cv
        self._closed = False  # lint: guarded-by self._cv
        self.counters = {
            "published": 0, "collected": 0, "failed": 0, "timeouts": 0,
            "dedup_pages": 0, "released_pages": 0,
        }

    # ------------------------------------------------- prefill side

    def register(self, rid: str) -> None:
        with self._cv:
            self._want.add(rid)

    def registered(self, rid: str) -> bool:
        with self._cv:
            return rid in self._want

    def publish(self, cap: _Capture) -> None:
        with self._cv:
            wanted = cap.rid in self._want
            if wanted:
                self._caps[cap.rid] = cap
                self.counters["published"] += 1
            self._cv.notify_all()
        if not wanted:
            # collector already gave up (deadline, cancel): the gathered
            # handles drop here and the device copies are simply unread
            log.debug("migration capture for %s arrived late", cap.rid)

    def fail(self, rid: str, why: str) -> None:
        with self._cv:
            if rid in self._want:
                self._failed[rid] = why
                self.counters["failed"] += 1
            self._cv.notify_all()

    # -------------------------------------------------- router side

    def collect(self, rid: str,
                timeout: float) -> tuple[Optional[KVHandoff], str]:
        """Wait for the probe's capture and finalize it into host
        blocks. Returns (handoff, "") or (None, why)."""
        deadline = time.perf_counter() + max(0.0, timeout)
        with self._cv:
            while (rid not in self._caps and rid not in self._failed
                   and not self._closed):
                left = deadline - time.perf_counter()
                if left <= 0:
                    self._want.discard(rid)
                    self.counters["timeouts"] += 1
                    return None, "timeout"
                self._cv.wait(timeout=min(left, 0.5))
            if rid in self._failed:
                self._want.discard(rid)
                return None, self._failed.pop(rid)
            if self._closed:
                return None, "closed"
            cap = self._caps.pop(rid)
            self._want.discard(rid)
        # finalize OFF the lock: np.asarray blocks until the async
        # device->host copies land — pump-thread time, not scheduler
        hostside = [np.asarray(h) for h in cap.handles]
        row = jax.tree_util.tree_map(np.asarray, cap.row)
        hpids: list = []
        nbytes = 0
        with self._cv:
            for i in range(cap.npg):
                end = (i + 1) * self.P
                key = (_page_key(cap.tokens, end)
                       if end <= cap.n else None)
                hit = self._dedup.get(key) if key is not None else None
                if hit is not None:
                    self._blocks[hit].ref += 1
                    self.counters["dedup_pages"] += 1
                    hpids.append(hit)
                    continue
                arrays = {nm: np.array(a[:, i])
                          for nm, a in zip(cap.names, hostside)}
                bn = sum(a.nbytes for a in arrays.values())
                bid = self._next_id
                self._next_id += 1
                self._blocks[bid] = _HostBlock(arrays, bn, ref=1, key=key)
                if key is not None:
                    self._dedup[key] = bid
                self._bytes += bn
                nbytes += bn
                hpids.append(bid)
            self.counters["collected"] += 1
        dur = time.perf_counter() - cap.t0
        FLIGHT.transfer("migrate_out", cap.t0, dur, cap.npg, nbytes,
                        track=MIGRATE_TRACK)
        return KVHandoff(
            rid=rid, tokens=cap.tokens, n=cap.n,
            first_token=cap.first_token, hpids=hpids,
            sampler_row=row, nbytes=nbytes, npg=cap.npg,
            prefill_ms=cap.prefill_ms, enq_ms=cap.enq_ms,
            queued_ms=cap.queued_ms, _bus=self), ""

    def forget(self, rid: str) -> None:
        with self._cv:
            self._want.discard(rid)
            self._caps.pop(rid, None)
            self._failed.pop(rid, None)

    # --------------------------------------------------- decode side

    def blocks(self, hpids: list) -> list:
        """The host blocks for a handoff's pages, in table order. The
        handoff's refs keep them live until its release."""
        with self._cv:
            return [self._blocks[h] for h in hpids]

    def _deref(self, hpids: list, npg: int) -> None:
        with self._cv:
            for h in hpids:
                blk = self._blocks.get(h)
                if blk is None:
                    continue
                blk.ref -= 1
                if blk.ref <= 0:
                    del self._blocks[h]
                    if blk.key is not None \
                            and self._dedup.get(blk.key) == h:
                        del self._dedup[blk.key]
                    self._bytes -= blk.nbytes
            self.counters["released_pages"] += npg

    # ------------------------------------------------------ lifecycle

    def host_bytes(self) -> int:
        with self._cv:
            return self._bytes

    def live_blocks(self) -> int:
        with self._cv:
            return len(self._blocks)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class Migrator:
    """One engine's side of the migration protocol, attached as
    ``engine._migrator`` by the router. The prefill side captures
    finishing probe slots into the bus (``on_finish``, scheduler
    thread); the decode side stages + adopts handoffs at admission
    (``assign_migrated``, scheduler thread). Both paths are enqueue-
    only on the device: neither ever blocks a device step."""

    def __init__(self, eng, bus: MigrationBus, side: str) -> None:
        self.eng = eng
        self.bus = bus
        self.side = side
        self._stage_free = [eng.n_slots + _STAGE_BASE + i
                            for i in range(_N_STAGE)]
        self.counters = {
            "captures": 0, "capture_skips": 0, "capture_faults": 0,
            "adoptions": 0, "adopt_faults": 0, "reused_tokens": 0,
        }

    # ---------------------------------------------------- prefill side

    def on_finish(self, slot, reason: str) -> None:
        """Capture a finishing prefill probe's pages onto the bus.
        Called from the prefill engine's ``_finish`` BEFORE release —
        the gathers enqueue ahead of any later overwrite of these pages
        in device order, so the copy is coherent without a sync."""
        if self.side != "prefill":
            return
        req = slot.request
        rid = req.id
        if not rid.endswith(PREFILL_SUFFIX):
            return
        base = rid[: -len(PREFILL_SUFFIX)]
        if not self.bus.registered(base):
            return
        eng = self.eng
        n = slot.n_past
        npg = eng._pool.pages_for(n) if eng._paged else 0
        if (reason != "length" or not slot.generated
                or not eng._paged or req.soft_embeds is not None
                or n <= 0 or npg <= 0):
            self.counters["capture_skips"] += 1
            self.bus.fail(base, reason if reason != "length"
                          else "not_migratable")
            return
        try:
            if faultinject.ACTIVE:
                faultinject.fire("disagg.migrate")
        except faultinject.InjectedFault:
            # capture abandoned with NO bus or pool mutation: the
            # router's collect fails fast and the request re-prefills
            # on the decode engine
            self.counters["capture_faults"] += 1
            tm.ENGINE_KV_MIGRATED_PAGES.labels(
                model=eng._mlabel, outcome="fault").inc(npg)
            self.bus.fail(base, "fault")
            return
        table = eng._pool.table(slot.idx)[:npg]
        if len(table) < npg:
            self.counters["capture_skips"] += 1
            self.bus.fail(base, "short_table")
            return
        c = eng.cache
        tbl = jnp.asarray(np.asarray(
            list(table) + [TRASH_PAGE] * (_pow2(npg) - npg), np.int32))
        handles = [_gather_pages(c.k, tbl), _gather_pages(c.v, tbl)]
        names = ["k", "v"]
        if c.quantized:
            handles.append(_gather_pages(c.k_scale, tbl))
            handles.append(_gather_pages(c.v_scale, tbl))
            names += ["k_scale", "v_scale"]
        for h in handles:
            h.copy_to_host_async()
        # the sampler row AFTER the probe's first sample: rng advanced,
        # penalty counts/history include the prompt and first token —
        # scattering it into the decode sampler makes the continued
        # stream bit-identical to the single-engine stream
        row = _gather_row(eng.sampling, jnp.int32(slot.idx))
        queued = 0.0
        if req.t_submit:
            queued = max(0.0, (slot.t_start - req.t_submit) * 1e3)
        self.counters["captures"] += 1
        self.bus.publish(_Capture(
            rid=base, tokens=list(slot.cache_tokens), n=n,
            first_token=int(slot.generated[0]), handles=tuple(handles),
            names=tuple(names), row=row, npg=npg,
            prefill_ms=slot.t_prefill_ms, enq_ms=slot.t_prefill_enq_ms,
            queued_ms=queued, t0=time.perf_counter()))

    # ----------------------------------------------------- decode side

    def assign_migrated(self, slot, req: GenRequest, out) -> bool:
        """Stage a handoff's pages into ``slot`` and wake it in DECODE.
        Returns False (handoff released, caller re-prefills) on any
        staging failure — fault injection, pool pressure, plane
        mismatch. On success the slot owns private refs to the pages
        and the probe's first token has been emitted."""
        h: KVHandoff = req.disagg
        eng = self.eng
        try:
            if faultinject.ACTIVE:
                faultinject.fire("disagg.handoff")
        except faultinject.InjectedFault:
            # adoption abandoned with NO pool or cache mutation: the
            # caller falls through to _assign and re-prefills
            self.counters["adopt_faults"] += 1
            tm.ENGINE_KV_MIGRATED_PAGES.labels(
                model=eng._mlabel, outcome="dropped").inc(h.npg)
            h.release()
            return False
        if not eng._paged or h.n <= 0 or h.n >= eng.max_seq \
                or not self._stage_free:
            tm.ENGINE_KV_MIGRATED_PAGES.labels(
                model=eng._mlabel, outcome="dropped").inc(h.npg)
            h.release()
            return False
        t0 = time.perf_counter()
        sid = self._stage_free.pop()
        try:
            eng._pool.ensure(sid, h.n)
        except PagePoolExhausted:
            eng._pool.drop(sid)  # release any partial allocation
            self._stage_free.append(sid)
            tm.ENGINE_KV_MIGRATED_PAGES.labels(
                model=eng._mlabel, outcome="dropped").inc(h.npg)
            h.release()
            return False
        table = eng._pool.table(sid)
        npg = len(table)
        b = _pow2(npg)
        c = eng.cache
        blocks = self.bus.blocks(h.hpids[:npg])
        if c.quantized and "k_scale" not in blocks[0].arrays:
            # dtype drift between the two engines (misconfigured
            # prefill cache_dtype): adopt would scatter garbage scales
            eng._pool.drop(sid)
            self._stage_free.append(sid)
            tm.ENGINE_KV_MIGRATED_PAGES.labels(
                model=eng._mlabel, outcome="dropped").inc(h.npg)
            h.release()
            return False
        L, F = c.k.shape[0], c.k.shape[-1]
        P = self.bus.P
        rk = np.zeros((L, b, P, F), c.k.dtype)
        rv = np.zeros((L, b, P, F), c.v.dtype)
        rks = rvs = None
        if c.quantized:
            rks = np.zeros((L, b, P), np.float32)
            rvs = np.zeros((L, b, P), np.float32)
        for i, blk in enumerate(blocks):
            rk[:, i] = blk.arrays["k"]
            rv[:, i] = blk.arrays["v"]
            if rks is not None:
                rks[:, i] = blk.arrays["k_scale"]
                rvs[:, i] = blk.arrays["v_scale"]
        tbl = jnp.asarray(np.asarray(
            list(table) + [TRASH_PAGE] * (b - npg), np.int32))
        dk, dv = jax.device_put(rk), jax.device_put(rv)
        ck = _scatter_pages(c.k, tbl, dk)
        cv = _scatter_pages(c.v, tbl, dv)
        ks, vs = c.k_scale, c.v_scale
        nbytes = int(dk.nbytes) + int(dv.nbytes)
        if c.quantized:
            dks, dvs = jax.device_put(rks), jax.device_put(rvs)
            ks = _scatter_pages(ks, tbl, dks)
            vs = _scatter_pages(vs, tbl, dvs)
            nbytes += int(dks.nbytes) + int(dvs.nbytes)
        eng.cache = type(c)(k=ck, v=cv, k_scale=ks, v_scale=vs)
        # the slot adopts the staged pages by REFERENCE (refcount share,
        # no second copy); dropping the stage leaves the slot as sole
        # owner, so its decode write frontier is privately writable
        eng._pool.share(slot.idx, sid, npg)
        eng._pool.drop(sid)
        self._stage_free.append(sid)
        # sampler row: the probe's post-sample state lands in this
        # slot's column — seeded streams continue bit-identically
        eng.sampling = _scatter_row(
            eng.sampling, jnp.int32(slot.idx), h.sampler_row)
        now = time.perf_counter()
        TRACER.event(req.id, "admit", t=now, model=eng._mlabel)
        TRACER.annotate(req.id, "migrate_adopt", t=now, pages=npg,
                        bytes=nbytes, reused_tokens=h.n)
        wait = max(0.0, now - (h.t_resubmit or req.t_submit or now))
        tm.ENGINE_QUEUE_WAIT.labels(model=eng._mlabel).observe(wait)
        with eng._lock:
            eng._queue_waits.append(wait)
        slot.cache_loaded = None
        slot.request = req
        slot.out = out
        slot.state = SlotState.DECODE
        slot.n_past = h.n
        slot.n_prompt = len(req.prompt_ids)
        slot.cache_tokens = list(h.tokens)
        slot.n_reused = h.n
        if eng._prefix_enabled:
            eng._prefix_index.set_tokens(slot.idx, slot.cache_tokens)
            eng._prefix_index.touch(slot.idx)
            eng._prefix_index.set_chain(
                slot.idx, req.prefix_chain, len(req.prompt_ids))
        slot.generated = []
        slot.decoder = StreamDecoder(eng.tokenizer)
        slot.pending_text = ""
        slot.emit_buf = []
        slot.emit_tok = None
        slot.t_start = now
        slot.t_first = 0.0
        # prompt-processing attribution for a migrated request: the
        # prefill ENGINE's device time plus the migration wall — the
        # decode engine did zero prompt work (satellite: stage-correct
        # TTFT/timing for the disaggregated path)
        slot.t_prefill_ms = h.prefill_ms + h.migrate_ms
        slot.t_prefill_enq_ms = h.enq_ms
        slot.t_prefill_t0 = 0.0
        slot.t_decode_ms = 0.0
        slot.t_last = now
        slot.constraint_state = (
            req.constraint.initial_state() if req.constraint else None)
        eng._epoch += 1
        FLIGHT.transfer("migrate_in", t0, now - t0, npg, nbytes,
                        track=MIGRATE_TRACK)
        tm.ENGINE_KV_MIGRATED_PAGES.labels(
            model=eng._mlabel, outcome="migrated").inc(npg)
        self.counters["adoptions"] += 1
        self.counters["reused_tokens"] += h.n
        h.release()
        # re-emit the probe's first token on the DECODE engine: stamps
        # t_first against the ORIGINAL t_submit (end-to-end TTFT),
        # observes prefill timing, and handles the EOS/stop/max_tokens
        # edges exactly like the single-engine first emit did
        eng._emit_token(slot, h.first_token)
        return True


class DisaggRouter:
    """The front door of a disaggregated pair: routes each request to
    the decode engine directly (local path) or through the prefill ->
    migrate -> decode relay. Everything the worker layer touches on an
    engine that is NOT explicitly overridden here delegates to the
    decode engine — the router is a drop-in for ``LLMEngine`` from the
    backend's point of view."""

    def __init__(self, prefill, decode) -> None:
        self.prefill = prefill
        self.decode = decode
        self.bus = MigrationBus(page=prefill._page)
        prefill._migrator = Migrator(prefill, self.bus, "prefill")
        decode._migrator = Migrator(decode, self.bus, "decode")
        # the prefill engine's active slots run PROMPTS: an expiry
        # there is a prefill-stage overrun, not a decode one
        prefill._deadline_stage = "prefill"
        self.min_prompt = max(1, knobs.int_("LOCALAI_DISAGG_MIN_PROMPT"))
        self.min_ms = knobs.float_("LOCALAI_DISAGG_MIN_MS")
        self.migrate_deadline_s = max(
            0.1, knobs.float_("LOCALAI_DISAGG_MIGRATE_DEADLINE_S"))
        self._mlabel = decode._mlabel
        self._pumps: set = set()  # lint: guarded-by self._plock
        self._plock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------- routing

    def _use_disagg(self, req: GenRequest) -> bool:
        if req.soft_embeds is not None or req.prompt_cache_path:
            return False  # image KV / disk-cache paths stay local
        if req.max_tokens <= 1:
            return False  # the probe WOULD BE the whole request
        n = len(req.prompt_ids)
        if n < self.min_prompt or n >= self.decode.max_seq:
            return False
        if self.min_ms > 0:
            cm = getattr(self.prefill, "_costmodel", None)
            tok_ms = cm.prefill_token_ms() if cm is not None else None
            if tok_ms is not None and tok_ms * n < self.min_ms:
                return False  # predicted prefill too cheap to relay
        return True

    def submit(self, req: GenRequest) -> queue.SimpleQueue:
        return self.submit_many([req])[0]

    def submit_many(
            self, reqs: list[GenRequest]) -> list[queue.SimpleQueue]:
        outs: list = [None] * len(reqs)
        local_idx: list[int] = []
        for i, req in enumerate(reqs):
            if self._closed or not self._use_disagg(req):
                local_idx.append(i)
                continue
            out: queue.SimpleQueue = queue.SimpleQueue()
            outs[i] = out
            tname = f"disagg-pump-{req.id[:8]}"
            t = threading.Thread(target=self._pump, args=(req, out),
                                 daemon=True, name=tname)
            with self._plock:
                self._pumps.add(t)
            t.start()
        if local_idx:
            local_outs = self.decode.submit_many(
                [reqs[i] for i in local_idx])
            for i, out in zip(local_idx, local_outs):
                outs[i] = out
                tm.ENGINE_DISAGG_REQUESTS.labels(
                    model=self._mlabel, path="local").inc()
        return outs

    def generate(self, req: GenRequest) -> StreamEvent:
        q = self.submit(req)
        while True:
            ev = q.get()
            if ev.done:
                return ev

    def cancel(self, request_id: str) -> None:
        self.decode.cancel(request_id)
        self.prefill.cancel(request_id + PREFILL_SUFFIX)

    # --------------------------------------------------------- relay

    def _pump(self, req: GenRequest, out: queue.SimpleQueue) -> None:
        """One disaggregated request's relay thread: run the prefill
        probe, collect the migration, resubmit onto the decode engine
        (the client's queue rides along — no per-token forwarding hop).
        Exactly ONE terminal event reaches ``out`` on every path."""
        rid = req.id
        owned = True  # until the decode engine owns the client stream
        try:
            now0 = time.perf_counter()
            req.t_submit = now0
            budget = req.timeout_s or self.decode._default_deadline_s
            if budget > 0:
                req.deadline = now0 + budget
            # open (or extend) the request's trace before minting the
            # shared id — trace_id_of returns "" on a never-seen id
            TRACER.event(rid, "queue", t=now0, model=self._mlabel)
            if not req.trace_id:
                req.trace_id = TRACER.trace_id_of(rid)
            TRACER.annotate(rid, "disagg", t=now0,
                            prompt_tokens=len(req.prompt_ids))
            self.bus.register(rid)
            probe = dataclasses.replace(
                req, id=rid + PREFILL_SUFFIX, max_tokens=1,
                disagg=None, prompt_cache_path="",
                prompt_cache_all=False, t_submit=0.0, deadline=0.0,
                timeout_s=(max(0.05, req.deadline - now0)
                           if req.deadline else 0.0))
            # the probe rides the SAME distributed trace: one joined
            # trace covers queue -> prefill -> migrate -> decode
            TRACER.start(probe.id, model=self._mlabel,
                         trace_id=req.trace_id)
            probe_q = self.prefill.submit(probe)
            term: Optional[StreamEvent] = None
            buffered: list[StreamEvent] = []
            while term is None:
                ev = probe_q.get()
                if ev.done:
                    term = ev
                else:
                    buffered.append(ev)
            migratable = (term.finish_reason == "length"
                          and term.completion_tokens == 1
                          and not term.error)
            if not migratable:
                if term.finish_reason in ("error", "shed"):
                    # the decode engine may still serve it the plain
                    # way (its own queue/limits decide)
                    owned = self._fallback(req, out)
                    return
                # the request genuinely COMPLETED at its first token
                # (stop hit, EOS, max-length edge, deadline, cancel):
                # the probe's stream IS the answer — forward it
                for ev in buffered:
                    out.put(ev)
                out.put(term)
                owned = False
                tm.ENGINE_DISAGG_REQUESTS.labels(
                    model=self._mlabel, path="disagg").inc()
                TRACER.event(rid, "done")
                TRACER.annotate(rid, "terminal",
                                outcome=term.finish_reason,
                                stage="prefill")
                TRACER.finish(rid, status=term.finish_reason)
                return
            tm.ENGINE_DISAGG_STAGE.labels(
                model=self._mlabel, stage="queued").observe(
                max(0.0, term.timing_queue_ms) / 1e3)
            tm.ENGINE_DISAGG_STAGE.labels(
                model=self._mlabel, stage="prefill").observe(
                max(0.0, term.timing_prompt_processing_ms) / 1e3)
            nowm = time.perf_counter()
            tmo = self.migrate_deadline_s
            if req.deadline:
                tmo = min(tmo, max(0.0, req.deadline - nowm))
            h = why = None
            span = TRACER.begin_span(rid, "migrate", t=nowm)
            try:
                h, why = self.bus.collect(rid, timeout=tmo)
            finally:
                dur_ms = (time.perf_counter() - nowm) * 1e3
                if h is not None:
                    TRACER.end_span(span, bytes=h.nbytes, pages=h.npg,
                                    ms=round(dur_ms, 3))
                else:
                    TRACER.end_span(span, failed=why or "unknown",
                                    ms=round(dur_ms, 3))
            nowr = time.perf_counter()
            if h is None and req.deadline and nowr >= req.deadline:
                # the migrate stage overran the request deadline: emit
                # the terminal HERE with the stage attributed (neither
                # engine owns the request at this instant)
                out.put(StreamEvent(
                    done=True, finish_reason="deadline_exceeded",
                    error="deadline exceeded during KV migration"))
                owned = False
                tm.ENGINE_REQUESTS.labels(
                    model=self._mlabel,
                    reason="deadline_exceeded").inc()
                tm.ENGINE_DEADLINE_EXCEEDED.labels(
                    model=self._mlabel, stage="migrate").inc()
                tm.ENGINE_DISAGG_REQUESTS.labels(
                    model=self._mlabel, path="fallback").inc()
                TRACER.event(rid, "done")
                TRACER.annotate(rid, "terminal",
                                outcome="deadline_exceeded",
                                stage="migrate")
                TRACER.finish(rid, status="deadline_exceeded")
                return
            if h is None:
                owned = self._fallback(req, out)
                return
            mig_ms = (nowr - nowm) * 1e3
            h.migrate_ms = mig_ms
            h.t_resubmit = nowr
            tm.ENGINE_KV_MIGRATION.labels(
                model=self._mlabel).observe(mig_ms / 1e3)
            tm.ENGINE_DISAGG_STAGE.labels(
                model=self._mlabel, stage="migrate").observe(
                mig_ms / 1e3)
            req.disagg = h
            self.decode.submit_many([req], outs=[out])
            owned = False
            tm.ENGINE_DISAGG_REQUESTS.labels(
                model=self._mlabel, path="disagg").inc()
        except Exception:
            log.exception("disagg relay for %s failed", rid)
            if owned:
                out.put(StreamEvent(
                    done=True, finish_reason="error",
                    error="disaggregated relay failed"))
                owned = False
                tm.ENGINE_REQUESTS.labels(
                    model=self._mlabel, reason="error").inc()
                TRACER.event(rid, "done")
                TRACER.annotate(rid, "terminal", outcome="error",
                                detail="disagg relay failure")
                TRACER.finish(rid, status="error")
        finally:
            self.bus.forget(rid)
            with self._plock:
                self._pumps.discard(threading.current_thread())

    def _fallback(self, req: GenRequest, out) -> bool:
        """Re-prefill the request on the decode engine (migration
        failed or was never viable). Returns the new ``owned`` flag —
        False: the decode engine owns the stream now."""
        req.disagg = None
        tm.ENGINE_DISAGG_REQUESTS.labels(
            model=self._mlabel, path="fallback").inc()
        self.decode.submit_many([req], outs=[out])
        return False

    # ----------------------------------------------------- lifecycle

    @property
    def params(self):
        return self.decode.params

    @params.setter
    def params(self, value) -> None:
        # LoRA hot-merge swaps weights on BOTH engines: a migrated
        # prompt must have been prefilled by the same weights that
        # decode it
        self.decode.params = value
        self.prefill.params = value

    def start(self) -> None:
        self.prefill.start()
        self.decode.start()

    def warmup(self) -> None:
        self.decode.warmup()
        self.prefill.warmup()

    def close(self) -> None:
        self._closed = True
        self.bus.close()
        self.prefill.close()
        self.decode.close()

    def __getattr__(self, name: str):
        # everything not overridden (tokenize, embed, metrics, spec,
        # tokenizer, max_seq, ...) is the decode engine's
        return getattr(self.decode, name)


def build_prefill_engine(spec, params, tokenizer, *, decode,
                         cache_dtype=None, tag: str = ""):
    """A prefill-tuned sibling for ``decode``: few large slots (a
    prefill flood is compute-bound — slot count buys nothing), the same
    bucket ladder and context, k=2 decode scan (each probe decodes
    exactly one token past its prompt), no KV tier (probe slots live
    one prompt each; the migration bus is their interchange), and —
    CRITICALLY — the same sampler penalty window, so a captured sampler
    row scatters into the decode engine's state shape-exactly. Shares
    ``params`` by reference: no second copy of the weights in HBM."""
    from .engine import LLMEngine

    kwargs = dict(
        n_slots=max(1, knobs.int_("LOCALAI_DISAGG_PREFILL_SLOTS")),
        max_seq=decode.max_seq,
        prefill_buckets=decode.prefill_buckets,
        penalty_window=decode.sampling.window,
        decode_steps=2,
        latency_target_ms=None,
        autostart=False,
        kv_tier=False,
        # shares `params` by reference with the decode engine: paging
        # either side out would strand the other's dispatches
        weight_paging=False,
        tag=(tag + "-prefill") if tag else "prefill",
    )
    if cache_dtype is not None:
        kwargs["cache_dtype"] = cache_dtype
    return LLMEngine(spec, params, tokenizer, **kwargs)
