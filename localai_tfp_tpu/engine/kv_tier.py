"""Tiered KV memory: HBM pages, host-RAM spill, on-disk cold sessions.

The paged pool (kv_pool.py) made HBM scale with live tokens, but a
RETURNING session — a chat user who steps away and comes back — still
costs either resident HBM pages held idle or a full re-prefill. This
module adds two tiers under the HBM arena so resident-session capacity
is bound by host RAM (and then disk), not HBM:

- HOT: pages in the device arena, owned by PagePool. Unchanged.
- WARM: pages spilled to host RAM as numpy arrays (native KV dtype,
  int8 scale planes included), moved by an async D2H gather enqueued on
  the device stream — ``copy_to_host_async`` + ``is_ready`` polling
  through ``TransferWindow.reap`` (models/staging.py), so a spill NEVER
  blocks a device step. Promotion stages pages into a pseudo-slot page
  table (ids >= n_slots — the pool is keyed by int, not bounded by the
  slot array) via an async H2D scatter overlapped with the request's
  queue wait, then adopts them into the assigned slot by reference
  (``share``), so a prefetch hit re-prefills zero tokens.
- COLD: whole sessions demoted to the on-disk prompt-cache format
  (np.savez tokens/k/v[/k_scale/v_scale], slot-contiguous [L, n, F]) —
  the SAME format ``prompt_cache_path`` reads and writes, produced and
  consumed here by background threads so the scheduler never waits on
  the filesystem. A request whose session is cold waits in the
  admission queue (bounded by a deadline) while the load runs; past
  the deadline it admits normally and re-prefills.

Why correctness is cheap here:

- Device-order serialization: a spill's gather is enqueued before any
  later dispatch can recycle its source pages, so the copy reads
  pre-overwrite content even if the table is dropped immediately (the
  same argument kv_pool.prepare_write makes for COW source pages). The
  pool-side ``pin`` exists to protect the ACCOUNTING of background
  spills, not the content.
- Content addressing: a KV page holding positions [0, (i+1)*page) is a
  pure function of the token prefix through the page end (causal
  attention), so warm pages dedup by token-prefix hash — a prefix
  shared by N sessions spills ONCE, with refcounts, and needs no
  invalidation machinery (the key never goes stale because it IS the
  content identity). This is the host-RAM mirror of the pool's
  refcounted prefix sharing.

All tier state is mutated on the engine scheduler thread; background
threads touch only their own file I/O and hand results back through
queues. ``LOCALAI_KV_TIER=off`` removes every hook (meshed, multihost,
follower and draft-model engines force it off — spilled main-model
pages would strand a draft cache, and the arena is single-chip-only).
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..config import knobs
import numpy as np

from ..models.staging import TransferWindow
from ..telemetry import metrics as tm
from ..telemetry.flightrec import FLIGHT
from ..utils import faultinject
from .kv_pool import TRASH_PAGE, PagePoolExhausted

__all__ = ["KVTierManager", "write_cache_file", "read_cache_file"]


# ------------------------------------------------------------ cold format
#
# The cold tier IS the prompt-cache on-disk format: one np.savez with
# tokens (int32 [n]) and slot-contiguous rows k/v ([L, n, F]; int8 adds
# k_scale/v_scale [L, n]). bf16 rows are widened to f32 (no portable
# numpy encoding); the restore path casts back. Files written here are
# readable through prompt_cache_path on any engine — paged or dense —
# and vice versa.


def write_cache_file(path: str, tokens: np.ndarray, k: np.ndarray,
                     v: np.ndarray,
                     scales: Optional[tuple] = None) -> None:
    """Atomically persist one session in the prompt-cache format."""

    def host(arr):  # bf16 has no portable numpy encoding
        out = np.asarray(arr)
        return out if out.dtype in (np.int8, np.float32) \
            else out.astype(np.float32)

    payload = {"tokens": np.asarray(tokens, np.int32),
               "k": host(k), "v": host(v)}
    if scales is not None:
        payload["k_scale"] = np.asarray(scales[0])
        payload["v_scale"] = np.asarray(scales[1])
    # unique temp name: concurrent saves to one path must not truncate
    # each other's half-written file before os.replace
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)


def read_cache_file(path: str):
    """Open a prompt-cache/cold-tier file (lazy NpzFile mapping with
    keys tokens/k/v[/k_scale/v_scale])."""
    return np.load(path)


# --------------------------------------------------------- device helpers


@jax.jit
def _gather_pages(arr, tbl):
    # [L, n_pages, ...] x [b] -> [L, b, ...]; padded entries read the
    # trash page (no data) and are ignored by the finalize slicing
    return arr[:, tbl]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_pages(arr, tbl, rows):
    # padded entries write the trash page — the established discard
    # target for routed-away writes
    return arr.at[:, tbl].set(rows.astype(arr.dtype))


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _common_prefix(a, b) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


# ------------------------------------------------------------ host store


@dataclass
class _HostPage:
    """One spilled KV page in host RAM: native-dtype rows plus scale
    planes, refcounted across entries (content-addressed pages shared
    by several sessions hold one copy)."""
    arrays: dict  # k/v [L, P, F]; k_scale/v_scale [L, P] when int8
    nbytes: int
    ref: int = 0
    key: Optional[bytes] = None  # content hash; full pages only


@dataclass
class _Entry:
    """One demoted session: an exact token prefix and the host (or
    disk) pages holding its KV."""
    eid: int
    tokens: list
    n: int
    hpids: list  # warm/saving; emptied when cold
    state: str  # warm | saving | cold | loading
    path: Optional[str] = None
    last_used: float = 0.0


@dataclass
class _Spill:
    slot_idx: int
    tokens: list
    n: int
    plan: list  # ("dup", hpid) | ("copy", j, key-or-None) per page
    copies: list  # device page ids gathered (unpin set)
    handles: tuple  # gather outputs bound for host
    nbytes: int
    t0: float
    urgent: bool
    pinned: bool


@dataclass
class _Fetch:
    entry: _Entry
    stage: int  # pseudo-slot id holding the staged table
    n: int
    t0: float


class KVTierManager:
    """Demotion/promotion policy and bookkeeping for the three tiers.

    Owned by one paged, single-chip engine; every public method runs on
    its scheduler thread (tests may call ``tick``/``settle`` only while
    the scheduler is quiescent). ``self._lock`` guards the host store
    for cross-thread readers (stats endpoints, profilers); background
    save/load threads never touch tier state directly — they post to
    ``_done_saves``/``_done_loads`` and the next ``tick`` applies."""

    # pseudo-slot ids for staged promotions (bounded: a fetch holds one)
    N_STAGE = 4
    # staged pages not adopted within this window are abandoned (the
    # request was cancelled or its admission stalled behind a full pool)
    STAGE_TTL_S = 5.0
    _SCAN_EVERY_S = 0.05  # demotion/eviction policy cadence

    def __init__(self, eng) -> None:
        self.eng = eng
        self.P = eng._page
        self._mlabel = eng._mlabel
        self.host_budget = int(
            knobs.float_("LOCALAI_KV_TIER_HOST_MB") * (1 << 20))
        self.watermark = min(1.0, max(0.05, knobs.float_(
            "LOCALAI_KV_TIER_WATERMARK")))
        self.idle_s = max(0.0, knobs.float_("LOCALAI_KV_TIER_IDLE_S"))
        self.cold_s = max(0.0, knobs.float_("LOCALAI_KV_TIER_COLD_S"))
        self.fetch_deadline_s = max(0.05, knobs.float_(
            "LOCALAI_KV_TIER_FETCH_DEADLINE_S"))
        self.cold_dir = knobs.str_("LOCALAI_KV_TIER_DIR")
        self._lock = threading.Lock()
        self._host: dict[int, _HostPage] = {}  # lint: guarded-by self._lock
        self._dedup: dict[bytes, int] = {}  # lint: guarded-by self._lock
        self._entries: dict[int, _Entry] = {}  # lint: guarded-by self._lock
        self._host_bytes = 0
        self._disk_pages = 0
        self._next_id = 1
        # in-flight transfers (scheduler-thread-owned)
        self._swin = TransferWindow(int(
            knobs.float_("LOCALAI_KV_TIER_INFLIGHT_MB") * (1 << 20)))
        self._fwin = TransferWindow(1 << 62)  # tracking only, no cap
        self._spilling: set[int] = set()  # slot idxs with a spill aloft
        self._fetches: dict[str, _Fetch] = {}  # req.id -> staged fetch
        self._stage_free = [eng.n_slots + i for i in range(self.N_STAGE)]
        self._waiting: dict[str, float] = {}  # req.id -> cold deadline
        self._late: set[str] = set()  # deadline passed: re-prefill
        self._done_loads: queue.SimpleQueue = queue.SimpleQueue()
        self._done_saves: queue.SimpleQueue = queue.SimpleQueue()
        self._io_threads: list[threading.Thread] = []
        self._last_active: dict[int, float] = {}
        self._t_scan = 0.0
        self._t_born = time.perf_counter()
        # host-side tallies for tools/profile_kv.py and bench extras
        # (the Prometheus families are process-cumulative; these are
        # per-engine ground truth)
        self.counters = {
            "spills": 0, "spilled_pages": 0, "dedup_pages": 0,
            "fetches": 0, "reused_tokens": 0, "prefetch_hit": 0,
            "prefetch_late": 0, "prefetch_miss": 0,
            "prefetch_expired": 0, "saves": 0, "loads": 0,
            "spill_faults": 0, "fetch_faults": 0,
        }

    # ------------------------------------------------------------- policy

    def tick(self) -> None:
        """One policy step, piggybacked on the scheduler's admission
        pass: harvest completed transfers, apply background-thread
        results, expire stale stages, and (rate-limited) run the
        demotion/eviction watermarks. Never blocks on the device."""
        now = time.perf_counter()
        for sp in self._swin.reap():
            self._finalize_spill(sp, now)
        for npg, nbytes, t0 in self._fwin.reap():
            FLIGHT.transfer("fetch", t0, now - t0, npg, nbytes)
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="fetch", outcome="ok").inc()
            tm.ENGINE_KV_TIER_BYTES.labels(
                model=self._mlabel, direction="fetch").inc(nbytes)
        self._apply_io_results(now)
        self._expire_stages(now)
        if now - self._t_scan >= self._SCAN_EVERY_S:
            self._t_scan = now
            self._scan(now)

    def _scan(self, now: float) -> None:
        eng = self.eng
        for s in eng.slots:
            if s.active:
                self._last_active[s.idx] = now
        st = eng._pool.stats()
        if st.total and st.in_use / st.total >= self.watermark:
            cands = [
                s for s in eng.slots
                if not s.active and s.cache_tokens
                and s.idx not in self._spilling
                and eng._pool.held(s.idx)
                and now - self._last_active.get(s.idx, self._t_born)
                >= self.idle_s]
            mono = time.monotonic()
            cands.sort(key=lambda s: eng._prefix_index.value(s.idx, mono))
            for s in cands[:2]:
                self._spill(s, urgent=False, now=now)
        if self.cold_s and self.cold_dir:
            with self._lock:
                stale = [e for e in self._entries.values()
                         if e.state == "warm"
                         and now - e.last_used >= self.cold_s]
            for e in stale[:2]:
                self._start_save(e)
        evicted = 0
        while self._host_bytes > self.host_budget and evicted < 4:
            if not self._evict_one(now):
                break
            evicted += 1

    def _evict_one(self, now: float) -> bool:
        """Push the least-recently-used warm entry down a tier: save to
        disk when a cold dir is configured, discard otherwise."""
        with self._lock:
            warm = [e for e in self._entries.values()
                    if e.state == "warm"]
        if not warm:
            return False
        victim = min(warm, key=lambda e: e.last_used)
        if self.cold_dir:
            self._start_save(victim)
            # saving frees host pages only at completion; stop the
            # eviction sweep here rather than queue every warm entry
            return False
        self._drop_entry(victim)
        tm.ENGINE_KV_TIER_MOVES.labels(
            model=self._mlabel, direction="save",
            outcome="aborted").inc()
        return True

    # -------------------------------------------------------------- spill

    def capture(self, slot, req) -> None:
        """Demote-on-reuse: the slot is about to be reassigned and
        _assign's prepare_write will discard every resident page beyond
        the new request's common prefix. Enqueue the spill FIRST —
        device-order serialization lets the gather read pre-overwrite
        content even though the pages recycle right after — so slot
        churn moves sessions down a tier instead of erasing them."""
        common = _common_prefix(slot.cache_tokens, req.prompt_ids)
        if len(slot.cache_tokens) - common >= self.P:
            self._spill(slot, urgent=True, now=time.perf_counter())

    def demote_urgent(self, slot) -> bool:
        """Pool-pressure demotion: called by the engine's reclaim path
        immediately before it drops the victim's table. Enqueues the
        D2H gather and returns — the caller's drop proceeds regardless
        (device-order keeps the copy coherent), so the allocator's
        observable behavior is identical to a plain reclaim."""
        return self._spill(slot, urgent=True, now=time.perf_counter())

    def _spill(self, slot, urgent: bool, now: float) -> bool:
        eng = self.eng
        if slot.idx in self._spilling:
            return True  # the in-flight spill already covers this state
        tokens = list(slot.cache_tokens)
        n = min(len(tokens), eng.max_seq)
        if n < self.P:
            return False  # under one page: re-prefill is cheaper
        if self._covered(tokens, n):
            self._touch_covering(tokens, n, now)
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="spill",
                outcome="dedup").inc()
            return True
        if not urgent and self._swin.over(1):
            return False  # in-flight spill budget full: retry next scan
        try:
            if faultinject.ACTIVE:
                faultinject.fire("kv_tier.spill")
        except faultinject.InjectedFault:
            # spill abandoned BEFORE any bookkeeping: for an urgent
            # demote the caller's drop falls back to today's plain
            # reclaim (the session re-prefills on return); pool state
            # stays leak_check-clean by construction
            self.counters["spill_faults"] += 1
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="spill",
                outcome="fault").inc()
            return False
        npg = eng._pool.pages_for(n)
        table = eng._pool.table(slot.idx)[:npg]
        if len(table) < npg:
            return False  # table shorter than the token run: skip
        plan: list = []
        copies: list[int] = []
        with self._lock:
            for i in range(npg):
                end = (i + 1) * self.P
                key = self._page_key(tokens, end) if end <= n else None
                hpid = self._dedup.get(key) if key is not None else None
                if hpid is not None:
                    # hold the shared page for the in-flight spill so
                    # eviction cannot free it before finalize
                    self._host[hpid].ref += 1
                    plan.append(("dup", hpid))
                else:
                    plan.append(("copy", len(copies), key))
                    copies.append(table[i])
        if not copies:
            # every page dedup'd: the entry materializes with no DMA
            sp = _Spill(slot.idx, tokens, n, plan, [], (), 0, now,
                        urgent, False)
            self._finalize_spill(sp, now)
            return True
        c = eng.cache
        tbl = jnp.asarray(np.asarray(
            copies + [TRASH_PAGE] * (_pow2(len(copies)) - len(copies)),
            np.int32))
        handles = [_gather_pages(c.k, tbl), _gather_pages(c.v, tbl)]
        if c.quantized:
            handles.append(_gather_pages(c.k_scale, tbl))
            handles.append(_gather_pages(c.v_scale, tbl))
        for h in handles:
            h.copy_to_host_async()
        nbytes = sum(int(h.nbytes) for h in handles)
        pinned = not urgent
        if pinned:
            # background spill: the slot stays resident until the copy
            # lands; pin the gathered pages so a concurrent reclaim's
            # drop can't recycle their ids under the bookkeeping
            eng._pool.pin(copies)
            self._spilling.add(slot.idx)
        sp = _Spill(slot.idx, tokens, n, plan, copies, tuple(handles),
                    nbytes, now, urgent, pinned)
        self._swin.add(sp, nbytes, sp.handles)
        return True

    def _finalize_spill(self, sp: _Spill, now: float) -> None:
        """Turn a landed spill into warm host pages + an entry. Runs at
        harvest (handles already ready), so the np.asarray calls are
        host-memory copies, not device syncs."""
        eng = self.eng
        hostside = [np.asarray(h) for h in sp.handles]
        names = ["k", "v", "k_scale", "v_scale"][:len(hostside)]
        hpids: list[int] = []
        with self._lock:
            for step in sp.plan:
                if step[0] == "dup":
                    hpids.append(step[1])  # ref already held at plan
                    self.counters["dedup_pages"] += 1
                    continue
                _, j, key = step
                arrays = {nm: np.array(a[:, j])
                          for nm, a in zip(names, hostside)}
                nbytes = sum(a.nbytes for a in arrays.values())
                hpid = self._next_id
                self._next_id += 1
                if key is not None and key in self._dedup:
                    key = None  # racing spill published it first
                self._host[hpid] = _HostPage(arrays, nbytes, ref=1,
                                             key=key)
                if key is not None:
                    self._dedup[key] = hpid
                self._host_bytes += nbytes
                hpids.append(hpid)
            ent = _Entry(self._next_id, sp.tokens, sp.n, hpids, "warm",
                         last_used=now)
            self._next_id += 1
            self._entries[ent.eid] = ent
            # an older entry that is a strict prefix of this one is
            # subsumed (its pages live on via the dedup refs)
            for old in [e for e in self._entries.values()
                        if e is not ent and e.state == "warm"
                        and e.n <= sp.n
                        and e.tokens[:e.n] == sp.tokens[:e.n]]:
                self._drop_entry_locked(old)
        self.counters["spills"] += 1
        self.counters["spilled_pages"] += len(sp.copies)
        if sp.pinned:
            eng._pool.unpin(sp.copies)
            self._spilling.discard(sp.slot_idx)
            slot = eng.slots[sp.slot_idx]
            if not slot.active and slot.cache_tokens == sp.tokens:
                # the demotion's point: the resident copy moves DOWN —
                # release the HBM pages now that host RAM holds them
                eng._pool.drop(slot.idx)
                slot.cache_tokens = []
                slot.n_past = 0
                eng._prefix_index.remove(slot.idx)
        if sp.copies:
            FLIGHT.transfer("spill", sp.t0, now - sp.t0,
                            len(sp.copies), sp.nbytes)
            tm.ENGINE_KV_TIER_BYTES.labels(
                model=self._mlabel, direction="spill").inc(sp.nbytes)
        tm.ENGINE_KV_TIER_MOVES.labels(
            model=self._mlabel, direction="spill", outcome="ok").inc()

    # -------------------------------------------------------- promotion

    def plan(self, req, now: float) -> bool:
        """Admission-time prefetch: when a tier entry covers the
        request's prompt, stage its pages back into the arena (async
        H2D, overlapped with the rest of the wave). Returns True when
        the request should requeue — its session is cold and the disk
        load is still inside the deadline window."""
        rid = req.id
        if rid in self._fetches or rid in self._late:
            return False
        ent, n = self._lookup(req.prompt_ids)
        if ent is None or not self._worth(req, n):
            return False
        if ent.state in ("warm", "saving"):
            self._stage(req, ent, n, now)
            return False
        # cold / loading: hold the request while the background load
        # runs, but never past the deadline — a slow disk degrades to
        # today's re-prefill, it cannot stall admission
        deadline = self._waiting.get(rid)
        if deadline is None:
            self._waiting[rid] = now + self.fetch_deadline_s
            if ent.state == "cold":
                self._start_load(ent)
            return True
        if now > deadline:
            self._waiting.pop(rid, None)
            self._late.add(rid)
            return False
        return True

    def adopt(self, slot, req) -> int:
        """Attach a staged fetch to the slot the request was assigned:
        the stage table is shared in by reference and the slot's
        resident prefix becomes the promoted session, so _assign's
        ordinary prefix-reuse path skips the covered tokens. Returns
        the number of promoted tokens (0 = re-prefill)."""
        now = time.perf_counter()
        rid = req.id
        self._waiting.pop(rid, None)
        f = self._fetches.pop(rid, None)
        if f is None and rid not in self._late:
            ent, n = self._lookup(req.prompt_ids)
            if ent is not None and ent.state in ("warm", "saving") \
                    and self._worth(req, n):
                # not planned ahead (e.g. zero queue wait): stage now —
                # the scatter is still only ENQUEUED before the prefill
                # that follows it in program order, so it costs no sync
                if self._stage(req, ent, n, now):
                    f = self._fetches.pop(rid, None)
        if f is None:
            result = "late" if rid in self._late else "miss"
            self._late.discard(rid)
            self.counters["prefetch_" + result] += 1
            tm.ENGINE_KV_TIER_PREFETCH.labels(
                model=self._mlabel, result=result).inc()
            return 0
        eng = self.eng
        if _common_prefix(slot.cache_tokens, req.prompt_ids) >= f.n:
            # the assigned slot already holds a better resident prefix;
            # the staged copy is redundant — abandon it
            self._abandon_fetch(rid, f)
            return 0
        npg = eng._pool.held(f.stage)
        eng._pool.share(slot.idx, f.stage, npg)
        eng._pool.drop(f.stage)
        self._stage_free.append(f.stage)
        slot.cache_tokens = list(f.entry.tokens[:f.n])
        slot.n_past = f.n
        if eng._prefix_enabled:
            eng._prefix_index.set_tokens(slot.idx, slot.cache_tokens)
        f.entry.last_used = now
        self.counters["prefetch_hit"] += 1
        self.counters["reused_tokens"] += f.n
        tm.ENGINE_KV_TIER_PREFETCH.labels(
            model=self._mlabel, result="hit").inc()
        return f.n

    def _stage(self, req, ent: _Entry, n: int, now: float) -> bool:
        eng = self.eng
        try:
            if faultinject.ACTIVE:
                faultinject.fire("kv_tier.fetch")
        except faultinject.InjectedFault:
            # promotion abandoned with NO pool or cache mutation: the
            # request admits normally and re-prefills (the warm entry
            # survives for the next attempt)
            self.counters["fetch_faults"] += 1
            self._late.add(req.id)
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="fetch",
                outcome="fault").inc()
            return False
        if not self._stage_free:
            return False
        sid = self._stage_free.pop()
        try:
            eng._pool.ensure(sid, n)
        except PagePoolExhausted:
            eng._pool.drop(sid)  # release any partial allocation
            self._stage_free.append(sid)
            return False
        table = eng._pool.table(sid)
        npg = len(table)
        b = _pow2(npg)
        c = eng.cache
        L, F = c.k.shape[0], c.k.shape[-1]
        rk = np.zeros((L, b, self.P, F), c.k.dtype)
        rv = np.zeros((L, b, self.P, F), c.v.dtype)
        rks = rvs = None
        if c.quantized:
            rks = np.zeros((L, b, self.P), np.float32)
            rvs = np.zeros((L, b, self.P), np.float32)
        with self._lock:
            for i, hpid in enumerate(ent.hpids[:npg]):
                hp = self._host[hpid]
                rk[:, i] = hp.arrays["k"]
                rv[:, i] = hp.arrays["v"]
                if rks is not None:
                    rks[:, i] = hp.arrays["k_scale"]
                    rvs[:, i] = hp.arrays["v_scale"]
        tbl = jnp.asarray(np.asarray(
            table + [TRASH_PAGE] * (b - npg), np.int32))
        dk, dv = jax.device_put(rk), jax.device_put(rv)
        ck = _scatter_pages(c.k, tbl, dk)
        cv = _scatter_pages(c.v, tbl, dv)
        ks, vs = c.k_scale, c.v_scale
        handles = [dk, dv]
        if c.quantized:
            dks, dvs = jax.device_put(rks), jax.device_put(rvs)
            ks = _scatter_pages(ks, tbl, dks)
            vs = _scatter_pages(vs, tbl, dvs)
            handles += [dks, dvs]
        eng.cache = type(c)(k=ck, v=cv, k_scale=ks, v_scale=vs)
        eng._epoch += 1
        nbytes = sum(int(h.nbytes) for h in handles)
        self._fwin.add((npg, nbytes, now), nbytes, tuple(handles))
        self._fetches[req.id] = _Fetch(ent, sid, n, now)
        self.counters["fetches"] += 1
        ent.last_used = now
        return True

    def _abandon_fetch(self, rid: str, f: _Fetch) -> None:
        self.eng._pool.drop(f.stage)
        self._stage_free.append(f.stage)
        self.counters["prefetch_expired"] += 1
        tm.ENGINE_KV_TIER_PREFETCH.labels(
            model=self._mlabel, result="expired").inc()

    def _expire_stages(self, now: float) -> None:
        for rid, f in list(self._fetches.items()):
            if now - f.t0 > self.STAGE_TTL_S:
                del self._fetches[rid]
                self._abandon_fetch(rid, f)

    def _worth(self, req, n: int) -> bool:
        if n < self.P:
            return False
        eng = self.eng
        have = max((_common_prefix(s.cache_tokens, req.prompt_ids)
                    for s in eng.slots if not s.active), default=0)
        if eng._prefix_enabled:
            have = max(have, eng._prefix_index.match(req.prompt_ids)[0])
        # a resident/copyable prefix at least as long makes the host
        # fetch redundant; require one full page of net gain
        return n >= have + self.P

    def _lookup(self, prompt_ids) -> tuple[Optional[_Entry], int]:
        best, best_n = None, 0
        with self._lock:
            for e in self._entries.values():
                n = min(_common_prefix(e.tokens, prompt_ids), e.n,
                        self.eng.max_seq - 1)
                if n > best_n:
                    best, best_n = e, n
        return best, best_n

    # ------------------------------------------------------- cold tier IO

    def _cold_path(self, ent: _Entry) -> str:
        h = hashlib.sha1(np.asarray(ent.tokens[:ent.n],
                                    np.int64).tobytes()).hexdigest()[:24]
        return os.path.join(self.cold_dir,
                            f"kvtier-{self._mlabel}-{h}.npz")

    def _start_save(self, ent: _Entry) -> None:
        """Warm -> cold: background thread assembles the contiguous
        rows and writes the prompt-cache file; host pages release when
        the tick applies the completion."""
        if ent.state != "warm":
            return
        ent.state = "saving"
        with self._lock:
            pages = [self._host[h].arrays for h in ent.hpids]
        tokens = np.asarray(ent.tokens[:ent.n], np.int32)
        n, path, q = ent.n, self._cold_path(ent), self._done_saves

        def save():
            try:
                k = np.concatenate([p["k"] for p in pages],
                                   axis=1)[:, :n]
                v = np.concatenate([p["v"] for p in pages],
                                   axis=1)[:, :n]
                scales = None
                if "k_scale" in pages[0]:
                    scales = (
                        np.concatenate([p["k_scale"] for p in pages],
                                       axis=1)[:, :n],
                        np.concatenate([p["v_scale"] for p in pages],
                                       axis=1)[:, :n])
                write_cache_file(path, tokens, k, v, scales)
                q.put((ent.eid, path, None))
            except OSError as e:
                q.put((ent.eid, path, e))

        t = threading.Thread(target=save, daemon=True,
                             name="kv-tier-save")
        t.start()
        self._io_threads.append(t)

    def _start_load(self, ent: _Entry) -> None:
        if ent.state != "cold":
            return
        try:
            if faultinject.ACTIVE:
                faultinject.fire("kv_tier.fetch")
        except faultinject.InjectedFault:
            # the cold copy is unreachable this round: drop the entry so
            # waiting requests fall through to re-prefill at deadline
            self.counters["fetch_faults"] += 1
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="load",
                outcome="fault").inc()
            self._drop_entry(ent)
            return
        ent.state = "loading"
        path, q = ent.path, self._done_loads

        def load():
            try:
                with read_cache_file(path) as data:
                    arrs = {nm: np.array(data[nm]) for nm in data.files}
                q.put((ent.eid, arrs, None))
            except (OSError, ValueError, KeyError) as e:
                q.put((ent.eid, None, e))

        t = threading.Thread(target=load, daemon=True,
                             name="kv-tier-load")
        t.start()
        self._io_threads.append(t)

    def _apply_io_results(self, now: float) -> None:
        while True:
            try:
                eid, path, err = self._done_saves.get_nowait()
            except queue.Empty:
                break
            with self._lock:
                ent = self._entries.get(eid)
            if ent is None or ent.state != "saving":
                continue
            if err is not None:
                ent.state = "warm"  # host pages still held: no loss
                tm.ENGINE_KV_TIER_MOVES.labels(
                    model=self._mlabel, direction="save",
                    outcome="fault").inc()
                continue
            ent.state = "cold"
            ent.path = path
            with self._lock:
                for hpid in ent.hpids:
                    self._deref_locked(hpid)
                ent.hpids = []
            npg = -(-ent.n // self.P)
            self._disk_pages += npg
            self.counters["saves"] += 1
            tm.ENGINE_KV_TIER_MOVES.labels(
                model=self._mlabel, direction="save", outcome="ok").inc()
            tm.ENGINE_KV_TIER_BYTES.labels(
                model=self._mlabel, direction="save").inc(
                self._entry_bytes(ent))
        while True:
            try:
                eid, arrs, err = self._done_loads.get_nowait()
            except queue.Empty:
                break
            with self._lock:
                ent = self._entries.get(eid)
            if ent is None or ent.state != "loading":
                continue
            if err is not None or "k" not in (arrs or {}):
                tm.ENGINE_KV_TIER_MOVES.labels(
                    model=self._mlabel, direction="load",
                    outcome="fault").inc()
                self._drop_entry(ent)
                continue
            self._install_loaded(ent, arrs, now)

    def _install_loaded(self, ent: _Entry, arrs: dict,
                        now: float) -> None:
        """Disk rows -> warm host pages (chopped to page granularity,
        full pages re-entering the dedup index)."""
        P = self.P
        n = min(ent.n, arrs["k"].shape[1])
        if n < P:
            self._drop_entry(ent)
            return
        ent.n = n
        npg = -(-n // P)
        names = ["k", "v"] + (
            ["k_scale", "v_scale"] if "k_scale" in arrs else [])
        hpids: list[int] = []
        nbytes_total = 0
        with self._lock:
            for i in range(npg):
                lo, hi = i * P, min((i + 1) * P, n)
                key = (self._page_key(ent.tokens, hi)
                       if hi == (i + 1) * P else None)
                hpid = self._dedup.get(key) if key is not None else None
                if hpid is not None:
                    self._host[hpid].ref += 1
                    hpids.append(hpid)
                    continue
                arrays = {}
                for nm in names:
                    a = np.zeros(
                        (arrs[nm].shape[0], P) + arrs[nm].shape[2:],
                        arrs[nm].dtype)
                    a[:, : hi - lo] = arrs[nm][:, lo:hi]
                    arrays[nm] = a
                nbytes = sum(a.nbytes for a in arrays.values())
                hpid = self._next_id
                self._next_id += 1
                self._host[hpid] = _HostPage(arrays, nbytes, ref=1,
                                             key=key)
                if key is not None:
                    self._dedup[key] = hpid
                self._host_bytes += nbytes
                nbytes_total += nbytes
                hpids.append(hpid)
            ent.hpids = hpids
            ent.state = "warm"
            ent.last_used = now
        self._disk_pages = max(0, self._disk_pages - npg)
        self.counters["loads"] += 1
        tm.ENGINE_KV_TIER_MOVES.labels(
            model=self._mlabel, direction="load", outcome="ok").inc()
        tm.ENGINE_KV_TIER_BYTES.labels(
            model=self._mlabel, direction="load").inc(nbytes_total)

    # --------------------------------------------------------- host store

    def _page_key(self, tokens, end: int) -> bytes:
        # causal attention: KV rows for positions [0, end) are a pure
        # function of tokens[:end], so the prefix hash IS the content id
        return hashlib.sha1(
            np.asarray(tokens[:end], np.int64).tobytes()).digest()

    def _covered(self, tokens, n: int) -> bool:
        with self._lock:
            return any(e.n >= n and e.tokens[:n] == tokens[:n]
                       for e in self._entries.values()
                       if e.state != "loading")

    def _touch_covering(self, tokens, n: int, now: float) -> None:
        with self._lock:
            for e in self._entries.values():
                if e.n >= n and e.tokens[:n] == tokens[:n]:
                    e.last_used = now

    def _deref_locked(self, hpid: int) -> None:
        # lint: holds self._lock
        hp = self._host[hpid]
        hp.ref -= 1
        if hp.ref > 0:
            return
        del self._host[hpid]
        self._host_bytes -= hp.nbytes
        if hp.key is not None and self._dedup.get(hp.key) == hpid:
            del self._dedup[hp.key]

    def _drop_entry(self, ent: _Entry) -> None:
        with self._lock:
            self._drop_entry_locked(ent)

    def _drop_entry_locked(self, ent: _Entry) -> None:
        # lint: holds self._lock
        if self._entries.pop(ent.eid, None) is None:
            return
        for hpid in ent.hpids:
            self._deref_locked(hpid)
        if ent.state == "cold":
            self._disk_pages = max(
                0, self._disk_pages - (-(-ent.n // self.P)))
        ent.hpids = []
        ent.state = "dropped"

    # ------------------------------------------------------- diagnostics

    def tier_pages(self, hbm_in_use: int) -> dict:
        with self._lock:
            return {"hbm": hbm_in_use, "host": len(self._host),
                    "disk": self._disk_pages}

    def stats(self) -> dict:
        with self._lock:
            warm = sum(1 for e in self._entries.values()
                       if e.state in ("warm", "saving"))
            cold = sum(1 for e in self._entries.values()
                       if e.state in ("cold", "loading"))
            return {
                "entries_warm": warm, "entries_cold": cold,
                "host_pages": len(self._host),
                "host_bytes": self._host_bytes,
                "disk_pages": self._disk_pages,
                **self.counters,
            }

    def busy(self) -> bool:
        """Transfers or IO still in flight (settle/close use this)."""
        return bool(len(self._swin) or len(self._fwin)
                    or any(t.is_alive() for t in self._io_threads)
                    or any(e.state in ("saving", "loading")
                           for e in list(self._entries.values())))

    def _entry_bytes(self, ent: _Entry) -> int:
        c = self.eng.cache
        per_tok = 2 * c.k.dtype.itemsize * c.k.shape[0] * c.k.shape[-1]
        if c.quantized:
            per_tok += 2 * 4 * c.k.shape[0]
        return ent.n * per_tok

    def leak_check(self) -> None:
        """Cross-tier accounting invariants: host-page refcounts equal
        their referencing entries plus in-flight spill holds, the
        dedup index points at live pages that carry its keys, and the
        byte tally matches the store. Raises AssertionError."""
        expect: dict[int, int] = {}
        for sp in [t for t, _, _ in self._swin._q]:
            for step in sp.plan:
                if step[0] == "dup":
                    expect[step[1]] = expect.get(step[1], 0) + 1
        with self._lock:
            for e in self._entries.values():
                for hpid in e.hpids:
                    expect[hpid] = expect.get(hpid, 0) + 1
            for hpid, hp in self._host.items():
                if hp.ref != expect.get(hpid, 0):
                    raise AssertionError(
                        f"host page {hpid}: ref {hp.ref} != "
                        f"{expect.get(hpid, 0)} references")
                if hp.key is not None \
                        and self._dedup.get(hp.key) != hpid:
                    raise AssertionError(
                        f"host page {hpid} carries a dedup key the "
                        "index does not map to it")
            for key, hpid in self._dedup.items():
                if hpid not in self._host:
                    raise AssertionError("dedup key maps to a freed "
                                         f"host page {hpid}")
            orphans = set(expect) - set(self._host)
            if orphans:
                raise AssertionError(
                    f"entries reference freed host pages: {orphans}")
            if self._host_bytes != sum(h.nbytes
                                       for h in self._host.values()):
                raise AssertionError("host byte tally drifted")
        staged = {f.stage for f in self._fetches.values()}
        if staged & set(self._stage_free):
            raise AssertionError("stage id both free and in use")

    # ---------------------------------------------------------- lifecycle

    def settle(self, timeout_s: float = 10.0) -> None:
        """Drive ticks until every in-flight transfer and IO thread
        lands. ONLY for tests/tools while the scheduler is quiescent
        (engine closed, or idle with no pending work)."""
        self._t_scan = 0.0  # force one policy scan past the rate limit
        self.tick()
        deadline = time.perf_counter() + timeout_s
        while self.busy() and time.perf_counter() < deadline:
            self.tick()
            time.sleep(0.005)
        self.tick()

    def close(self) -> None:
        """Engine teardown: complete (blocking is fine here — the
        scheduler is gone) and account every in-flight transfer, then
        abandon staged fetches so the pool's leak_check stays clean."""
        now = time.perf_counter()
        while len(self._swin):
            for h in self._swin._q[0][2]:
                jax.block_until_ready(h)
            for sp in self._swin.reap():
                self._finalize_spill(sp, now)
        for rid, f in list(self._fetches.items()):
            del self._fetches[rid]
            self._abandon_fetch(rid, f)
        for t in self._io_threads:
            t.join(timeout=2.0)
        self._apply_io_results(now)
        self._io_threads = [t for t in self._io_threads
                            if t.is_alive()]
