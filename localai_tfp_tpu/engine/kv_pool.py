"""Host-owned page allocator for the paged KV pool.

The dense KV cache pre-reserves ``max_seq`` positions of HBM per slot, so
slot count — the direct ceiling on batch size — is bound by WORST-CASE
context. The paged pool instead backs every slot with a table of
fixed-size pages drawn from one shared arena
(``[n_layers, n_pages, page, kv_dim]``, models/transformer.py), so HBM
scales with *live* tokens and a prefix resident in one slot can be
shared into another by reference (refcount bump) instead of by row copy
— the block-granular design TPU serving converged on (Ragged Paged
Attention / RTP-LLM, PAPERS.md).

This module is the HOST side only: pure bookkeeping (free list,
refcounts, per-slot page tables), no jax imports. The engine snapshots
tables into dispatch payloads as plain int32 index arrays, so multihost
followers replay paged dispatches like any other record and the device
never sees allocator state.

Invariants the engine relies on (asserted by ``leak_check``):

- page 0 is the reserved TRASH page: reads of unallocated table slots
  and discarded writebacks are pointed at it; it never carries data.
- a page's refcount equals the number of table entries referencing it.
- a page is WRITABLE only while exactly one table references it
  (``writable``); shared pages are full, immutable prefix pages.
- every free-list page has refcount 0 and appears in no table.
- a PINNED page (tier transfer in flight — engine/kv_tier.py) never
  enters the free list: dropping its last table reference parks it in
  limbo until ``unpin`` releases it, so an in-flight device->host DMA's
  source pages cannot be reallocated and rewritten under the copy's
  bookkeeping (device-order already protects the *content*; the pin
  protects the *accounting*).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

__all__ = ["PagePool", "PagePoolExhausted", "TRASH_PAGE"]

TRASH_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free page available (after any engine-side reclaim)."""


@dataclass
class PoolStats:
    total: int  # data pages (excludes the trash page)
    free: int
    in_use: int  # distinct allocated pages
    shared: int  # pages referenced by >1 table (zero-copy prefix shares)
    refs: int  # total table entries (>= in_use; the gap is sharing)
    pinned: int = 0  # pages held by an in-flight tier transfer


class PagePool:
    """Free-list page allocator with refcounted cross-slot sharing."""

    def __init__(self, n_pages: int, page_size: int) -> None:
        if n_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (1 is the trash "
                             f"page); got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1; got {page_size}")
        self.page = page_size
        self.n_pages = n_pages
        # the scheduler thread owns all allocation, but stats()/tables
        # are read from server threads (/backend/monitor, profilers), so
        # bookkeeping mutations take a lock — sub-microsecond host work
        # at admission granularity, invisible next to a device dispatch
        self._lock = threading.Lock()
        # pop() allocates ascending (1, 2, ...): keeps fresh arenas dense
        self._free: list[int] = list(range(n_pages - 1, 0, -1))  # lint: guarded-by self._lock
        self._ref = [0] * n_pages  # lint: guarded-by self._lock
        self._ref[TRASH_PAGE] = 1  # permanently reserved
        self._tables: dict[int, list[int]] = {}  # lint: guarded-by self._lock
        # allocation outcomes, exported as
        # engine_kv_page_alloc_total{outcome=...} by the engine
        self.allocs = {"fresh": 0, "shared": 0, "cow": 0}  # lint: guarded-by self._lock
        # pin counts per page (engine/kv_tier.py spill-in-flight holds):
        # a pinned page whose refcount drops to 0 parks in limbo instead
        # of re-entering the free list, until its last unpin
        self._pins: dict[int, int] = {}  # lint: guarded-by self._lock

    # ----------------------------------------------------------- queries

    def table(self, slot: int) -> list[int]:
        """The slot's physical page run (page i covers token positions
        [i*page, (i+1)*page)). Returns a snapshot copy: concurrent
        monitor reads must not alias a list the scheduler mutates."""
        with self._lock:
            return list(self._tables.get(slot, ()))

    def held(self, slot: int) -> int:
        """Pages currently referenced by the slot's table."""
        with self._lock:
            return len(self._tables.get(slot, ()))

    def writable(self, pg: int) -> bool:
        """Whether a dispatch may write this page (exactly one owner;
        never the trash page)."""
        with self._lock:
            return self._writable(pg)

    def _writable(self, pg: int) -> bool:
        # lint: holds self._lock
        return pg != TRASH_PAGE and self._ref[pg] == 1

    def pages_for(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 0) // self.page)

    def pinned(self, pg: int) -> bool:
        """Whether the page is held by an in-flight tier transfer (a
        drop would park it in limbo, not free it — reclaim estimates
        must not count it)."""
        with self._lock:
            return pg in self._pins

    def pinned_in(self, slot: int) -> bool:
        """Whether any page in the slot's table is pinned (a tier spill
        of this slot is in flight — reclaim must not race its drop)."""
        with self._lock:
            return any(pg in self._pins
                       for pg in self._tables.get(slot, ()))

    def stats(self) -> PoolStats:
        with self._lock:
            in_use = (self.n_pages - 1) - len(self._free)
            shared = sum(1 for pg in range(1, self.n_pages)
                         if self._ref[pg] > 1)
            refs = sum(len(t) for t in self._tables.values())
            return PoolStats(total=self.n_pages - 1,
                             free=len(self._free),
                             in_use=in_use, shared=shared, refs=refs,
                             pinned=len(self._pins))

    # -------------------------------------------------------- allocation

    def _alloc(self) -> int:
        # lint: holds self._lock
        if not self._free:
            raise PagePoolExhausted(
                f"KV page pool exhausted ({self.n_pages - 1} pages of "
                f"{self.page} tokens)")
        pg = self._free.pop()
        self._ref[pg] = 1
        self.allocs["fresh"] += 1
        return pg

    def _unref(self, pg: int) -> None:
        # lint: holds self._lock
        if pg == TRASH_PAGE:
            return
        self._ref[pg] -= 1
        if self._ref[pg] < 0:
            raise AssertionError(f"page {pg} refcount went negative")
        if self._ref[pg] == 0 and pg not in self._pins:
            self._free.append(pg)

    # ----------------------------------------------------------- pinning

    def pin(self, pages) -> None:
        """Hold ``pages`` out of the free list while a tier transfer is
        in flight: an unreferenced pinned page parks in limbo instead of
        becoming allocatable, so the transfer's completion bookkeeping
        (engine/kv_tier.py) runs against stable page identities."""
        with self._lock:
            for pg in pages:
                if pg == TRASH_PAGE:
                    continue
                if self._ref[pg] == 0 and pg not in self._pins:
                    raise AssertionError(
                        f"pin of free page {pg}: pin while referenced")
                self._pins[pg] = self._pins.get(pg, 0) + 1

    def unpin(self, pages) -> None:
        """Release pins; a page whose last pin drops with refcount 0
        (its tables were dropped mid-transfer) re-enters the free
        list here."""
        with self._lock:
            for pg in pages:
                if pg == TRASH_PAGE:
                    continue
                n = self._pins.get(pg, 0) - 1
                if n < 0:
                    raise AssertionError(f"unpin of unpinned page {pg}")
                if n:
                    self._pins[pg] = n
                else:
                    del self._pins[pg]
                    if self._ref[pg] == 0:
                        self._free.append(pg)

    def ensure(self, slot: int, n_tokens: int) -> int:
        """Grow the slot's table to cover positions [0, n_tokens);
        returns the number of fresh pages appended. Raises
        PagePoolExhausted when the arena runs dry (the engine reclaims
        free-slot residents and retries)."""
        with self._lock:
            t = self._tables.setdefault(slot, [])
            need = self.pages_for(n_tokens)
            added = 0
            while len(t) < need:
                t.append(self._alloc())
                added += 1
            return added

    def append_fresh(self, slot: int) -> int:
        """Append one fresh private page; returns its physical id."""
        with self._lock:
            pg = self._alloc()
            self._tables.setdefault(slot, []).append(pg)
            return pg

    def truncate(self, slot: int, n_tokens: int) -> None:
        """Drop table entries wholly beyond ``n_tokens`` positions."""
        with self._lock:
            t = self._tables.get(slot)
            if t is None:
                return
            keep = self.pages_for(n_tokens)
            while len(t) > keep:
                self._unref(t.pop())

    def drop(self, slot: int) -> None:
        """Release every page the slot references (shared pages survive
        while other tables still reference them)."""
        with self._lock:
            for pg in self._tables.pop(slot, []):
                self._unref(pg)

    # ----------------------------------------------------------- sharing

    def share(self, dst: int, src: int, n_full_pages: int) -> int:
        """Zero-copy prefix share: dst's table becomes the first
        ``n_full_pages`` of src's run by REFERENCE (refcount bump, no
        device work). dst's previous pages are released first. Returns
        the number of pages shared."""
        self.drop(dst)
        with self._lock:
            run = self._tables.get(src, [])[:n_full_pages]
            for pg in run:
                self._ref[pg] += 1
            self._tables[dst] = list(run)
            self.allocs["shared"] += len(run)
            return len(run)

    def prepare_write(self, slot: int, pos: int):
        """Make position ``pos`` (the slot's write frontier) privately
        writable: pages wholly beyond the frontier are dropped, and a
        SHARED boundary page holding committed rows [boundary, pos) is
        copy-on-write swapped for a fresh private page. Returns the
        (src_page, dst_page) pair the engine must row-copy on device, or
        None when no copy is needed."""
        with self._lock:
            t = self._tables.setdefault(slot, [])
            b = pos // self.page
            while len(t) > b + 1:
                self._unref(t.pop())
            if len(t) <= b:
                return None  # frontier page not allocated yet: ensure()
            if pos % self.page == 0:
                # the boundary page carries no committed rows — a shared
                # one is simply released (content lives on in the
                # donor's table)
                if not self._writable(t[b]):
                    self._unref(t.pop())
                return None
            if self._writable(t[b]):
                return None
            old = t[b]
            fresh = self._alloc()
            t[b] = fresh
            self._unref(old)
            self.allocs["cow"] += 1
        # the device copy the caller dispatches is enqueued before any
        # later write can recycle ``old``, so device-order serialization
        # keeps the read coherent even if old just hit the free list
        return old, fresh

    # ------------------------------------------------------- diagnostics

    def leak_check(self) -> None:
        """Assert the structural invariants; raises AssertionError on a
        leak or double-owner (used by the churn fuzz test and callable
        from debug endpoints)."""
        with self._lock:
            return self._leak_check()

    def _leak_check(self) -> None:
        # lint: holds self._lock
        counts = [0] * self.n_pages
        for t in self._tables.values():
            for pg in t:
                counts[pg] += 1
        if counts[TRASH_PAGE]:
            raise AssertionError("trash page referenced by a table")
        for pg in range(1, self.n_pages):
            if counts[pg] != self._ref[pg]:
                raise AssertionError(
                    f"page {pg}: refcount {self._ref[pg]} != "
                    f"{counts[pg]} table references")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate pages on the free list")
        for pg in free:
            if self._ref[pg] != 0:
                raise AssertionError(f"free page {pg} has refcount "
                                     f"{self._ref[pg]}")
        live = {pg for t in self._tables.values() for pg in t}
        if live & free:
            raise AssertionError("page both free and table-referenced")
        # cross-tier accounting: pins are positive, never on the trash
        # page, and a pinned-but-unreferenced page sits in limbo —
        # excluded from the free list until unpin returns it
        limbo = set()
        for pg, n in self._pins.items():
            if n <= 0:
                raise AssertionError(f"page {pg} has pin count {n}")
            if pg == TRASH_PAGE:
                raise AssertionError("trash page pinned")
            if self._ref[pg] == 0:
                limbo.add(pg)
        if limbo & free:
            raise AssertionError("pinned unreferenced page on the free "
                                 "list")
        if len(live) + len(free) + len(limbo) != self.n_pages - 1:
            raise AssertionError("orphaned pages: neither free, "
                                 "referenced, nor pinned in limbo")
