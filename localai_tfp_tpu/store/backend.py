"""In-memory vector store backend.

Capability counterpart of the reference's local-store worker
(ref: backend/go/stores/store.go:39-511 — columnar float32 keys + byte
values, StoresSet :106, StoresGet :266, StoresDelete, StoresFindNormalized
:373 with the normalized-keys fast path, topK selection :349).

Design: contiguous numpy matrix of keys + parallel list of values. Cosine
similarity is one matvec — on-device via jnp when the store is large enough
to benefit, numpy below that threshold (host matvec beats a TPU dispatch
for small stores).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from ..config import knobs
from ..workers.base import Backend, ModelLoadOptions, Result

_DEVICE_THRESHOLD = 50_000  # rows; above this the matvec moves to jnp


class NativeVectorStore:
    """ctypes wrapper over native/vecstore.cpp — same surface as
    VectorStore; key storage + similarity scan live in C++, values stay
    here keyed by row id."""

    def __init__(self) -> None:
        from ..native import load_library

        lib = load_library("vecstore")
        if lib is None:
            raise RuntimeError("native vecstore unavailable")
        c = ctypes
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.vs_new.restype = c.c_void_p
        lib.vs_free.argtypes = [c.c_void_p]
        lib.vs_len.restype = c.c_int64
        lib.vs_len.argtypes = [c.c_void_p]
        lib.vs_set.restype = c.c_int64
        lib.vs_set.argtypes = [c.c_void_p, f32p, c.c_int64, c.c_int, i64p]
        lib.vs_get.argtypes = [c.c_void_p, f32p, c.c_int64, i64p]
        lib.vs_delete.restype = c.c_int64
        lib.vs_delete.argtypes = [c.c_void_p, f32p, c.c_int64, i64p]
        lib.vs_find.restype = c.c_int64
        lib.vs_find.argtypes = [c.c_void_p, f32p, c.c_int64, i64p,
                                np.ctypeslib.ndpointer(np.float32)]
        lib.vs_row_key.argtypes = [c.c_void_p, c.c_int64, f32p]
        lib.vs_dim.restype = c.c_int
        lib.vs_dim.argtypes = [c.c_void_p]
        self._lib = lib
        self._h = lib.vs_new()
        self._values: list = []
        self._lock = threading.RLock()

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.vs_free(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.vs_len(self._h))

    def _check_dim(self, keys: np.ndarray) -> None:
        """The C side trusts the caller's width; enforce it here (the
        Python fallback raises the same way)."""
        dim = self._lib.vs_dim(self._h)
        if dim and keys.shape[-1] != dim:
            raise ValueError(
                f"key width {keys.shape[-1]} != store width {dim}")

    def set(self, keys: np.ndarray, values: list) -> None:
        keys = np.ascontiguousarray(np.atleast_2d(keys), np.float32)
        if len(values) != keys.shape[0]:
            raise ValueError("keys and values length mismatch")
        with self._lock:
            rows = np.zeros(keys.shape[0], np.int64)
            total = self._lib.vs_set(
                self._h, keys, keys.shape[0], keys.shape[1], rows)
            if total < 0:
                raise ValueError(
                    f"key width {keys.shape[1]} != store width "
                    f"{self._lib.vs_dim(self._h)}")
            for r, v in zip(rows, values):
                if r < len(self._values):
                    self._values[r] = v
                else:
                    self._values.append(v)

    def get(self, keys: np.ndarray) -> tuple[np.ndarray, list]:
        keys = np.ascontiguousarray(np.atleast_2d(keys), np.float32)
        with self._lock:
            self._check_dim(keys)
            rows = np.zeros(keys.shape[0], np.int64)
            self._lib.vs_get(self._h, keys, keys.shape[0], rows)
            hit = rows >= 0
            return keys[hit], [self._values[r] for r in rows[hit]]

    def delete(self, keys: np.ndarray) -> int:
        keys = np.ascontiguousarray(np.atleast_2d(keys), np.float32)
        with self._lock:
            self._check_dim(keys)
            remap = np.zeros(max(len(self._values), 1), np.int64)
            dropped = self._lib.vs_delete(
                self._h, keys, keys.shape[0], remap)
            if dropped:
                self._values = [
                    v for r, v in enumerate(self._values) if remap[r] >= 0
                ]
            return int(dropped)

    def find(self, key: np.ndarray, top_k: int
             ) -> tuple[np.ndarray, list, np.ndarray]:
        key = np.ascontiguousarray(np.asarray(key, np.float32).reshape(-1))
        with self._lock:
            self._check_dim(key[None])
            n = len(self._values)
            if not n:
                return (np.zeros((0, key.shape[0]), np.float32), [],
                        np.zeros((0,), np.float32))
            rows = np.zeros(min(top_k, n), np.int64)
            sims = np.zeros(min(top_k, n), np.float32)
            k = self._lib.vs_find(self._h, key, top_k, rows, sims)
            out_keys = np.zeros((k, key.shape[0]), np.float32)
            for j in range(k):
                self._lib.vs_row_key(self._h, rows[j], out_keys[j])
            return out_keys, [self._values[r] for r in rows[:k]], sims[:k]


def make_store():
    """Native store when built (unless LOCALAI_NATIVE_STORE=0)."""
    if knobs.flag("LOCALAI_NATIVE_STORE"):
        try:
            return NativeVectorStore()
        except RuntimeError:
            pass
    return VectorStore()


class VectorStore:
    def __init__(self) -> None:
        self._keys = np.zeros((0, 0), np.float32)
        self._norms = np.zeros((0,), np.float32)
        self._values: list[list] = []
        self._index: dict[bytes, int] = {}
        self._normalized = True  # all keys unit-norm so far (ref :373)
        self._lock = threading.RLock()

    def __len__(self) -> int:
        return len(self._values)

    @staticmethod
    def _kb(key: np.ndarray) -> bytes:
        return np.ascontiguousarray(key, np.float32).tobytes()

    def set(self, keys: np.ndarray, values: list) -> None:
        """Upsert rows (ref: StoresSet :106 — replaces on same key)."""
        keys = np.atleast_2d(np.asarray(keys, np.float32))
        if len(values) != keys.shape[0]:
            raise ValueError("keys and values length mismatch")
        with self._lock:
            if self._keys.size == 0 and keys.shape[0]:
                self._keys = np.zeros((0, keys.shape[1]), np.float32)
            if keys.shape[0] and keys.shape[1] != self._keys.shape[1]:
                raise ValueError(
                    f"key width {keys.shape[1]} != store width "
                    f"{self._keys.shape[1]}"
                )
            new_rows = []
            new_vals = []
            for k, v in zip(keys, values):
                kb = self._kb(k)
                i = self._index.get(kb)
                if i is not None:
                    self._values[i] = v
                else:
                    self._index[kb] = len(self._values) + len(new_rows)
                    new_rows.append(k)
                    new_vals.append(v)
            if new_rows:
                block = np.stack(new_rows)
                self._keys = np.concatenate([self._keys, block])
                norms = np.linalg.norm(block, axis=1)
                self._norms = np.concatenate([self._norms, norms])
                self._values.extend(new_vals)
                if not np.allclose(norms, 1.0, atol=1e-4):
                    self._normalized = False

    def get(self, keys: np.ndarray) -> tuple[np.ndarray, list]:
        keys = np.atleast_2d(np.asarray(keys, np.float32))
        out_k, out_v = [], []
        with self._lock:
            for k in keys:
                i = self._index.get(self._kb(k))
                if i is not None:
                    out_k.append(k)
                    out_v.append(self._values[i])
        return (np.stack(out_k) if out_k else
                np.zeros((0, keys.shape[1]), np.float32)), out_v

    def delete(self, keys: np.ndarray) -> int:
        keys = np.atleast_2d(np.asarray(keys, np.float32))
        with self._lock:
            drop = {self._index[self._kb(k)] for k in keys
                    if self._kb(k) in self._index}
            if not drop:
                return 0
            keep = [i for i in range(len(self._values)) if i not in drop]
            self._keys = self._keys[keep]
            self._norms = self._norms[keep]
            self._values = [self._values[i] for i in keep]
            self._index = {self._kb(k): i
                           for i, k in enumerate(self._keys)}
            return len(drop)

    def find(self, key: np.ndarray, top_k: int
             ) -> tuple[np.ndarray, list, np.ndarray]:
        """Cosine top-K (ref: StoresFind :373 — dot product when all keys
        normalized, full cosine otherwise)."""
        key = np.asarray(key, np.float32).reshape(-1)
        with self._lock:
            if not len(self._values):
                return np.zeros((0, key.shape[0]), np.float32), [], \
                    np.zeros((0,), np.float32)
            keys, norms = self._keys, self._norms
            values = list(self._values)
            normalized = self._normalized

        if keys.shape[0] >= _DEVICE_THRESHOLD:
            import jax.numpy as jnp

            dots = np.asarray(jnp.asarray(keys) @ jnp.asarray(key))
        else:
            dots = keys @ key
        if normalized:
            sims = dots
        else:
            qn = np.linalg.norm(key)
            sims = dots / np.maximum(norms * qn, 1e-12)
        k = min(top_k, sims.shape[0])
        top = np.argpartition(-sims, k - 1)[:k]
        top = top[np.argsort(-sims[top])]
        return keys[top], [values[i] for i in top], sims[top]


class LocalStoreBackend(Backend):
    """Worker wrapper speaking the Stores* RPC surface
    (ref: backend.proto StoresSet/Delete/Get/Find)."""

    def __init__(self) -> None:
        self.store = make_store()

    def load_model(self, opts: ModelLoadOptions) -> Result:
        return Result(True, "store ready")

    def health(self) -> bool:
        return True

    def stores_set(self, keys, values) -> Result:
        self.store.set(np.asarray(keys, np.float32), list(values))
        return Result(True)

    def stores_delete(self, keys) -> Result:
        self.store.delete(np.asarray(keys, np.float32))
        return Result(True)

    def stores_get(self, keys):
        got_k, got_v = self.store.get(np.asarray(keys, np.float32))
        return got_k.tolist(), got_v

    def stores_find(self, key, top_k: int):
        got_k, got_v, sims = self.store.find(
            np.asarray(key, np.float32), top_k
        )
        return got_k.tolist(), got_v, sims.tolist()
