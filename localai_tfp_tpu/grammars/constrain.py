"""Grammar-constrained decoding: per-step token masks for the TPU sampler.

The reference constrains generation by handing llama.cpp a GBNF grammar that
its sampler consults per candidate token (ref: pkg/functions builds the
grammar; grpc-server.cpp:2441-2454 plumbs grammar triggers). On TPU the
sampler runs on device, so the constraint is realized as a boolean
vocab mask computed host-side by a pushdown automaton and shipped with the
decode dispatch (SURVEY.md §7 hard part #3: host mask computation
overlapped with the device step).

Mask computation walks a byte-trie of the vocabulary against the grammar's
"set of stacks" state: a trie subtree is pruned the moment a prefix char is
rejected, so the cost per step is proportional to the *feasible* frontier,
not the vocab size. States are cached by (state, char) in the matcher.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .gbnf import Grammar, GrammarMatcher, MatchState, parse_gbnf


class _TrieNode:
    __slots__ = ("children", "token_ids")

    def __init__(self) -> None:
        self.children: dict[str, _TrieNode] = {}
        self.token_ids: list[int] = []


def _build_trie(token_strs: list[Optional[str]]) -> _TrieNode:
    root = _TrieNode()
    for tid, s in enumerate(token_strs):
        if not s:
            continue
        node = root
        for ch in s:
            nxt = node.children.get(ch)
            if nxt is None:
                nxt = node.children[ch] = _TrieNode()
            node = nxt
        node.token_ids.append(tid)
    return root


class GrammarConstraint:
    """Constrains decoding to strings of a GBNF grammar.

    Engine contract (engine/engine.py GenRequest.constraint):
    - ``initial_state()`` → opaque state
    - ``next_mask(state)`` → np.bool_[vocab] of admissible next tokens
    - ``advance(state, token_id)`` → next state
    EOS is admitted iff the grammar can terminate at the current state.
    """

    def __init__(self, grammar: Grammar, tokenizer) -> None:
        self.matcher = GrammarMatcher(grammar)
        self.tokenizer = tokenizer
        self.vocab_size = tokenizer.vocab_size
        self.eos_ids = set(getattr(tokenizer, "eos_ids", ()) or ())
        self._token_strs: list[Optional[str]] = [None] * self.vocab_size
        for tid in range(self.vocab_size):
            try:
                s = tokenizer.decode([tid])
            except (KeyError, IndexError, ValueError,
                    UnicodeDecodeError):
                s = None  # special/control token: not grammar text
            # control/special tokens (decode to empty or replacement char)
            # are never part of grammar text
            if s and "�" not in s:
                self._token_strs[tid] = s
        self._trie = _build_trie(self._token_strs)
        self._mask_cache: dict[MatchState, np.ndarray] = {}

    @classmethod
    def from_gbnf(cls, text: str, tokenizer) -> "GrammarConstraint":
        return cls(parse_gbnf(text), tokenizer)

    def initial_state(self) -> MatchState:
        return self.matcher.initial_state()

    def advance(self, state: MatchState, token_id: int) -> MatchState:
        s = self._token_strs[token_id]
        if s is None:
            return state  # eos / special token: state unchanged (terminal)
        return self.matcher.accept_string(state, s)

    def accept_text(self, state: MatchState, text: str) -> MatchState:
        """Feed raw text (symmetric with NativeGrammarConstraint)."""
        return self.matcher.accept_string(state, text)

    def next_mask(self, state: MatchState) -> np.ndarray:
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        mask = np.zeros(self.vocab_size, dtype=bool)
        # iterative DFS over the vocab trie, pruning rejected prefixes
        stack = [(self._trie, state)]
        while stack:
            node, st = stack.pop()
            for tid in node.token_ids:
                mask[tid] = True
            for ch, child in node.children.items():
                nst = self.matcher.accept_char(st, ch)
                if nst:
                    stack.append((child, nst))
        if self.matcher.can_end(state):
            for e in self.eos_ids:
                mask[e] = True
        if len(self._mask_cache) < 4096:
            self._mask_cache[state] = mask
        return mask


class LazyGrammarConstraint:
    """Trigger-gated grammar (ref: grpc-server.cpp:2441-2454 grammar_lazy
    + grammar_triggers; pkg/functions/parse.go:51 `triggers:` yaml).

    The grammar stays DORMANT — generation unconstrained — until one of
    the trigger words appears in the generated text; from the trigger
    boundary on, the inner grammar constrains decoding, and the text
    from the trigger onward (trigger word included, llama.cpp
    semantics) is fed to it. This is how text-then-tool-call models
    work: prose preamble free-form, `<function=...` onward constrained.

    Wraps any constraint implementing the engine contract
    (initial_state/next_mask/advance) plus a ``tokenizer`` attribute.
    State: ("d", tail) while dormant, ("a", inner_state) once active.
    """

    def __init__(self, inner, triggers: list[str], tokenizer) -> None:
        self.triggers = [t for t in triggers if t]
        assert self.triggers, (
            "use the inner constraint when there are no triggers")
        self.inner = inner
        self.tokenizer = tokenizer
        self.vocab_size = inner.vocab_size
        self._max_trig = max(len(t) for t in self.triggers)
        self._free = np.ones(self.vocab_size, dtype=bool)
        # callers (engine logit_bias path) must not corrupt the shared
        # dormant mask in place — they copy before mutating, and this
        # flag turns any violation into a loud error
        self._free.setflags(write=False)
        strs = getattr(inner, "_token_strs", None)
        if strs is not None:  # reuse the inner table: a 128k-vocab
            # decode loop is seconds of first-request latency
            self._token_strs = strs
        else:
            self._token_strs = [None] * self.vocab_size
            for tid in range(self.vocab_size):
                try:
                    s = tokenizer.decode([tid])
                except (KeyError, IndexError, ValueError,
                        UnicodeDecodeError):
                    continue  # special/control token: not grammar text
                if s and "�" not in s:
                    self._token_strs[tid] = s

    def initial_state(self):
        return ("d", "")

    def next_mask(self, state) -> np.ndarray:
        kind, st = state
        if kind == "d":
            return self._free
        return self.inner.next_mask(st)

    def advance(self, state, token_id: int):
        kind, st = state
        if kind == "a":
            return ("a", self.inner.advance(st, token_id))
        s = self._token_strs[token_id] if token_id < self.vocab_size else None
        if not s:
            return state
        tail = st + s
        # a trigger fully inside the OLD tail would have fired then, so
        # scanning the whole (bounded) tail is idempotent-safe
        hit = min((p for p in (tail.find(t) for t in self.triggers)
                   if p >= 0), default=-1)
        if hit >= 0:
            # grammar receives the trigger word and everything after it
            return ("a", self.inner.accept_text(
                self.inner.initial_state(), tail[hit:]))
        # bound the rolling tail: a trigger can straddle token
        # boundaries, so keep max_trigger-1 chars of lookbehind
        return ("d", tail[-(self._max_trig - 1):] if self._max_trig > 1
                else "")


class JSONConstraint(GrammarConstraint):
    """Constrain output to (schema-conforming) JSON — the TPU realization of
    the reference's response_format json_schema → BNF path
    (ref: core/http/endpoints/openai/chat.go:216-246)."""

    def __init__(self, tokenizer, schema: Optional[dict] = None) -> None:
        from .json_schema import schema_to_gbnf

        super().__init__(parse_gbnf(schema_to_gbnf(schema)), tokenizer)
