"""Parse LLM output back into tool calls + text content.

Capability counterpart of the reference's function-call response parsing
(ref: pkg/functions/parse.go — FunctionsConfig options :16-60,
ParseFunctionCall :221-338 with regex/JSON recovery and parallel calls,
text-content capture ParseTextContent :163, cleanup rules CleanupLLMResult
:149). Clean-room Python implementation over the same YAML config surface
(config/model_config.py FunctionsConfig).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..config.model_config import FunctionsConfig


@dataclass
class FuncCallResults:
    name: str = ""
    arguments: str = ""  # JSON string (OpenAI wire format)


def cleanup_llm_result(text: str, cfg: FunctionsConfig) -> str:
    """Apply replace_llm_results regex rules (ref: parse.go:149-161)."""
    for rule in cfg.replace_llm_results or []:
        key = rule.get("key", "")
        value = rule.get("value", "")
        if key:
            text = re.sub(key, value, text)
    return text


def parse_text_content(text: str, cfg: FunctionsConfig) -> str:
    """Extract free-text content via capture_llm_results regexes
    (ref: parse.go ParseTextContent :163-186)."""
    for pattern in cfg.capture_llm_results or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            return (m.group(1) if m.groups() else m.group(0)).strip()
    return ""


def _replace_results(text: str, cfg: FunctionsConfig) -> str:
    for rule in cfg.replace_function_results or []:
        key = rule.get("key", "")
        value = rule.get("value", "")
        if key:
            text = re.sub(key, value, text)
    return text


_LLAMA31_CALL = re.compile(
    r"<function=(\w+)>(.*?)</function>", re.DOTALL
)


def _json_candidates(text: str) -> list[str]:
    """Find balanced top-level JSON objects/arrays in free text."""
    out = []
    depth = 0
    start = -1
    in_str = False
    esc = False
    for i, ch in enumerate(text):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "{[":
            if depth == 0:
                start = i
            depth += 1
        elif ch in "}]":
            if depth > 0:
                depth -= 1
                if depth == 0 and start >= 0:
                    out.append(text[start:i + 1])
                    start = -1
    return out


def parse_function_call(text: str, cfg: FunctionsConfig) -> list[FuncCallResults]:
    """Recover tool calls from model output (ref: parse.go
    ParseFunctionCall :221-338). Handles: single JSON object, JSON array of
    calls (parallel), llama3.1 <function=…> syntax, json_regex_match
    extraction, response_regex named groups, and argument-as-object or
    argument-as-string forms."""
    name_key = cfg.function_name_key or "name"
    args_key = cfg.function_arguments_key or "arguments"

    text = _replace_results(text, cfg)
    results: list[FuncCallResults] = []

    # llama 3.1 native syntax
    for m in _LLAMA31_CALL.finditer(text):
        results.append(FuncCallResults(name=m.group(1),
                                       arguments=m.group(2).strip()))
    if results:
        return results

    # response_regex with named groups (ref: parse.go:287-317)
    for pattern in cfg.response_regex or []:
        for m in re.finditer(pattern, text, re.DOTALL):
            gd = m.groupdict()
            if name_key in gd:
                args = gd.get(args_key, "") or "{}"
                results.append(FuncCallResults(name=gd[name_key],
                                               arguments=args))
    if results:
        return results

    # json_regex_match: extract the JSON blob first (ref: parse.go:240-255)
    candidates: list[str] = []
    for pattern in cfg.json_regex_match or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            candidates.append(m.group(1) if m.groups() else m.group(0))
            break
    if not candidates:
        candidates = _json_candidates(text)

    for cand in candidates:
        try:
            obj = json.loads(cand)
        except ValueError:
            continue
        calls = obj if isinstance(obj, list) else [obj]
        for c in calls:
            if not isinstance(c, dict):
                continue
            name = c.get(name_key)
            if not isinstance(name, str) or not name:
                continue
            args = c.get(args_key, {})
            if isinstance(args, str):
                args_str = args
            else:
                args_str = json.dumps(args)
            results.append(FuncCallResults(name=name, arguments=args_str))
        if results:
            break
    return results


def apply_finetune(text: str, *, echo_prompt: str = "",
                   cutstrings: Optional[list[str]] = None,
                   extract_regex: Optional[list[str]] = None,
                   trimspace: Optional[list[str]] = None,
                   trimsuffix: Optional[list[str]] = None) -> str:
    """Response post-processing, reference-exact order (ref:
    core/backend/llm.go:192-240 Finetune): echo → cutstrings (regex delete)
    → extract_regex (concatenate first match of each; replaces text if any)
    → trimspace (TrimPrefix then strip) → trimsuffix (TrimSuffix then
    strip)."""
    if echo_prompt:
        text = echo_prompt + text
    for pattern in cutstrings or []:
        text = re.sub(pattern, "", text)
    extracted = ""
    for pattern in extract_regex or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            extracted += m.group(0)
    if extracted:
        text = extracted
    for s in trimspace or []:
        if s and text.startswith(s):
            text = text[len(s):]
        text = text.strip()
    for s in trimsuffix or []:
        if s and text.endswith(s):
            text = text[: -len(s)]
        text = text.strip()
    return text
