"""Parse LLM output back into tool calls + text content.

Capability counterpart of the reference's function-call response parsing
(ref: pkg/functions/parse.go — FunctionsConfig options :16-60,
ParseFunctionCall :221-338 with regex/JSON recovery and parallel calls,
text-content capture ParseTextContent :163, cleanup rules CleanupLLMResult
:149). Clean-room Python implementation over the same YAML config surface
(config/model_config.py FunctionsConfig).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..config.model_config import FunctionsConfig


@dataclass
class FuncCallResults:
    name: str = ""
    arguments: str = ""  # JSON string (OpenAI wire format)


def cleanup_llm_result(text: str, cfg: FunctionsConfig) -> str:
    """Apply replace_llm_results regex rules (ref: parse.go:149-161)."""
    for rule in cfg.replace_llm_results or []:
        key = rule.get("key", "")
        value = rule.get("value", "")
        if key:
            text = re.sub(key, value, text)
    return text


def parse_text_content(text: str, cfg: FunctionsConfig) -> str:
    """Extract free-text content via capture_llm_results regexes
    (ref: parse.go ParseTextContent :163-186)."""
    for pattern in cfg.capture_llm_results or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            return (m.group(1) if m.groups() else m.group(0)).strip()
    return ""


def _replace_results(text: str, cfg: FunctionsConfig) -> str:
    for rule in cfg.replace_function_results or []:
        key = rule.get("key", "")
        value = rule.get("value", "")
        if key:
            text = re.sub(key, value, text)
    return text


_LLAMA31_CALL = re.compile(
    r"<function=(\w+)>(.*?)</function>", re.DOTALL
)


def _json_candidates(text: str) -> list[str]:
    """Find balanced top-level JSON objects/arrays in free text."""
    out = []
    depth = 0
    start = -1
    in_str = False
    esc = False
    for i, ch in enumerate(text):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "{[":
            if depth == 0:
                start = i
            depth += 1
        elif ch in "}]":
            if depth > 0:
                depth -= 1
                if depth == 0 and start >= 0:
                    out.append(text[start:i + 1])
                    start = -1
    return out


def parse_function_call(text: str, cfg: FunctionsConfig) -> list[FuncCallResults]:
    """Recover tool calls from model output (ref: parse.go
    ParseFunctionCall :221-338). Handles: single JSON object, JSON array of
    calls (parallel), llama3.1 <function=…> syntax, json_regex_match
    extraction, response_regex named groups, and argument-as-object or
    argument-as-string forms."""
    name_key = cfg.function_name_key or "name"
    args_key = cfg.function_arguments_key or "arguments"

    text = _replace_results(text, cfg)
    results: list[FuncCallResults] = []

    # llama 3.1 native syntax
    for m in _LLAMA31_CALL.finditer(text):
        results.append(FuncCallResults(name=m.group(1),
                                       arguments=m.group(2).strip()))
    if results:
        return results

    # response_regex with named groups (ref: parse.go:287-317)
    for pattern in cfg.response_regex or []:
        for m in re.finditer(pattern, text, re.DOTALL):
            gd = m.groupdict()
            if name_key in gd:
                args = gd.get(args_key, "") or "{}"
                results.append(FuncCallResults(name=gd[name_key],
                                               arguments=args))
    if results:
        return results

    # json_regex_match: extract the JSON blob first (ref: parse.go:240-255)
    candidates: list[str] = []
    for pattern in cfg.json_regex_match or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            candidates.append(m.group(1) if m.groups() else m.group(0))
            break
    if not candidates:
        candidates = _json_candidates(text)

    for cand in candidates:
        try:
            obj = json.loads(cand)
        except ValueError:
            continue
        calls = obj if isinstance(obj, list) else [obj]
        for c in calls:
            if not isinstance(c, dict):
                continue
            name = c.get(name_key)
            if not isinstance(name, str) or not name:
                continue
            args = c.get(args_key, {})
            if isinstance(args, str):
                args_str = args
            else:
                args_str = json.dumps(args)
            results.append(FuncCallResults(name=name, arguments=args_str))
        if results:
            break
    return results


def apply_finetune(text: str, *, echo_prompt: str = "",
                   cutstrings: Optional[list[str]] = None,
                   extract_regex: Optional[list[str]] = None,
                   trimspace: Optional[list[str]] = None,
                   trimsuffix: Optional[list[str]] = None) -> str:
    """Response post-processing, reference-exact order (ref:
    core/backend/llm.go:192-240 Finetune): echo → cutstrings (regex delete)
    → extract_regex (concatenate first match of each; replaces text if any)
    → trimspace (TrimPrefix then strip) → trimsuffix (TrimSuffix then
    strip)."""
    if echo_prompt:
        text = echo_prompt + text
    for pattern in cutstrings or []:
        text = re.sub(pattern, "", text)
    extracted = ""
    for pattern in extract_regex or []:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            extracted += m.group(0)
    if extracted:
        text = extracted
    for s in trimspace or []:
        if s and text.startswith(s):
            text = text[len(s):]
        text = text.strip()
    for s in trimsuffix or []:
        if s and text.endswith(s):
            text = text[: -len(s)]
        text = text.strip()
    return text


class FinetuneStream:
    """Incremental ``apply_finetune`` for SSE streaming.

    The reference only post-processes NON-streaming responses (Finetune
    is called from ComputeChoices / handleQuestion, never from the token
    callback — ref: core/http/endpoints/openai/inference.go:58,
    chat.go:516,552). Here streamed output is post-processed too, so a
    model YAML with ``cutstrings:`` behaves identically in both modes:

    - ``cutstrings`` / ``extract_regex`` need the whole text; with
      either set the stream is buffered and emitted as ONE final chunk
      (semantics over latency — the same degeneration the tool-call
      streaming path already accepts).
    - ``echo`` / ``trimspace`` / ``trimsuffix`` stream incrementally: a
      start-phase state machine resolves the prefix trims, a
      conservative tail holdback (suffix candidates + adjacent
      whitespace, like stop-string withholding) keeps the final trims
      possible, and ``finish()`` reconciles against ``apply_finetune``
      on the full raw text, so the concatenated stream is bit-identical
      to the non-streaming result.
    """

    def __init__(self, *, echo_prompt: str = "",
                 cutstrings: Optional[list[str]] = None,
                 extract_regex: Optional[list[str]] = None,
                 trimspace: Optional[list[str]] = None,
                 trimsuffix: Optional[list[str]] = None) -> None:
        self._kw = dict(echo_prompt=echo_prompt, cutstrings=cutstrings,
                        extract_regex=extract_regex, trimspace=trimspace,
                        trimsuffix=trimsuffix)
        self._buffer_all = bool(cutstrings or extract_regex)
        self._trimspace = list(trimspace or [])
        self._trimsuffix = list(trimsuffix or [])
        self._raw: list[str] = []  # every raw span, for reconciliation
        # echo text flows THROUGH the trim pipeline like apply_finetune
        # prepends it before trimming (a trimspace entry may well match
        # the echoed prompt); it is seeded into the stream, not into
        # _raw — finish()'s apply_finetune re-adds it via echo_prompt
        self._start_done = not (self._trimspace or self._trimsuffix)
        self._head = "" if self._buffer_all else echo_prompt
        self._body = ""  # resolved text not yet emitted (tail holdback)
        if self._start_done:
            self._body, self._head = self._head, ""
        self._emitted = ""  # exactly what the caller has streamed so far

    def _resolve_start(self) -> Optional[str]:
        """Run the prefix side of the trim pipeline on the buffered
        head. None = undecided (a trim string may still be completed by
        future text, or we are inside a leading-whitespace run; a
        stream that ENDS undecided is settled by finish()'s
        apply_finetune reconciliation). Each trimsuffix entry's strip()
        ALSO trims the leading side, so with any trimsuffix configured
        the leading whitespace must be swallowed here too."""
        cur = self._head
        for s in self._trimspace:
            if s and len(cur) < len(s) and s.startswith(cur):
                return None  # proper prefix: hold
            if s and cur.startswith(s):
                cur = cur[len(s):]
            cur = cur.lstrip()
            if not cur:
                return None  # still swallowing leading whitespace
        if self._trimsuffix:
            cur = cur.lstrip()
            if not cur:
                return None
        return cur

    def _holdback_boundary(self, text: str) -> int:
        """Largest emit-safe prefix length: everything past it could
        still be consumed by the trailing-trim pipeline (each trimsuffix
        entry removes one suffix then strips; trimspace entries strip
        trailing whitespace)."""
        b = len(text)
        while b > 0 and text[b - 1].isspace():
            b -= 1
        for s in reversed(self._trimsuffix):
            b = max(0, b - len(s))
            while b > 0 and text[b - 1].isspace():
                b -= 1
        return b

    def feed(self, span: str) -> str:
        """Add raw model text; returns the text safe to stream now."""
        if not span:
            return ""
        self._raw.append(span)
        if self._buffer_all:
            return ""
        out = ""
        if not self._start_done:
            self._head += span
            resolved = self._resolve_start()
            if resolved is None:
                return out
            self._start_done = True
            self._body += resolved
        else:
            self._body += span
        b = self._holdback_boundary(self._body)
        if b > 0:
            out += self._body[:b]
            self._emitted += self._body[:b]
            self._body = self._body[b:]
        return out

    def finish(self) -> str:
        """Final span: whatever of the canonical post-processed text has
        not been streamed yet."""
        final = apply_finetune("".join(self._raw), **self._kw)
        if final.startswith(self._emitted):
            return final[len(self._emitted):]
        # conservative holdback should make this unreachable; emitting
        # nothing further keeps the stream a prefix of the canonical
        # text rather than diverging from it
        return ""
