"""JSON-schema → GBNF grammar generation.

Capability counterpart of the reference's grammar generators
(ref: pkg/functions/grammars/json_schema.go:220 JSONSchemaConverter,
bnf_rules.go base rules, rules.go grammar-option assembly;
llama31_schema.go for the <function=…> syntax). Clean-room: rule naming
and structure follow the GBNF idiom, not the Go code.

Two entry points:
- ``schema_to_gbnf(schema)``: any JSON schema → grammar for one conforming
  JSON document (used by response_format json_schema,
  ref: core/http/endpoints/openai/chat.go:216-246).
- ``functions_grammar(functions, opts)``: OpenAI tool definitions → grammar
  for {"name": …, "arguments": …} calls, with the reference's options:
  parallel calls (array form), mixed text+JSON mode, prefix, llama 3.1
  <function=name>{args}</function> syntax (ref: parse.go:16-60
  FunctionsConfig grammar options).
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

SPACE_RULE = '" "?'

BASE_RULES = {
    "space": SPACE_RULE,
    "string": r'"\"" ( [^"\\\x00-\x1f] | "\\" (["\\/bfnrt] | "u" [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F]) )* "\"" space',
    "number": '("-"? ([0-9] | [1-9] [0-9]*)) ("." [0-9]+)? ([eE] [-+]? [0-9]+)? space',
    "integer": '("-"? ([0-9] | [1-9] [0-9]*)) space',
    "boolean": '("true" | "false") space',
    "null": '"null" space',
    "value": "object | array | string | number | boolean | null",
    "object": '"{" space ( string ":" space value ("," space string ":" space value)* )? "}" space',
    "array": '"[" space ( value ("," space value)* )? "]" space',
    "freestring": r'( [^\x00] )*',
}

_INVALID_RULE_CHARS = re.compile(r"[^a-zA-Z0-9-]+")


def _fmt_literal(s: str) -> str:
    esc = s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return f'"{esc}"'


class SchemaConverter:
    def __init__(self, prop_order: Optional[list[str]] = None) -> None:
        self.prop_order = {k: i for i, k in enumerate(prop_order or [])}
        self.rules: dict[str, str] = {"space": SPACE_RULE}
        self.defs: dict[str, Any] = {}

    def _add_rule(self, name: str, rule: str) -> str:
        key = _INVALID_RULE_CHARS.sub("-", name) or "rule"
        if key in self.rules:
            if self.rules[key] == rule:
                return key
            i = 0
            while f"{key}{i}" in self.rules and self.rules[f"{key}{i}"] != rule:
                i += 1
            key = f"{key}{i}"
        self.rules[key] = rule
        return key

    def _base(self, name: str) -> str:
        return self._add_rule(name, BASE_RULES[name])

    def visit(self, schema: Any, name: str = "root") -> str:
        """Emit rules for ``schema``; returns the rule name."""
        if schema is None or schema is True or schema == {}:
            for dep in ("value", "object", "array", "string", "number",
                        "boolean", "null"):
                self._base(dep)
            return self._add_rule(name, "value")
        if not isinstance(schema, dict):
            raise ValueError(f"unsupported schema node: {schema!r}")

        for defs_key in ("$defs", "definitions"):
            if defs_key in schema:
                self.defs.update(schema[defs_key])

        if "$ref" in schema:
            ref = schema["$ref"]
            target = ref.split("/")[-1]
            if target not in self.defs:
                raise ValueError(f"unresolvable $ref {ref}")
            return self.visit(self.defs[target], target)

        if "const" in schema:
            return self._add_rule(
                name, _fmt_literal(json.dumps(schema["const"])) + " space"
            )
        if "enum" in schema:
            alts = " | ".join(
                _fmt_literal(json.dumps(v)) for v in schema["enum"]
            )
            return self._add_rule(name, f"({alts}) space")
        for comb in ("oneOf", "anyOf"):
            if comb in schema:
                alts = [
                    self.visit(sub, f"{name}-{i}")
                    for i, sub in enumerate(schema[comb])
                ]
                return self._add_rule(name, " | ".join(alts))

        t = schema.get("type")
        if isinstance(t, list):
            alts = [
                self.visit({**schema, "type": tt}, f"{name}-{tt}")
                for tt in t
            ]
            return self._add_rule(name, " | ".join(alts))

        if t == "object" or (t is None and "properties" in schema):
            return self._object(schema, name)
        if t == "array" or (t is None and "items" in schema):
            return self._array(schema, name)
        if t == "string":
            return self._string(schema, name)
        if t in ("number", "integer", "boolean", "null"):
            return self._add_rule(name, self._base(t))
        # unconstrained
        for dep in ("value", "object", "array", "string", "number",
                    "boolean", "null"):
            self._base(dep)
        return self._add_rule(name, "value")

    def _string(self, schema: dict, name: str) -> str:
        fmt = schema.get("format")
        if fmt == "date":
            return self._add_rule(
                name,
                '"\\"" [0-9] [0-9] [0-9] [0-9] "-" [0-9] [0-9] "-" [0-9] [0-9] "\\"" space',
            )
        return self._add_rule(name, self._base("string"))

    def _object(self, schema: dict, name: str) -> str:
        props = schema.get("properties") or {}
        required = set(schema.get("required") or props.keys())

        def order_key(item):
            k = item[0]
            return (self.prop_order.get(k, len(self.prop_order)), k)

        items = sorted(props.items(), key=order_key)
        if not items:
            return self._add_rule(name, self._base("object"))

        kvs: dict[str, str] = {}
        for k, sub in items:
            sub_rule = self.visit(sub, f"{name}-{k}")
            kvs[k] = f'{_fmt_literal(json.dumps(k))} space ":" space {sub_rule}'

        req = [k for k, _ in items if k in required]
        opt = [k for k, _ in items if k not in required]

        # optional tails: opt-i matches any ordered non-empty subset of
        # opt[i:], comma-separated (the canonical GBNF converter scheme)
        tail_rules: list[str] = []
        for i in range(len(opt) - 1, -1, -1):
            expr = kvs[opt[i]]
            if tail_rules:
                # start at opt[i] (optionally continuing) or skip to a later one
                expr = (f'{expr} ("," space {tail_rules[-1]})? '
                        f'| {tail_rules[-1]}')
            rule_name = self._add_rule(f"{name}-opt{i}", expr)
            tail_rules.append(rule_name)
        opt_entry = tail_rules[-1] if tail_rules else ""

        parts: list[str] = ['"{" space']
        for j, k in enumerate(req):
            if j:
                parts.append('"," space')
            parts.append(kvs[k])
        if opt_entry:
            if req:
                parts.append(f'("," space {opt_entry})?')
            else:
                parts.append(f"({opt_entry})?")
        parts.append('"}" space')
        return self._add_rule(name, " ".join(parts))

    def _array(self, schema: dict, name: str) -> str:
        items = schema.get("items")
        if isinstance(items, list):  # tuple validation
            rules = [
                self.visit(sub, f"{name}-{i}") for i, sub in enumerate(items)
            ]
            body = ' "," space '.join(rules)
            return self._add_rule(name, f'"[" space {body} "]" space')
        item_rule = self.visit(items, f"{name}-item")
        min_items = int(schema.get("minItems") or 0)
        rep = f'{item_rule} ("," space {item_rule})*'
        if min_items == 0:
            rep = f"({rep})?"
        return self._add_rule(name, f'"[" space {rep} "]" space')

    def format_grammar(self, root_rule: str = "root") -> str:
        lines = []
        if "root" not in self.rules:
            lines.append(f"root ::= {root_rule}")
        for k, v in self.rules.items():
            lines.append(f"{k} ::= {v}")
        return "\n".join(lines) + "\n"


def schema_to_gbnf(schema: Any, prop_order: Optional[list[str]] = None) -> str:
    c = SchemaConverter(prop_order)
    c.visit(schema if schema is not None else None, "root")
    return c.format_grammar()


# ---------------------------------------------------------------------------
# tool-calling grammars (ref: pkg/functions/grammars/rules.go options)
# ---------------------------------------------------------------------------


def _tool_call_schema(functions: list[dict],
                      name_key: str = "name",
                      args_key: str = "arguments") -> dict:
    """One-of over {name, arguments} objects, one alternative per tool
    (ref: pkg/functions/function_structure.go JSONFunctionStructure)."""
    alts = []
    for fn in functions:
        f = fn.get("function", fn)  # accept OpenAI tools[] or functions[]
        alts.append({
            "type": "object",
            "properties": {
                name_key: {"const": f["name"]},
                args_key: f.get("parameters") or {},
            },
            "required": [name_key, args_key],
        })
    return {"oneOf": alts} if len(alts) != 1 else alts[0]


def functions_grammar(
    functions: list[dict],
    *,
    parallel_calls: bool = False,
    mixed_mode: bool = False,
    prefix: str = "",
    expect_strings_after_json: bool = False,
    prop_order: Optional[list[str]] = None,
    name_key: str = "name",
    args_key: str = "arguments",
) -> str:
    """GBNF for tool calls (ref: rules.go:  disable-parallel / maybe-string /
    prefix / strings-after-json grammar options)."""
    c = SchemaConverter(prop_order or [name_key, args_key])
    call = c.visit(_tool_call_schema(functions, name_key, args_key), "call")
    if parallel_calls:
        root = f'( {call} | "[" space {call} ("," space {call})* "]" space )'
    else:
        root = call
    if prefix:
        root = f"{_fmt_literal(prefix)} {root}"
    if expect_strings_after_json:
        c.rules["freestring"] = BASE_RULES["freestring"]
        root = f"{root} freestring?"
    if mixed_mode:
        c.rules["freestring"] = BASE_RULES["freestring"]
        root = f"( {root} | freestring )"
    c.rules["root"] = root
    return c.format_grammar()


def llama31_functions_grammar(functions: list[dict]) -> str:
    """Llama-3.1 native tool syntax: <function=name>{args}</function>
    (ref: pkg/functions/grammars/llama31_schema.go:281)."""
    c = SchemaConverter()
    alts = []
    for i, fn in enumerate(functions):
        f = fn.get("function", fn)
        args = c.visit(f.get("parameters") or {}, f"args-{i}")
        alts.append(
            f'"<function=" {_fmt_literal(f["name"])} ">" {args} "</function>"'
        )
    c.rules["root"] = " | ".join(f"( {a} )" for a in alts)
    return c.format_grammar()
