"""GBNF grammar parser + incremental byte-level recognizer.

Capability counterpart of llama.cpp's grammar engine that the reference
relies on for constrained decoding (ref: pkg/functions/grammars/*.go emits
GBNF; the C++ side consumes it via llama.cpp's `llama_grammar` — vendored,
not in the reference tree). This is a clean-room implementation:

- `parse_gbnf` turns GBNF text into rules of alternates of symbols
  (literal bytes, char classes, rule refs); `*`/`+`/`?` repetitions are
  rewritten into auxiliary recursive rules, mirroring how GBNF defines them.
- `GrammarMatcher` is a pushdown recognizer: a match state is a frozenset of
  stacks (tuples of pending symbols); `accept_char` advances every stack.
  This matches llama.cpp's "set of stacks" representation, which handles the
  nondeterminism of alternates without backtracking.

The matcher is intentionally transport-free: grammars/constrain.py builds
per-step token masks from it for the TPU decode loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Union


@dataclass(frozen=True)
class Lit:
    ch: str  # exactly one unicode char


@dataclass(frozen=True)
class CharClass:
    ranges: tuple[tuple[str, str], ...]  # inclusive (lo, hi) pairs
    negated: bool = False

    def matches(self, ch: str) -> bool:
        hit = any(lo <= ch <= hi for lo, hi in self.ranges)
        return (not hit) if self.negated else hit


@dataclass(frozen=True)
class Ref:
    name: str


Symbol = Union[Lit, CharClass, Ref]
Alternate = tuple[Symbol, ...]


class Grammar:
    def __init__(self, rules: dict[str, list[Alternate]], root: str = "root"):
        if root not in rules:
            raise ValueError(f"grammar has no '{root}' rule")
        self.rules = rules
        self.root = root


class GBNFError(ValueError):
    pass


class _Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.i = 0
        self.rules: dict[str, list[Alternate]] = {}
        self.aux = 0

    # --- lexing helpers ---

    def _ws(self, newlines: bool = True) -> None:
        while self.i < len(self.text):
            c = self.text[self.i]
            if c == "#":  # comment to EOL
                while self.i < len(self.text) and self.text[self.i] != "\n":
                    self.i += 1
            elif c in " \t\r" or (newlines and c == "\n"):
                self.i += 1
            else:
                break

    def _peek(self) -> str:
        return self.text[self.i] if self.i < len(self.text) else ""

    def _name(self) -> str:
        j = self.i
        while j < len(self.text) and (
            self.text[j].isalnum() or self.text[j] in "-_"
        ):
            j += 1
        if j == self.i:
            raise GBNFError(f"expected name at {self.i}")
        name, self.i = self.text[self.i:j], j
        return name

    def _escaped_char(self) -> str:
        c = self.text[self.i]
        self.i += 1
        if c != "\\":
            return c
        e = self.text[self.i]
        self.i += 1
        table = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
                 "/": "/", "'": "'", "[": "[", "]": "]"}
        if e in table:
            return table[e]
        if e == "x":
            h = self.text[self.i:self.i + 2]
            self.i += 2
            return chr(int(h, 16))
        if e == "u":
            h = self.text[self.i:self.i + 4]
            self.i += 4
            return chr(int(h, 16))
        if e == "U":
            h = self.text[self.i:self.i + 8]
            self.i += 8
            return chr(int(h, 16))
        raise GBNFError(f"bad escape \\{e}")

    # --- grammar parsing ---

    def parse(self) -> Grammar:
        self._ws()
        while self.i < len(self.text):
            name = self._name()
            self._ws()
            if self.text[self.i:self.i + 3] == "::=":
                self.i += 3
            else:
                raise GBNFError(f"expected '::=' after rule '{name}'")
            alts = self._alternates(name)
            if name in self.rules:
                self.rules[name].extend(alts)
            else:
                self.rules[name] = alts
            self._ws()
        return Grammar(self.rules)

    def _alternates(self, rulename: str) -> list[Alternate]:
        alts = [self._sequence(rulename)]
        self._ws(newlines=False)
        while self._peek() == "|":
            self.i += 1
            alts.append(self._sequence(rulename))
            self._ws(newlines=False)
        return alts

    def _sequence(self, rulename: str) -> Alternate:
        seq: list[Symbol] = []
        while True:
            self._ws(newlines=False)
            c = self._peek()
            if c == "" or c in "|)\n":
                break
            sym = self._symbol(rulename)
            self._ws(newlines=False)
            c = self._peek()
            if c and c in "*+?{":
                sym = self._apply_repeat(rulename, sym, c)
            seq.append(sym)
        return tuple(seq)

    def _symbol(self, rulename: str) -> Symbol:
        c = self._peek()
        if c == '"':
            self.i += 1
            chars: list[str] = []
            while self._peek() != '"':
                if self.i >= len(self.text):
                    raise GBNFError("unterminated string literal")
                chars.append(self._escaped_char())
            self.i += 1
            if len(chars) == 1:
                return Lit(chars[0])
            # multi-char literal becomes an aux rule of single chars
            name = self._aux_name(rulename)
            self.rules[name] = [tuple(Lit(ch) for ch in chars)]
            return Ref(name)
        if c == "[":
            self.i += 1
            negated = False
            if self._peek() == "^":
                negated = True
                self.i += 1
            ranges: list[tuple[str, str]] = []
            while self._peek() != "]":
                if self.i >= len(self.text):
                    raise GBNFError("unterminated char class")
                lo = self._escaped_char()
                hi = lo
                if self._peek() == "-" and self.text[self.i + 1] != "]":
                    self.i += 1
                    hi = self._escaped_char()
                ranges.append((lo, hi))
            self.i += 1
            return CharClass(tuple(ranges), negated)
        if c == "(":
            self.i += 1
            name = self._aux_name(rulename)
            # placeholder so recursive refs resolve
            self.rules[name] = []
            alts = self._alternates(name)
            self._ws()
            if self._peek() != ")":
                raise GBNFError("expected ')'")
            self.i += 1
            self.rules[name] = alts
            return Ref(name)
        if c == ".":
            self.i += 1
            return CharClass((("\x00", "\U0010ffff"),), False)
        return Ref(self._name())

    def _apply_repeat(self, rulename: str, sym: Symbol, op: str) -> Symbol:
        self.i += 1
        if op == "{":  # {m}, {m,}, {m,n}
            j = self.text.index("}", self.i)
            body = self.text[self.i:j]
            self.i = j + 1
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s.strip() else 0
                hi = int(hi_s) if hi_s.strip() else None
            else:
                lo = hi = int(body)
            return self._bounded(rulename, sym, lo, hi)
        if op == "?":
            name = self._aux_name(rulename)
            self.rules[name] = [(sym,), ()]
            return Ref(name)
        if op == "*":
            name = self._aux_name(rulename)
            self.rules[name] = [(sym, Ref(name)), ()]
            return Ref(name)
        # op == "+"
        name = self._aux_name(rulename)
        star = self._aux_name(rulename)
        self.rules[star] = [(sym, Ref(star)), ()]
        self.rules[name] = [(sym, Ref(star))]
        return Ref(name)

    def _bounded(self, rulename: str, sym: Symbol, lo: int,
                 hi: Optional[int]) -> Symbol:
        name = self._aux_name(rulename)
        if hi is None:
            star = self._aux_name(rulename)
            self.rules[star] = [(sym, Ref(star)), ()]
            self.rules[name] = [tuple([sym] * lo) + (Ref(star),)]
        else:
            alts = [tuple([sym] * n) for n in range(lo, hi + 1)]
            self.rules[name] = alts or [()]
        return Ref(name)

    def _aux_name(self, rulename: str) -> str:
        self.aux += 1
        return f"{rulename}-aux{self.aux}"


def parse_gbnf(text: str) -> Grammar:
    return _Parser(text).parse()


# ---------------------------------------------------------------------------
# pushdown recognizer
# ---------------------------------------------------------------------------

Stack = tuple[Symbol, ...]  # symbols still to match; stack[0] is the top
MatchState = frozenset  # of Stack


class GrammarMatcher:
    """Incremental recognizer over unicode chars (one char at a time)."""

    def __init__(self, grammar: Grammar) -> None:
        self.grammar = grammar
        self._accept_cache: dict[tuple[MatchState, str], MatchState] = {}

    def initial_state(self) -> MatchState:
        stacks: set[Stack] = set()
        for alt in self.grammar.rules[self.grammar.root]:
            self._expand(tuple(alt), stacks, set())
        return frozenset(stacks)

    def _expand(self, stack: Stack, out: set[Stack],
                seen: set[Stack]) -> None:
        """Expand leading Refs until the top is a terminal (or empty)."""
        if stack in seen:
            return
        seen.add(stack)
        if not stack or isinstance(stack[0], (Lit, CharClass)):
            out.add(stack)
            return
        ref = stack[0]
        for alt in self.grammar.rules[ref.name]:
            self._expand(tuple(alt) + stack[1:], out, seen)

    def accept_char(self, state: MatchState, ch: str) -> MatchState:
        key = (state, ch)
        hit = self._accept_cache.get(key)
        if hit is not None:
            return hit
        nxt: set[Stack] = set()
        seen: set[Stack] = set()
        for stack in state:
            if not stack:
                continue
            top = stack[0]
            ok = top.ch == ch if isinstance(top, Lit) else top.matches(ch)
            if ok:
                self._expand(stack[1:], nxt, seen)
        res = frozenset(nxt)
        self._accept_cache[key] = res
        return res

    def accept_string(self, state: MatchState, s: str) -> MatchState:
        for ch in s:
            if not state:
                return state
            state = self.accept_char(state, ch)
        return state

    @staticmethod
    def is_dead(state: MatchState) -> bool:
        return len(state) == 0

    @staticmethod
    def can_end(state: MatchState) -> bool:
        return any(len(stack) == 0 for stack in state)

    def matches(self, text: str) -> bool:
        st = self.accept_string(self.initial_state(), text)
        return self.can_end(st)
