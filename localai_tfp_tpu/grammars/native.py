"""ctypes binding for the native GBNF mask engine (native/gbnf_mask.cpp).

Same contract as grammars/constrain.py GrammarConstraint (the engine
accepts either). States are plain ints interned inside the C++ engine, so
the per-token host cost is one FFI call for advance and one for the mask
fill — the decode scheduler's grammar budget (SURVEY.md §7 hard part #3).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..config import knobs
from ..native import load_library


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.gbnf_new.restype = c.c_void_p
    lib.gbnf_new.argtypes = [c.c_char_p, c.c_char_p, c.c_int]
    lib.gbnf_free.argtypes = [c.c_void_p]
    lib.gbnf_set_vocab.argtypes = [c.c_void_p, c.c_int]
    lib.gbnf_add_token.argtypes = [c.c_void_p, c.c_int, c.c_char_p, c.c_int]
    lib.gbnf_add_eos.argtypes = [c.c_void_p, c.c_int]
    lib.gbnf_initial.restype = c.c_int
    lib.gbnf_initial.argtypes = [c.c_void_p]
    lib.gbnf_advance.restype = c.c_int
    lib.gbnf_advance.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.gbnf_accept_text.restype = c.c_int
    lib.gbnf_accept_text.argtypes = [c.c_void_p, c.c_int, c.c_char_p,
                                     c.c_int]
    lib.gbnf_can_end.restype = c.c_int
    lib.gbnf_can_end.argtypes = [c.c_void_p, c.c_int]
    lib.gbnf_is_dead.restype = c.c_int
    lib.gbnf_is_dead.argtypes = [c.c_void_p, c.c_int]
    lib.gbnf_mask.argtypes = [c.c_void_p, c.c_int,
                              np.ctypeslib.ndpointer(np.uint8)]
    return lib


def available() -> bool:
    if not knobs.flag("LOCALAI_NATIVE_GBNF"):
        return False
    return load_library("gbnf") is not None


class NativeGrammarConstraint:
    """Drop-in for GrammarConstraint backed by the C++ engine."""

    def __init__(self, gbnf_text: str, tokenizer) -> None:
        lib = load_library("gbnf")
        if lib is None:
            raise RuntimeError("native gbnf library unavailable")
        self._lib = _bind(lib)
        err = ctypes.create_string_buffer(256)
        self._h = self._lib.gbnf_new(gbnf_text.encode(), err, 256)
        if not self._h:
            raise ValueError(f"gbnf parse error: {err.value.decode()}")
        self.vocab_size = tokenizer.vocab_size
        self._lib.gbnf_set_vocab(self._h, self.vocab_size)
        for tid in range(self.vocab_size):
            try:
                s = tokenizer.decode([tid])
            except (KeyError, IndexError, ValueError,
                    UnicodeDecodeError):
                continue  # special/control token: not grammar text
            if s and "�" not in s:
                b = s.encode("utf-8")
                self._lib.gbnf_add_token(self._h, tid, b, len(b))
        for e in getattr(tokenizer, "eos_ids", ()) or ():
            self._lib.gbnf_add_eos(self._h, int(e))
        self._mask_cache: dict[int, np.ndarray] = {}

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.gbnf_free(h)
            self._h = None

    # --- engine contract (engine/engine.py GenRequest.constraint) ---

    def initial_state(self) -> int:
        return self._lib.gbnf_initial(self._h)

    def advance(self, state: int, token_id: int) -> int:
        return self._lib.gbnf_advance(self._h, state, token_id)

    def next_mask(self, state: int) -> np.ndarray:
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        out = np.zeros(self.vocab_size, np.uint8)
        self._lib.gbnf_mask(self._h, state, out)
        mask = out.astype(bool)
        if len(self._mask_cache) < 4096:
            self._mask_cache[state] = mask
        return mask

    # --- test/introspection helpers mirroring GrammarMatcher ---

    def accept_text(self, state: int, text: str) -> int:
        b = text.encode("utf-8")
        return self._lib.gbnf_accept_text(self._h, state, b, len(b))

    def can_end(self, state: int) -> bool:
        return bool(self._lib.gbnf_can_end(self._h, state))

    def is_dead(self, state: int) -> bool:
        return bool(self._lib.gbnf_is_dead(self._h, state))

    def matches(self, text: str) -> bool:
        st = self.accept_text(self.initial_state(), text)
        return self.can_end(st)


def make_constraint(gbnf_text: str, tokenizer,
                    triggers: Optional[list[str]] = None):
    """Factory: native engine when built, Python fallback otherwise.
    ``triggers`` (ref: grpc-server.cpp:2441-2454 grammar_lazy) gates the
    grammar behind the first occurrence of a trigger word in the
    generated text."""
    if available():
        try:
            inner = NativeGrammarConstraint(gbnf_text, tokenizer)
        except (RuntimeError, ValueError):
            inner = None
    else:
        inner = None
    if inner is None:
        from .constrain import GrammarConstraint

        inner = GrammarConstraint.from_gbnf(gbnf_text, tokenizer)
    live = [t for t in (triggers or []) if t]
    if live:
        from .constrain import LazyGrammarConstraint

        return LazyGrammarConstraint(inner, live, tokenizer)
    return inner
