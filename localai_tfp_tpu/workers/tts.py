"""Text-to-speech worker (ref: the reference ships piper/coqui/kokoro/bark
TTS backends — backend/go/tts/piper.go, backend/python/coqui|kokoro|bark —
served at POST /tts and /v1/text-to-speech/:voice_id).

This backend is a dependency-free formant synthesizer: grapheme→phoneme by
rule, each phoneme rendered from a 3-formant source-filter model (voiced
glottal pulse train or fricative noise, shaped by formant resonators), all
synthesized as one vectorized JAX program. It is intentionally a classical
DSP voice — the serving contract (text in, WAV out, voice/speed knobs) is
the parity surface; neural acoustic models can drop in behind the same
worker later.
"""

from __future__ import annotations

import logging
import os
import wave

import numpy as np

from .base import Backend, ModelLoadOptions, Result, StatusResponse

log = logging.getLogger(__name__)

SR = 16000

# (F1, F2, F3, voiced, duration_s) per phoneme — classic formant tables
PHONEMES: dict[str, tuple[float, float, float, bool, float]] = {
    "a": (730, 1090, 2440, True, 0.14),
    "e": (530, 1840, 2480, True, 0.12),
    "i": (270, 2290, 3010, True, 0.11),
    "o": (570, 840, 2410, True, 0.14),
    "u": (300, 870, 2240, True, 0.13),
    "m": (250, 1000, 2200, True, 0.08),
    "n": (250, 1700, 2600, True, 0.07),
    "l": (360, 1300, 2700, True, 0.07),
    "r": (490, 1350, 1690, True, 0.08),
    "w": (300, 610, 2200, True, 0.07),
    "y": (270, 2100, 3000, True, 0.06),
    "b": (200, 800, 2200, True, 0.04),
    "d": (200, 1700, 2600, True, 0.04),
    "g": (200, 1300, 2200, True, 0.05),
    "p": (400, 1100, 2300, False, 0.05),
    "t": (400, 1800, 2600, False, 0.04),
    "k": (400, 1400, 2300, False, 0.05),
    "s": (200, 5000, 7000, False, 0.09),
    "z": (200, 4500, 6500, True, 0.08),
    "f": (200, 4000, 6000, False, 0.08),
    "v": (200, 3500, 5500, True, 0.07),
    "h": (500, 1500, 2500, False, 0.05),
    " ": (0, 0, 0, False, 0.10),
}
ALIASES = {"c": "k", "q": "k", "x": "s", "j": "y"}


def _g2p(text: str) -> list[str]:
    out = []
    for ch in text.lower():
        if ch in PHONEMES:
            out.append(ch)
        elif ch in ALIASES:
            out.append(ALIASES[ch])
        elif ch.isspace() or ch in ".,;:!?-":
            out.append(" ")
    return out or [" "]


def _render(phonemes: list[str], pitch_hz: float, speed: float) -> np.ndarray:
    """Source-filter render: per-phoneme formant sinusoid bank with pitch
    modulation; noise excitation for unvoiced phonemes."""
    rng = np.random.default_rng(0)
    chunks = []
    t_off = 0.0
    for ph in phonemes:
        f1, f2, f3, voiced, dur = PHONEMES[ph]
        dur /= speed
        n = max(int(dur * SR), 1)
        t = np.arange(n) / SR
        if f1 == 0:  # silence
            chunks.append(np.zeros(n, np.float32))
            t_off += dur
            continue
        env = np.minimum(1.0, np.minimum(t / 0.015, (dur - t) / 0.02))
        env = np.clip(env, 0.0, 1.0)
        if voiced:
            # pitch with gentle declination + vibrato
            f0 = pitch_hz * (1.0 - 0.05 * t_off) * (
                1.0 + 0.01 * np.sin(2 * np.pi * 5 * (t_off + t)))
            phase = 2 * np.pi * np.cumsum(f0) / SR
            src = np.zeros(n)
            for k, amp in ((1, 1.0), (2, 0.5), (3, 0.25), (4, 0.12)):
                src += amp * np.sin(k * phase)
            sig = np.zeros(n)
            for fc, amp in ((f1, 1.0), (f2, 0.7), (f3, 0.3)):
                mod = np.sin(2 * np.pi * fc * t)
                sig += amp * src * mod
        else:
            noise = rng.standard_normal(n)
            sig = np.zeros(n)
            for fc, amp in ((f2, 1.0), (f3, 0.7)):
                mod = np.sin(2 * np.pi * fc * t)
                sig += amp * noise * mod
        chunks.append((sig * env).astype(np.float32))
        t_off += dur
    audio = np.concatenate(chunks)
    peak = np.max(np.abs(audio)) or 1.0
    return (audio / peak * 0.8).astype(np.float32)


def _try_tokenizer(model_dir: str):
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_dir)
    except Exception as e:
        log.debug("no usable HF tokenizer in %s (%r); using byte "
                  "fallback", model_dir, e)
        return None


VOICES = {  # voice id -> (pitch_hz, speed)
    "": (120.0, 1.0),
    "alloy": (120.0, 1.0),
    "echo": (95.0, 0.95),
    "fable": (140.0, 1.05),
    "onyx": (85.0, 0.9),
    "nova": (175.0, 1.1),
    "shimmer": (200.0, 1.05),
}


def write_wav(path: str, audio: np.ndarray, sr: int = SR) -> None:
    pcm = np.clip(audio * 32767.0, -32768, 32767).astype("<i2")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with wave.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(pcm.tobytes())


class JaxTTSBackend(Backend):
    """Neural VITS when the model dir holds an HF VitsModel checkpoint
    (facebook/mms-tts-* class — the reference's piper engine IS a VITS
    runtime, backend/go/tts/piper.go); formant-synth fallback otherwise
    so `/tts` always works with zero model files."""

    def __init__(self) -> None:
        self._state = "UNINITIALIZED"
        self._vits = None  # (spec, params, tokenizer-or-None)
        self._musicgen = None  # (bundle, tokenizer-or-None)
        self._bark = None  # models/bark.py BarkTTS
        self._kokoro = None  # (spec, params, voices)
        self._xtts = None  # (spec, params, tokenizer, voices)
        self._piper = None  # models/piper.py PiperVoice
        self._outetts = None  # models/outetts.py OuteTTSModel

    def load_model(self, opts: ModelLoadOptions) -> Result:
        # a reload must not leave a previous family reachable (tts()
        # dispatches on whichever slot is non-None)
        self._vits = self._musicgen = self._bark = self._kokoro = None
        self._xtts = None
        if self._outetts is not None:
            self._outetts.close()
        self._outetts = None
        self._bark_opts = {}
        model_dir = opts.model
        if model_dir and not os.path.isabs(model_dir):
            model_dir = os.path.join(opts.model_path or "", model_dir)
        self._piper = None
        if model_dir and model_dir.endswith(".onnx"):
            # piper voice: original-VITS onnx + sidecar json (ref:
            # backend/go/tts/piper.go:49 — the gallery's piper YAMLs
            # point parameters.model at the .onnx)
            from ..models.piper import PiperVoice

            try:
                self._piper = PiperVoice.load(model_dir)
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"piper load failed: {e}")
            self._state = "READY"
            return Result(True, "piper voice ready")
        cfg_path = os.path.join(model_dir or "", "config.json")
        if model_dir and os.path.exists(cfg_path):
            import json

            mtype = ""
            try:
                want_oute = str(opts.extra.get("type", "")
                                ).lower() == "outetts"
                if want_oute or os.path.isdir(
                        os.path.join(model_dir, "codec")):
                    # LLM-driven TTS (ref: transformers backend
                    # type==OuteTTS, backend.py:205-233): a causal LM
                    # with audio-code tokens + an EnCodec-layout codec
                    from ..models.outetts import OuteTTSModel

                    mtype = "outetts"
                    self._outetts = OuteTTSModel.load(model_dir)
                    self._state = "READY"
                    return Result(True, "outetts ready")
                from ..models.kokoro import is_kokoro_dir

                if is_kokoro_dir(model_dir):
                    # StyleTTS2-derived family; its config.json carries
                    # no transformers model_type (ref: backend/python/
                    # kokoro/backend.py)
                    from ..models.kokoro import load_kokoro

                    mtype = "kokoro"
                    self._kokoro = load_kokoro(model_dir)
                    self._state = "READY"
                    return Result(True, "kokoro ready")
                from ..models.xtts import is_xtts_dir

                if is_xtts_dir(model_dir):
                    # coqui XTTS v2 family (ref: backend/python/coqui/
                    # backend.py — TTS.api over xtts checkpoints)
                    from ..models.xtts import load_xtts

                    mtype = "xtts"
                    self._xtts = load_xtts(model_dir)
                    self._state = "READY"
                    return Result(True, "xtts ready")
                with open(cfg_path) as f:
                    mtype = (json.load(f).get("model_type") or "").lower()
                if mtype == "vits":
                    from ..models.vits import load_vits

                    spec, params = load_vits(model_dir)
                    self._vits = (spec, params, _try_tokenizer(model_dir))
                elif mtype == "musicgen":
                    # ref: transformers backend SoundGeneration :452 —
                    # MusicgenForConditionalGeneration
                    from ..models.musicgen import load_musicgen

                    self._musicgen = (load_musicgen(model_dir),
                                      _try_tokenizer(model_dir))
                elif mtype == "bark":
                    # ref: backend/python/bark/backend.py — the bark
                    # semantic/coarse/fine + EnCodec family
                    from ..models.bark import BarkTTS

                    self._bark = BarkTTS.load(model_dir)
                    self._bark_opts = {}
                    for kv in opts.options:
                        k, _, v = kv.partition("=")
                        if k == "max_semantic":
                            self._bark_opts["max_semantic"] = int(v)
                        elif k == "temperature":
                            self._bark_opts["temperature"] = float(v)
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"{mtype or 'tts'} load failed: {e}")
        self._state = "READY"
        return Result(True, "tts ready")

    def health(self) -> bool:
        return self._state == "READY"

    def shutdown(self) -> None:
        # the OuteTTS family owns a live LLMEngine (scheduler thread +
        # device KV cache) — unload must reclaim it, or model swaps
        # accumulate engines until the device OOMs
        if self._outetts is not None:
            self._outetts.close()
            self._outetts = None
        self._vits = self._musicgen = self._bark = self._kokoro = None
        self._xtts = None
        self._piper = None
        self._state = "UNINITIALIZED"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def _vits_ids(self, text: str) -> np.ndarray:
        spec, _, tok = self._vits
        if tok is not None:
            ids = tok(text)["input_ids"]
            if ids:
                return np.asarray(ids, np.int32)
        return np.asarray(
            [b % spec.vocab_size for b in text.encode()] or [0], np.int32)

    def tts(self, text: str, voice: str = "", dst: str = "",
            language: str = "") -> Result:
        if self._piper is not None:
            audio = self._piper.synthesize(text)
            write_wav(dst, audio, sr=self._piper.spec.sampling_rate)
            return Result(True, dst)
        if self._outetts is not None:
            from ..models.outetts import load_speaker

            speaker = None
            if voice:
                vpath = voice if os.path.isabs(voice) else os.path.join(
                    self._outetts.model_dir, voice)
                if os.path.exists(vpath):
                    speaker = load_speaker(vpath)
                elif os.path.exists(voice):
                    speaker = load_speaker(voice)
                else:
                    return Result(
                        False, f"outetts speaker profile not found: "
                               f"{voice!r} (a json with text + codes)")
            try:
                audio = self._outetts.synthesize(text, speaker=speaker)
            except RuntimeError as e:
                return Result(False, str(e))
            write_wav(dst, audio, sr=self._outetts.sample_rate)
            return Result(True, dst)
        if getattr(self, "_xtts", None) is not None:
            from ..models.xtts import synthesize

            xspec, xparams, xtok, voices = self._xtts
            if not voices:
                return Result(
                    False, "xtts model has no speakers file "
                           "(speakers_xtts.pth) — voice cloning from "
                           "reference audio needs precomputed latents")
            if voice and voice not in voices:
                return Result(
                    False, f"unknown xtts voice {voice!r}; available: "
                           f"{sorted(voices)}")
            lat, emb = voices[voice or next(iter(voices))]
            if xtok is not None:
                lang = language or "en"
                ids = xtok.encode(f"[{lang}]{text}").ids
            else:
                ids = [b % max(xspec.n_text_tokens - 2, 1) + 1
                       for b in text.encode()]
            audio = synthesize(xspec, xparams, np.asarray(ids), lat, emb)
            write_wav(dst, audio, sr=xspec.sample_rate)
            return Result(True, dst)
        if self._kokoro is not None:
            from ..models.kokoro import (pick_voice, synthesize_kokoro,
                                         text_to_tokens)

            kspec, kparams, voices = self._kokoro
            ids = text_to_tokens(text, kspec.n_token)
            ref = pick_voice(voices, voice, len(ids), kspec.style_dim)
            # official generate() pads the token stream with 0 on both
            # ends before the forward
            audio = synthesize_kokoro(kspec, kparams, [0, *ids, 0], ref)
            write_wav(dst, audio, sr=kspec.sampling_rate)
            return Result(True, dst)
        if self._bark is not None:
            audio = self._bark.generate(
                text, **getattr(self, "_bark_opts", {}))
            write_wav(dst, audio, sr=self._bark.sample_rate)
            return Result(True, dst)
        if self._vits is not None:
            from ..models.vits import synthesize

            spec, params, _ = self._vits
            _, speed = VOICES.get(voice.lower(), VOICES[""])
            audio = synthesize(spec, params, self._vits_ids(text),
                               speaking_rate=spec.speaking_rate * speed)
            write_wav(dst, audio, sr=spec.sampling_rate)
            return Result(True, dst)
        pitch, speed = VOICES.get(voice.lower(), VOICES[""])
        audio = _render(_g2p(text), pitch, speed)
        write_wav(dst, audio)
        return Result(True, dst)

    def sound_generation(self, text: str, dst: str = "", **kw) -> Result:
        """Neural MusicGen when a musicgen checkpoint is loaded (ref:
        ElevenLabs /v1/sound-generation, served by MusicGen in the
        reference — transformers/backend.py:452); otherwise a procedural
        seeded noise-band texture so the endpoint works with zero model
        files."""
        if self._musicgen is not None:
            from ..models.musicgen import mg_generate

            bundle, tok = self._musicgen
            meta = bundle[6]
            if tok is not None:
                ids = np.asarray(tok(text)["input_ids"], np.int32)
            else:
                t5_vocab = bundle[0].vocab_size
                ids = np.asarray(
                    [b % t5_vocab for b in text.encode()] or [0], np.int32)
            dur = kw.get("duration")
            dur = 5.0 if dur is None else float(dur)
            # cap the clip: step cost grows superlinearly in frames (no
            # KV cache yet) and logits scale with the padded prefix — an
            # uncapped client duration would be a one-request DoS
            dur = min(max(dur, 0.0), 30.0)
            frames = max(int(dur * meta["frame_rate"]), 8)
            audio = mg_generate(
                bundle, ids,
                max_new_tokens=frames + bundle[2].n_codebooks - 1,
                do_sample=bool(kw.get("do_sample", True)),
                temperature=float(1.0 if kw.get("temperature") is None
                                  else kw["temperature"]),
                guidance_scale=float(kw.get("guidance_scale") or 3.0),
                seed=int(kw.get("seed") or 0),
            )
            write_wav(dst, audio, sr=meta["sampling_rate"])
            return Result(True, dst)
        import hashlib

        seed = int.from_bytes(
            hashlib.sha256(text.encode()).digest()[:4], "little"
        )
        rng = np.random.default_rng(seed)
        dur = float(kw.get("duration") or 3.0)
        n = int(dur * SR)
        t = np.arange(n) / SR
        sig = np.zeros(n)
        for _ in range(4):
            fc = rng.uniform(100, 4000)
            bw = rng.uniform(0.5, 4.0)
            amp = rng.uniform(0.2, 1.0)
            env = np.exp(-bw * t) * np.sin(2 * np.pi * rng.uniform(0.2, 2) * t) ** 2
            sig += amp * env * np.sin(2 * np.pi * fc * t + rng.uniform(0, 6.28))
        noise_env = np.exp(-2.0 * t)
        sig += 0.3 * noise_env * rng.standard_normal(n)
        peak = np.max(np.abs(sig)) or 1.0
        write_wav(dst, (sig / peak * 0.8).astype(np.float32))
        return Result(True, dst)
