"""Subprocess-isolated backend: run a model in a child server process.

The reference runs EVERY backend as a separate OS process and reclaims a
wedged one by killing it (ref: pkg/model/process.go:21-61 process stop;
pkg/model/watchdog.go kill paths). This framework runs backends
in-process by default (one JAX runtime, no serialization overhead), which
left no escape hatch for a hung XLA compile or a crashed native backend
(VERDICT r3 weak #6). ``isolation: subprocess`` in the model YAML brings
the reference's containment back: the model loads inside a child
``localai-tpu run`` server on localhost, the parent proxies inference
over the OpenAI REST surface (the framework's external-worker wire
contract, workers/remote.py), and shutdown/watchdog kill is a real
``SIGKILL`` on the child's process group — always effective, no matter
how wedged the child is.

A load that exceeds ``load_timeout_s`` (YAML ``extra`` override;
default 600 s — first-compile at 8B scale is minutes) is treated as
wedged: the child is killed and the load fails, leaving the parent
serving everything else.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional

from .base import ModelLoadOptions, Result, StatusResponse
from .remote import RemoteOpenAIBackend

DEFAULT_LOAD_TIMEOUT_S = 600.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class SubprocessBackend(RemoteOpenAIBackend):
    """Child-process isolation wrapper around the OpenAI REST proxy."""

    def __init__(self) -> None:
        super().__init__()
        self.proc: Optional[subprocess.Popen] = None
        self._child_dir = ""

    # ----------------------------------------------------------- lifecycle

    def load_model(self, opts: ModelLoadOptions) -> Result:
        raw = opts.extra.get("_cfg_raw") or {}
        models_path = opts.extra.get("_models_path") or opts.model_path
        name = raw.get("name") or opts.model
        timeout = float(opts.extra.get("load_timeout_s",
                                       DEFAULT_LOAD_TIMEOUT_S))

        # child models dir: ONLY this model's yaml (minus the isolation
        # key — recursion guard), plus links to the parent's model files
        self._child_dir = tempfile.mkdtemp(prefix=f"isolated-{name}-")
        child_models = os.path.join(self._child_dir, "models")
        os.makedirs(child_models)
        child_cfg = {k: v for k, v in raw.items() if k != "isolation"}
        with open(os.path.join(child_models, "model.yaml"), "w") as f:
            json.dump(child_cfg, f)  # JSON is valid YAML
        if models_path and os.path.isdir(models_path):
            for entry in os.listdir(models_path):
                if entry.endswith((".yaml", ".yml")):
                    continue
                src = os.path.join(models_path, entry)
                dst = os.path.join(child_models, entry)
                try:
                    os.symlink(src, dst)
                except OSError:
                    pass

        # NOTE: the probe socket closes before the child binds, so the
        # port can be stolen in the gap; the wait loop below treats a
        # fast address-in-use exit as retryable (fresh port) rather
        # than a load failure
        env = dict(os.environ)
        # the child must import this package; PREPEND its root to any
        # existing PYTHONPATH (never clobber: TPU plugin site dirs ride
        # there in some deployments)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in [pkg_root, env.get("PYTHONPATH", "")] if p)
        log_path = os.path.join(self._child_dir, "child.log")
        custom_argv = opts.extra.get("_argv")  # test hook
        for attempt in range(2):
            port = _free_port()
            argv = custom_argv or [
                sys.executable, "-m", "localai_tfp_tpu.cli", "run",
                "--models-path", child_models,
                "--address", "127.0.0.1", "--port", str(port),
                "--disable-metrics",
            ]
            with open(log_path, "ab") as logf:
                self.proc = subprocess.Popen(
                    argv, cwd=self._child_dir, env=env,
                    stdout=logf, stderr=logf,
                    start_new_session=True,  # killpg reaches grandkids
                )
            self.base_url = f"http://127.0.0.1:{port}"
            self.model = name

            deadline = time.monotonic() + timeout
            last_err = "timed out"
            while time.monotonic() < deadline:
                if self.proc.poll() is not None:
                    tail = ""
                    try:
                        with open(log_path, "rb") as f:
                            tail = f.read()[-800:].decode(
                                errors="replace")
                    except OSError:
                        pass
                    if attempt == 0 and ("address in use" in tail.lower()
                                         or "errno 98" in tail.lower()):
                        # the probed port was stolen before the child
                        # bound it — retry once with a fresh one
                        break
                    return Result(
                        False,
                        f"isolated backend exited "
                        f"rc={self.proc.returncode}: {tail}")
                try:
                    with urllib.request.urlopen(
                            self.base_url + "/readyz", timeout=2) as r:
                        if r.status == 200:
                            self._state = "READY"
                            return Result(
                                True,
                                f"isolated backend pid={self.proc.pid}")
                except (urllib.error.URLError, OSError) as e:
                    last_err = str(e)
                time.sleep(0.25)
            else:
                # wedged load: reclaim the process (the point of
                # isolation)
                self.shutdown()
                return Result(
                    False, f"isolated backend wedged (> {timeout:.0f}s "
                           f"without /readyz; last: {last_err}); killed")
        return Result(False, "isolated backend could not bind a port")

    def health(self) -> bool:
        return (self._state == "READY" and self.proc is not None
                and self.proc.poll() is None)

    def status(self) -> StatusResponse:
        st = self._state
        if self.proc is not None and self.proc.poll() is not None:
            st = "ERROR"
        return StatusResponse(state=st)

    def shutdown(self) -> None:
        self._state = "UNINITIALIZED"
        proc, self.proc = self.proc, None
        if proc is None or proc.poll() is not None:
            return
        try:
            pgid = os.getpgid(proc.pid)
        except OSError:
            return
        try:
            os.killpg(pgid, signal.SIGTERM)
            try:
                proc.wait(timeout=3)
                return
            except subprocess.TimeoutExpired:
                pass
            # a wedged process ignores SIGTERM; SIGKILL cannot be ignored
            os.killpg(pgid, signal.SIGKILL)
            proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            pass

    # predict/predict_stream/embedding/tokenize_string proxy over REST —
    # inherited from RemoteOpenAIBackend. A dead child surfaces as a
    # connection error Reply, and health() flips so the loader rebuilds.
