"""Image/video generation worker (ref: backend/python/diffusers/backend.py
— LoadModel pipeline switch :139-272, GenerateImage :304, GenerateVideo;
also backend/go/image/stablediffusion-ggml). Serves
/v1/images/generations and /video.

Two pipelines:
- REAL checkpoints: a diffusers-format directory (model_index.json)
  loads the SD-class pipeline (models/sd.py — CLIP + UNet + VAE, full
  safetensors import, classifier-free-guided DDIM).
- ``__random__`` (explicit test fixture only): the toy random-init
  UNet+DDIM of models/diffusion.py with a byte-embedding conditioner —
  exercises the serving plumbing without a checkpoint.

Video = frame-chained sampling with the previous frame mixed into the
init noise (img2img-style temporal coherence).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import knobs
from ..models.diffusion import (
    DiffusionSpec, ddim_sample, init_diffusion_params, tiny_diffusion_spec,
)
from .base import Backend, ModelLoadOptions, Result, StatusResponse

COND_LEN = 64


def _read_image(path: str) -> np.ndarray:
    """Decode an on-disk image to [H, W, 3] uint8 (the src contract of
    GenerateImage — ref: endpoints/openai/image.go writes the request's
    base64 `file` to a temp path and hands backends the path)."""
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"), np.uint8)


def write_png(path: str, img: np.ndarray) -> None:
    """Minimal dependency-free PNG writer. img: [H, W, 3] uint8."""
    h, w, _ = img.shape
    raw = b"".join(
        b"\x00" + img[y].tobytes() for y in range(h)
    )

    def chunk(tag: bytes, data: bytes) -> bytes:
        c = struct.pack(">I", len(data)) + tag + data
        return c + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    png = (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
           + chunk(b"IDAT", zlib.compress(raw, 6)) + chunk(b"IEND", b""))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(png)


class JaxDiffusionBackend(Backend):
    def __init__(self) -> None:
        self.spec: Optional[DiffusionSpec] = None
        self.params = None
        self._sd = None  # models/sd.py SDPipeline for real checkpoints
        self._state = "UNINITIALIZED"
        self._lock = threading.Lock()
        self._steps = 12
        self._guidance: Optional[float] = None  # None => per-pipeline
        # default (7.5 for SD checkpoints, 3.0 for the toy fixture)

    def load_model(self, opts: ModelLoadOptions) -> Result:
        with self._lock:
            try:
                self._sd = None  # a reload must not keep a stale pipeline
                seed = 0
                for kv in opts.options:
                    k, _, v = kv.partition("=")
                    if k == "steps":
                        self._steps = int(v)
                    elif k == "guidance":
                        self._guidance = float(v)
                    elif k == "seed":
                        seed = int(v)
                model_dir = opts.model
                if model_dir and model_dir != "__random__" \
                        and not os.path.isabs(model_dir):
                    model_dir = os.path.join(opts.model_path or "",
                                             model_dir)
                control_net = (opts.extra.get("control_net")
                               or opts.extra.get("controlnet") or "")
                if control_net and not os.path.isabs(control_net):
                    control_net = os.path.join(opts.model_path or "",
                                               control_net)
                if model_dir and os.path.exists(
                        os.path.join(model_dir, "model_index.json")):
                    # pipeline-class switch (ref: diffusers backend.py
                    # :139-272 pipeline type dispatch)
                    from ..models.mmdit import pipeline_class_name

                    cls_name = pipeline_class_name(model_dir)
                    if control_net and cls_name.startswith(
                            ("StableDiffusion3", "Flux",
                             "StableVideoDiffusion")):
                        # the side network targets the 2D UNet skip
                        # topology (MMDiT and the SVD spatio-temporal
                        # UNet have none) — reject rather than silently
                        # ignore the requested conditioning
                        self._state = "ERROR"
                        return Result(
                            False, "control_net is only supported for "
                            "UNet pipelines (SD 1.x/2.x/SDXL), not "
                            f"{cls_name}")
                    if cls_name.startswith("StableVideoDiffusion"):
                        # real image-to-video (ref: backend.py:175-177)
                        from ..models.svd import SVDPipeline

                        self._sd = SVDPipeline.load(model_dir)
                        self._state = "READY"
                        return Result(True, "svd pipeline ready")
                    if cls_name.startswith("StableDiffusion3"):
                        from ..models.mmdit import SD3Pipeline

                        self._sd = SD3Pipeline.load(model_dir)
                        self._state = "READY"
                        return Result(True, "sd3 pipeline ready")
                    if cls_name.startswith("Flux"):
                        from ..models.mmdit import FluxPipeline

                        self._sd = FluxPipeline.load(model_dir)
                        self._state = "READY"
                        return Result(True, "flux pipeline ready")
                    from ..models.sd import SDPipeline, merge_sd_lora

                    self._sd = SDPipeline.load(model_dir)
                    if control_net:
                        # ref: backend/python/diffusers/backend.py
                        # :239-242 pipe.controlnet = ControlNetModel...
                        self._sd.attach_controlnet(control_net)
                    # image LoRAs fold into the loaded weights (ref:
                    # diffusers backend.py:245-252 load_lora_weights)
                    n_patched = 0
                    for i, la in enumerate(opts.lora_adapters):
                        if not os.path.isabs(la):
                            la = os.path.join(opts.model_path or "", la)
                        lscale = (float(opts.lora_scales[i])
                                  if i < len(opts.lora_scales) else 1.0)
                        if lscale == 0.0:
                            continue
                        if not os.path.isfile(la):
                            # a typo'd adapter path must fail the load,
                            # not quietly produce un-LoRA'd images (the
                            # reference's load_lora_weights raises too)
                            self._sd = None
                            self._state = "ERROR"
                            return Result(
                                False, f"lora adapter not found: {la}")
                        n_patched += merge_sd_lora(
                            self._sd.unet_tree, self._sd.text_tree,
                            la, scale=lscale)
                    self._state = "READY"
                    msg = "sd pipeline ready"
                    if n_patched:
                        msg += f" ({n_patched} LoRA weights merged)"
                    return Result(True, msg)
                if opts.model and opts.model != "__random__":
                    return Result(False, (
                        f"{opts.model!r} is not a diffusers-format "
                        "checkpoint directory (no model_index.json); "
                        "the random-init pipeline is a test fixture — "
                        "request it explicitly with model: __random__"))
                if control_net:
                    # never silently drop requested conditioning (the
                    # toy fixture has no UNet skips to condition)
                    self._state = "ERROR"
                    return Result(False, (
                        "control_net requires a diffusers-format UNet "
                        "checkpoint; the random test fixture cannot "
                        "honor it"))
                # explicit test fixture: random-init toy pipeline
                from ..ops.decode_attention import _interpret

                tiny = knobs.flag("LOCALAI_TINY_DIFFUSION") or \
                    _interpret()  # CPU: tiny pipeline (tests/smoke)
                self.spec = (tiny_diffusion_spec() if tiny
                             else DiffusionSpec())
                rng = jax.random.PRNGKey(seed)
                self.params = init_diffusion_params(rng, self.spec)
                self._cond_table = jax.random.normal(
                    jax.random.fold_in(rng, 1), (258, self.spec.d_cond)
                ) * 0.02
                self._state = "READY"
                return Result(True, "diffusion pipeline ready (random "
                                    "test fixture)")
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"load failed: {e}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def shutdown(self) -> None:
        self.spec = self.params = self._sd = None
        self._state = "UNINITIALIZED"

    # ------------------------------------------------------------ generation

    def _cond(self, prompt: str, negative: str = "") -> jnp.ndarray:
        ids = list(prompt.encode("utf-8"))[:COND_LEN]
        ids += [257] * (COND_LEN - len(ids))
        cond = self._cond_table[jnp.asarray(ids, jnp.int32)]
        if negative:
            nids = list(negative.encode("utf-8"))[:COND_LEN]
            nids += [257] * (COND_LEN - len(nids))
            cond = cond - 0.5 * self._cond_table[jnp.asarray(nids, jnp.int32)]
        return cond[None]

    def _sample(self, prompt: str, negative: str, w: int, h: int,
                steps: Optional[int], seed,
                init: Optional[np.ndarray] = None,
                strength: float = 0.5) -> np.ndarray:
        """txt2img, or img2img when ``init`` ([H, W, 3] uint8) is given:
        the init frame is encoded (VAE for real checkpoints, pixel space
        for the toy fixture), renoised to ``strength`` and denoised —
        the chaining primitive generate_video builds on."""
        if self._sd is not None:
            return self._sd.generate(
                prompt, negative_prompt=negative, height=h, width=w,
                steps=steps or self._steps,
                guidance=self._guidance if self._guidance is not None
                else 7.5,
                seed=seed, init_image=init, strength=strength,
            )
        # UNet downsamples len(channels) times; snap to the multiple
        mult = 2 ** len(self.spec.channels)
        w = max(mult, (w // mult) * mult)
        h = max(mult, (h // mult) * mult)
        rng = jax.random.PRNGKey(
            seed if seed is not None else
            int.from_bytes(os.urandom(4), "little")
        )
        guidance = self._guidance if self._guidance is not None else 3.0
        if init is not None:
            from ..models.diffusion import ddim_img2img

            init_arr = jnp.asarray(init, jnp.float32)[None] / 127.5 - 1.0
            img = ddim_img2img(
                self.spec, self.params, self._cond(prompt, negative), rng,
                init_arr, steps or self._steps, guidance, strength,
            )
        else:
            img = ddim_sample(
                self.spec, self.params, self._cond(prompt, negative), rng,
                h, w, steps or self._steps, guidance,
            )
        arr = np.asarray(img[0])
        return ((arr + 1.0) * 127.5).clip(0, 255).astype(np.uint8)

    def generate_image(self, prompt: str = "", negative_prompt: str = "",
                       width: int = 256, height: int = 256, dst: str = "",
                       step: Optional[int] = None, seed=None,
                       src: str = "", **kw) -> Result:
        if self._state != "READY":
            return Result(False, "model not loaded")
        from ..models.svd import SVDPipeline

        if isinstance(self._sd, SVDPipeline):
            return Result(
                False, "this model is an image-to-video pipeline "
                "(StableVideoDiffusion); use /video with start_image")
        if src and self._sd is not None \
                and getattr(self._sd, "control_spec", None) is not None:
            # a source image on a ControlNet pipeline is the
            # conditioning image, not an img2img init (ref: diffusers
            # backend.py:309-312 controlnet + request.src)
            img = self._sd.generate(
                prompt, negative_prompt=negative_prompt,
                height=height, width=width, steps=step or self._steps,
                guidance=self._guidance if self._guidance is not None
                else 7.5,
                seed=seed, control_image=_read_image(src),
            )
        elif src:
            img = self._sample(prompt, negative_prompt, width, height,
                               step, seed, init=_read_image(src))
        else:
            img = self._sample(prompt, negative_prompt, width, height,
                               step, seed)
        write_png(dst, img)
        return Result(True, dst)

    def generate_video(self, prompt: str = "", dst: str = "",
                       num_frames: Optional[int] = None, src: str = "",
                       width: int = 0, height: int = 0,
                       fps: int = 8, seed=None, step: Optional[int] = None,
                       **kw) -> Result:
        """Video generation. With a StableVideoDiffusionPipeline loaded
        (diffusers model_index class — ref: backend.py:175-177), ``src``
        (the request's start_image) drives the REAL image-to-video
        model: one temporally-attending UNet pass over all frames.
        Otherwise the frame-chaining fallback: frame 0 is a txt2img
        sample, every later frame img2img-chained from its predecessor
        (ref: diffusers GenerateVideo; core/backend/video.go). Muxed to
        mp4 via ffmpeg; frames are staged in a scratch dir removed on
        success (kept only on the no-ffmpeg poster fallback, plus under
        LOCALAI_KEEP_FRAMES=1 for tests)."""
        if self._state != "READY":
            return Result(False, "model not loaded")
        import shutil
        import subprocess

        from ..models.svd import SVDPipeline

        n = num_frames or 8
        frames_dir = dst + ".frames"
        os.makedirs(frames_dir, exist_ok=True)
        paths = []
        if isinstance(self._sd, SVDPipeline):
            if not src:
                return Result(
                    False, "StableVideoDiffusion is image-to-video: "
                    "the request needs a start_image")
            frames = self._sd.generate(
                _read_image(src), num_frames=n, height=height,
                width=width, steps=step or self._steps, fps=fps,
                seed=seed,
            )
            for i in range(frames.shape[0]):
                p = os.path.join(frames_dir, f"f{i:04d}.png")
                write_png(p, frames[i])
                paths.append(p)
        else:
            if src and self._sd is None:
                return Result(
                    False, "start_image video needs a diffusers "
                    "checkpoint (SVD for true img2vid, or an SD "
                    "pipeline for frame chaining)")
            prev: Optional[np.ndarray] = (
                _read_image(src) if src else None)
            base_seed = seed if seed is not None else 0
            for i in range(n):
                img = self._sample(prompt, "", width or 128,
                                   height or 128, step,
                                   seed=base_seed + i,
                                   init=prev, strength=0.45)
                prev = img
                p = os.path.join(frames_dir, f"f{i:04d}.png")
                write_png(p, img)
                paths.append(p)
        keep = knobs.flag("LOCALAI_KEEP_FRAMES")
        try:
            subprocess.run(
                ["ffmpeg", "-y", "-framerate", str(fps or 8), "-i",
                 os.path.join(frames_dir, "f%04d.png"), "-pix_fmt",
                 "yuv420p", dst],
                capture_output=True, check=True,
            )
            if not keep:  # scratch frames removed on success (ref:
                # pkg/utils/ffmpeg.go cleans its temp inputs)
                shutil.rmtree(frames_dir, ignore_errors=True)
        except OSError as e:
            # typed, operator-visible condition: ffmpeg missing (or not
            # executable) — ship the first frame as a poster and KEEP
            # the frames
            shutil.copy(paths[0], dst)
            why = ("not installed" if isinstance(e, FileNotFoundError)
                   else f"unavailable: {e}")
            return Result(
                True, f"{dst} (ffmpeg {why}: wrote the first frame as "
                f"a poster; raw frames kept in {frames_dir})")
        except subprocess.CalledProcessError as e:
            shutil.copy(paths[0], dst)
            return Result(
                True, f"{dst} (ffmpeg failed: "
                f"{e.stderr.decode(errors='replace')[-200:]}; wrote "
                f"poster; raw frames kept in {frames_dir})")
        return Result(True, dst)
