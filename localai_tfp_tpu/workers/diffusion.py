"""Image/video generation worker (ref: backend/python/diffusers/backend.py
— LoadModel pipeline switch :139-272, GenerateImage :304, GenerateVideo;
also backend/go/image/stablediffusion-ggml). Serves
/v1/images/generations and /video.

Two pipelines:
- REAL checkpoints: a diffusers-format directory (model_index.json)
  loads the SD-class pipeline (models/sd.py — CLIP + UNet + VAE, full
  safetensors import, classifier-free-guided DDIM).
- ``__random__`` (explicit test fixture only): the toy random-init
  UNet+DDIM of models/diffusion.py with a byte-embedding conditioner —
  exercises the serving plumbing without a checkpoint.

Video = frame-chained sampling with the previous frame mixed into the
init noise (img2img-style temporal coherence).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.diffusion import (
    DiffusionSpec, ddim_sample, init_diffusion_params, tiny_diffusion_spec,
)
from .base import Backend, ModelLoadOptions, Result, StatusResponse

COND_LEN = 64


def write_png(path: str, img: np.ndarray) -> None:
    """Minimal dependency-free PNG writer. img: [H, W, 3] uint8."""
    h, w, _ = img.shape
    raw = b"".join(
        b"\x00" + img[y].tobytes() for y in range(h)
    )

    def chunk(tag: bytes, data: bytes) -> bytes:
        c = struct.pack(">I", len(data)) + tag + data
        return c + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    png = (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
           + chunk(b"IDAT", zlib.compress(raw, 6)) + chunk(b"IEND", b""))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(png)


class JaxDiffusionBackend(Backend):
    def __init__(self) -> None:
        self.spec: Optional[DiffusionSpec] = None
        self.params = None
        self._sd = None  # models/sd.py SDPipeline for real checkpoints
        self._state = "UNINITIALIZED"
        self._lock = threading.Lock()
        self._steps = 12
        self._guidance: Optional[float] = None  # None => per-pipeline
        # default (7.5 for SD checkpoints, 3.0 for the toy fixture)

    def load_model(self, opts: ModelLoadOptions) -> Result:
        with self._lock:
            try:
                self._sd = None  # a reload must not keep a stale pipeline
                seed = 0
                for kv in opts.options:
                    k, _, v = kv.partition("=")
                    if k == "steps":
                        self._steps = int(v)
                    elif k == "guidance":
                        self._guidance = float(v)
                    elif k == "seed":
                        seed = int(v)
                model_dir = opts.model
                if model_dir and model_dir != "__random__" \
                        and not os.path.isabs(model_dir):
                    model_dir = os.path.join(opts.model_path or "",
                                             model_dir)
                if (opts.extra.get("control_net")
                        or opts.extra.get("controlnet")):
                    # conditioning side-network not implemented yet
                    # (PARITY.md ControlNet gap entry) — fail loudly,
                    # never silently ignore the requested conditioning.
                    # Covers the canonical diffusers.control_net key
                    # (forwarded by the loader) and top-level spellings.
                    self._state = "ERROR"
                    return Result(
                        False,
                        "controlnet conditioning is not supported yet "
                        "(see the ControlNet entry in PARITY.md's known "
                        "gaps); remove `control_net` from the model "
                        "yaml")
                if model_dir and os.path.exists(
                        os.path.join(model_dir, "model_index.json")):
                    # pipeline-class switch (ref: diffusers backend.py
                    # :139-272 pipeline type dispatch)
                    from ..models.mmdit import pipeline_class_name

                    cls_name = pipeline_class_name(model_dir)
                    if cls_name.startswith("StableDiffusion3"):
                        from ..models.mmdit import SD3Pipeline

                        self._sd = SD3Pipeline.load(model_dir)
                        self._state = "READY"
                        return Result(True, "sd3 pipeline ready")
                    if cls_name.startswith("Flux"):
                        from ..models.mmdit import FluxPipeline

                        self._sd = FluxPipeline.load(model_dir)
                        self._state = "READY"
                        return Result(True, "flux pipeline ready")
                    from ..models.sd import SDPipeline, merge_sd_lora

                    self._sd = SDPipeline.load(model_dir)
                    # image LoRAs fold into the loaded weights (ref:
                    # diffusers backend.py:245-252 load_lora_weights)
                    n_patched = 0
                    for i, la in enumerate(opts.lora_adapters):
                        if not os.path.isabs(la):
                            la = os.path.join(opts.model_path or "", la)
                        lscale = (float(opts.lora_scales[i])
                                  if i < len(opts.lora_scales) else 1.0)
                        if lscale == 0.0:
                            continue
                        if not os.path.isfile(la):
                            # a typo'd adapter path must fail the load,
                            # not quietly produce un-LoRA'd images (the
                            # reference's load_lora_weights raises too)
                            self._sd = None
                            self._state = "ERROR"
                            return Result(
                                False, f"lora adapter not found: {la}")
                        n_patched += merge_sd_lora(
                            self._sd.unet_tree, self._sd.text_tree,
                            la, scale=lscale)
                    self._state = "READY"
                    msg = "sd pipeline ready"
                    if n_patched:
                        msg += f" ({n_patched} LoRA weights merged)"
                    return Result(True, msg)
                if opts.model and opts.model != "__random__":
                    return Result(False, (
                        f"{opts.model!r} is not a diffusers-format "
                        "checkpoint directory (no model_index.json); "
                        "the random-init pipeline is a test fixture — "
                        "request it explicitly with model: __random__"))
                # explicit test fixture: random-init toy pipeline
                from ..ops.decode_attention import _interpret

                tiny = bool(os.environ.get("LOCALAI_TINY_DIFFUSION")) or \
                    _interpret()  # CPU: tiny pipeline (tests/smoke)
                self.spec = (tiny_diffusion_spec() if tiny
                             else DiffusionSpec())
                rng = jax.random.PRNGKey(seed)
                self.params = init_diffusion_params(rng, self.spec)
                self._cond_table = jax.random.normal(
                    jax.random.fold_in(rng, 1), (258, self.spec.d_cond)
                ) * 0.02
                self._state = "READY"
                return Result(True, "diffusion pipeline ready (random "
                                    "test fixture)")
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"load failed: {e}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def shutdown(self) -> None:
        self.spec = self.params = self._sd = None
        self._state = "UNINITIALIZED"

    # ------------------------------------------------------------ generation

    def _cond(self, prompt: str, negative: str = "") -> jnp.ndarray:
        ids = list(prompt.encode("utf-8"))[:COND_LEN]
        ids += [257] * (COND_LEN - len(ids))
        cond = self._cond_table[jnp.asarray(ids, jnp.int32)]
        if negative:
            nids = list(negative.encode("utf-8"))[:COND_LEN]
            nids += [257] * (COND_LEN - len(nids))
            cond = cond - 0.5 * self._cond_table[jnp.asarray(nids, jnp.int32)]
        return cond[None]

    def _sample(self, prompt: str, negative: str, w: int, h: int,
                steps: Optional[int], seed,
                init: Optional[np.ndarray] = None,
                strength: float = 0.5) -> np.ndarray:
        """txt2img, or img2img when ``init`` ([H, W, 3] uint8) is given:
        the init frame is encoded (VAE for real checkpoints, pixel space
        for the toy fixture), renoised to ``strength`` and denoised —
        the chaining primitive generate_video builds on."""
        if self._sd is not None:
            return self._sd.generate(
                prompt, negative_prompt=negative, height=h, width=w,
                steps=steps or self._steps,
                guidance=self._guidance if self._guidance is not None
                else 7.5,
                seed=seed, init_image=init, strength=strength,
            )
        # UNet downsamples len(channels) times; snap to the multiple
        mult = 2 ** len(self.spec.channels)
        w = max(mult, (w // mult) * mult)
        h = max(mult, (h // mult) * mult)
        rng = jax.random.PRNGKey(
            seed if seed is not None else
            int.from_bytes(os.urandom(4), "little")
        )
        guidance = self._guidance if self._guidance is not None else 3.0
        if init is not None:
            from ..models.diffusion import ddim_img2img

            init_arr = jnp.asarray(init, jnp.float32)[None] / 127.5 - 1.0
            img = ddim_img2img(
                self.spec, self.params, self._cond(prompt, negative), rng,
                init_arr, steps or self._steps, guidance, strength,
            )
        else:
            img = ddim_sample(
                self.spec, self.params, self._cond(prompt, negative), rng,
                h, w, steps or self._steps, guidance,
            )
        arr = np.asarray(img[0])
        return ((arr + 1.0) * 127.5).clip(0, 255).astype(np.uint8)

    def generate_image(self, prompt: str = "", negative_prompt: str = "",
                       width: int = 256, height: int = 256, dst: str = "",
                       step: Optional[int] = None, seed=None,
                       **kw) -> Result:
        if self._state != "READY":
            return Result(False, "model not loaded")
        img = self._sample(prompt, negative_prompt, width, height, step, seed)
        write_png(dst, img)
        return Result(True, dst)

    def generate_video(self, prompt: str = "", dst: str = "",
                       num_frames: Optional[int] = None, **kw) -> Result:
        """Temporally-coherent frame sequence: frame 0 is a txt2img
        sample, every later frame is img2img-chained from its
        predecessor (encode previous frame, renoise to ~0.45 strength,
        denoise) — so consecutive frames evolve instead of re-rolling
        (ref: diffusers GenerateVideo; core/backend/video.go). Muxed to
        mp4 via ffmpeg when available (ref utils/ffmpeg.go)."""
        if self._state != "READY":
            return Result(False, "model not loaded")
        import subprocess

        n = num_frames or 8
        frames_dir = dst + ".frames"
        os.makedirs(frames_dir, exist_ok=True)
        paths = []
        prev: Optional[np.ndarray] = None
        for i in range(n):
            img = self._sample(prompt, "", 128, 128, None, seed=i,
                               init=prev, strength=0.45)
            prev = img
            p = os.path.join(frames_dir, f"f{i:04d}.png")
            write_png(p, img)
            paths.append(p)
        try:
            subprocess.run(
                ["ffmpeg", "-y", "-framerate", "8", "-i",
                 os.path.join(frames_dir, "f%04d.png"), "-pix_fmt",
                 "yuv420p", dst],
                capture_output=True, check=True,
            )
        except (OSError, subprocess.CalledProcessError):
            # no ffmpeg: ship the first frame as a poster + keep frames dir
            import shutil

            shutil.copy(paths[0], dst)
        return Result(True, dst)
