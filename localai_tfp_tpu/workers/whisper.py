"""Whisper STT worker (ref: backend/go/transcribe/whisper for whisper.cpp,
backend/python/faster-whisper/backend.py — gRPC `AudioTranscription`,
served at POST /v1/audio/transcriptions, core/backend/transcript.go).

Audio intake mirrors the reference's ffmpeg conversion path
(pkg/utils/ffmpeg.go:55): non-WAV inputs are shelled through ffmpeg to
16kHz mono PCM when available; WAV is decoded natively.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
import wave
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.whisper import (
    CHUNK_S, SAMPLE_RATE, WhisperSpec, greedy_transcribe,
    load_whisper_params, log_mel_spectrogram,
)
from .base import (
    Backend, ModelLoadOptions, Result, StatusResponse, TranscriptResult,
    TranscriptSegment,
)

log = logging.getLogger(__name__)


def load_pcm(path: str) -> np.ndarray:
    """Decode an audio file to float32 mono 16kHz PCM."""
    if path.lower().endswith(".wav"):
        with wave.open(path) as w:
            sr = w.getframerate()
            n_ch = w.getnchannels()
            width = w.getsampwidth()
            raw = w.readframes(w.getnframes())
        if width == 1:  # 8-bit WAV is UNSIGNED, silence at 128
            pcm = (np.frombuffer(raw, np.uint8).astype(np.float32)
                   - 128.0) / 128.0
        elif width == 2:
            pcm = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
        elif width == 3:  # 24-bit packed little-endian
            b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
            val = (b[:, 0].astype(np.int32)
                   | (b[:, 1].astype(np.int32) << 8)
                   | (b[:, 2].astype(np.int32) << 16))
            val = np.where(val >= 1 << 23, val - (1 << 24), val)
            pcm = val.astype(np.float32) / float(1 << 23)
        elif width == 4:
            pcm = np.frombuffer(raw, np.int32).astype(np.float32) / float(
                1 << 31)
        else:
            raise ValueError(f"unsupported WAV sample width {width}")
        if n_ch > 1:
            pcm = pcm.reshape(-1, n_ch).mean(axis=1)
        if sr != SAMPLE_RATE:
            idx = np.linspace(0, len(pcm) - 1, int(len(pcm) * SAMPLE_RATE / sr))
            pcm = np.interp(idx, np.arange(len(pcm)), pcm).astype(np.float32)
        return pcm
    # non-wav: ffmpeg shell-out (ref: utils/ffmpeg.go audioToWav)
    out = subprocess.run(
        ["ffmpeg", "-i", path, "-f", "f32le", "-ac", "1",
         "-ar", str(SAMPLE_RATE), "-"],
        capture_output=True, check=True,
    )
    return np.frombuffer(out.stdout, np.float32)


class JaxWhisperBackend(Backend):
    def __init__(self) -> None:
        self.spec: Optional[WhisperSpec] = None
        self.params = None
        self.tokenizer = None
        self._state = "UNINITIALIZED"
        self._lock = threading.Lock()

    def load_model(self, opts: ModelLoadOptions) -> Result:
        with self._lock:
            try:
                model_dir = opts.model
                if not os.path.isabs(model_dir):
                    model_dir = os.path.join(opts.model_path or "", model_dir)
                if not os.path.isdir(model_dir):
                    raise FileNotFoundError(
                        f"model directory not found: {model_dir}")
                self.spec, self.params = load_whisper_params(model_dir)
                try:
                    from transformers import AutoTokenizer

                    self.tokenizer = AutoTokenizer.from_pretrained(
                        model_dir)
                except Exception as e:
                    log.warning("whisper tokenizer unavailable (%r); "
                                "token ids will be byte-decoded", e)
                    self.tokenizer = None
                self._state = "READY"
                return Result(True, "whisper model loaded")
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"load failed: {e}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def shutdown(self) -> None:
        self.spec = self.params = self.tokenizer = None
        self._state = "UNINITIALIZED"

    # ---------------------------------------------------------- transcribe

    def _prompt(self, language: str, translate: bool) -> list[int]:
        sp = self.spec
        lang_id = None
        if self.tokenizer is not None and language:
            lid = self.tokenizer.convert_tokens_to_ids(f"<|{language}|>")
            if lid is not None and lid != getattr(
                    self.tokenizer, "unk_token_id", None):
                lang_id = lid
        ids = [sp.sot]
        ids.append(lang_id if lang_id is not None else sp.lang_base)
        ids.append(sp.task_translate if translate else sp.task_transcribe)
        ids.append(sp.no_timestamps)
        return ids

    def _decode_text(self, ids: list[int]) -> str:
        sp = self.spec
        clean = [i for i in ids if i < sp.eot or (
            sp.eot < sp.sot and i < sp.sot)]
        clean = [i for i in clean if i not in (sp.sot, sp.eot)
                 and not (sp.timestamp_begin <= i)]
        if self.tokenizer is not None:
            return self.tokenizer.decode(clean, skip_special_tokens=True)
        return " ".join(str(i) for i in clean)

    def audio_transcription(self, audio_path: str, language: str = "",
                            translate: bool = False) -> TranscriptResult:
        if self._state != "READY":
            raise RuntimeError("model not loaded")
        pcm = load_pcm(audio_path)
        duration = len(pcm) / SAMPLE_RATE
        prompt = jnp.asarray(self._prompt(language, translate), jnp.int32)
        segments: list[TranscriptSegment] = []
        texts = []
        chunk = CHUNK_S * SAMPLE_RATE
        n_chunks = max(1, (len(pcm) + chunk - 1) // chunk)
        max_new = min(224, self.spec.max_target - prompt.shape[0] - 1)
        for ci in range(n_chunks):
            mel = log_mel_spectrogram(pcm[ci * chunk : (ci + 1) * chunk])
            toks = greedy_transcribe(
                self.spec, self.params, jnp.asarray(mel)[None],
                max_new, prompt,
            )
            ids = [int(t) for t in np.asarray(toks)]
            if self.spec.eot in ids:
                ids = ids[: ids.index(self.spec.eot)]
            text = self._decode_text(ids).strip()
            start = ci * CHUNK_S
            end = min((ci + 1) * CHUNK_S, duration)
            segments.append(TranscriptSegment(
                id=ci, start=float(start), end=float(end), text=text,
                tokens=ids,
            ))
            texts.append(text)
        return TranscriptResult(segments=segments, text=" ".join(
            t for t in texts if t).strip())
