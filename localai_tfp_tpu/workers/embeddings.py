"""Embeddings worker (ref: the reference serves /v1/embeddings via
sentence-transformers / mean-pooled causal LMs — backend/python/
transformers/backend.py:286-324; routed from core/backend/embeddings.go).

Loads a local checkpoint directory:
- encoder checkpoints (bert/minilm family) -> models/encoder.py, masked
  mean-pool + L2 normalize (sentence-transformers semantics);
- anything else is served by the LLM worker's hidden-state path (the
  loader aliases decoder-embedding configs there).

Batched, bucketed encode: requests are padded to the next length bucket so
the jit cache stays tiny.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..models.encoder import encode, mean_pool
from .base import EmbeddingResult, PredictOptions
from .encoder_base import EncoderWorkerBase


class JaxEmbeddingsBackend(EncoderWorkerBase):
    LEN_BUCKETS = (16, 64, 128, 256, 512)

    def _compile(self) -> None:
        spec = self.spec

        @jax.jit
        def _encode(params, tokens, mask):
            hidden = encode(spec, params, tokens, mask)
            return mean_pool(hidden, mask)

        self._encode = _encode

    def embed_batch(self, texts: list[str]) -> np.ndarray:
        assert self.spec and self.params is not None and self.tokenizer
        ids = [self.tokenizer.encode_special(t)[: self.spec.max_position]
               or [0] for t in texts]
        toks, mask, _ = self._batch(ids)
        out = self._encode(self.params, jnp.asarray(toks), jnp.asarray(mask))
        return np.asarray(out, dtype=np.float32)

    def embedding(self, opts: PredictOptions) -> EmbeddingResult:
        if self._state != "READY":
            raise RuntimeError("model not loaded")
        vec = self.embed_batch([opts.embeddings or opts.prompt])[0]
        return EmbeddingResult(embeddings=[float(x) for x in vec])
