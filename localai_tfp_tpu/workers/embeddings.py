"""Embeddings worker (ref: the reference serves /v1/embeddings via
sentence-transformers / mean-pooled causal LMs — backend/python/
transformers/backend.py:286-324; routed from core/backend/embeddings.go).

Loads a local checkpoint directory:
- encoder checkpoints (bert/minilm family) -> models/encoder.py, masked
  mean-pool + L2 normalize (sentence-transformers semantics);
- anything else is served by the LLM worker's hidden-state path (the
  loader aliases decoder-embedding configs there).

Batched, bucketed encode: requests are padded to the next length bucket so
the jit cache stays tiny.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.tokenizer import Tokenizer, load_tokenizer
from ..models.encoder import (
    EncoderSpec, EncParams, encode, load_encoder_params, mean_pool,
)
from .base import (
    Backend, EmbeddingResult, ModelLoadOptions, PredictOptions, Result,
    StatusResponse,
)

LEN_BUCKETS = (16, 64, 128, 256, 512)


class JaxEmbeddingsBackend(Backend):
    def __init__(self) -> None:
        self.spec: Optional[EncoderSpec] = None
        self.params: Optional[EncParams] = None
        self.tokenizer: Optional[Tokenizer] = None
        self._state = "UNINITIALIZED"
        self._lock = threading.Lock()

    def load_model(self, opts: ModelLoadOptions) -> Result:
        with self._lock:
            try:
                model_dir = opts.model
                if not os.path.isabs(model_dir):
                    model_dir = os.path.join(opts.model_path or "", model_dir)
                if not os.path.isdir(model_dir):
                    raise FileNotFoundError(
                        f"model directory not found: {model_dir}")
                self.spec, self.params = load_encoder_params(model_dir)
                self.tokenizer = load_tokenizer(model_dir)

                @partial(jax.jit, static_argnums=())
                def _encode(params, tokens, mask):
                    hidden = encode(self.spec, params, tokens, mask)
                    return mean_pool(hidden, mask)

                self._encode = _encode
                self._state = "READY"
                return Result(True, "embeddings model loaded")
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"load failed: {e}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def shutdown(self) -> None:
        self.spec = self.params = self.tokenizer = None
        self._state = "UNINITIALIZED"

    # ------------------------------------------------------------- encoding

    def _bucket(self, n: int) -> int:
        cap = self.spec.max_position
        for b in LEN_BUCKETS:
            if n <= b <= cap:
                return b
        return cap

    def embed_batch(self, texts: list[str]) -> np.ndarray:
        assert self.spec and self.params is not None and self.tokenizer
        ids = [self.tokenizer.encode_special(t)[: self.spec.max_position]
               or [0] for t in texts]
        T = self._bucket(max(len(x) for x in ids))
        B = len(ids)
        toks = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), np.int32)
        for r, x in enumerate(ids):
            x = x[:T]
            toks[r, : len(x)] = x
            mask[r, : len(x)] = 1
        out = self._encode(self.params, jnp.asarray(toks), jnp.asarray(mask))
        return np.asarray(out, dtype=np.float32)

    def embedding(self, opts: PredictOptions) -> EmbeddingResult:
        if self._state != "READY":
            raise RuntimeError("model not loaded")
        vec = self.embed_batch([opts.embeddings or opts.prompt])[0]
        return EmbeddingResult(embeddings=[float(x) for x in vec])
