"""Voice-activity detection worker (ref: the reference runs silero's ONNX
VAD via onnxruntime — backend/go/vad/silero/, served at POST /vad,
core/http/endpoints/localai/vad.go).

TPU-native re-design: a windowed energy + spectral-flatness detector
computed as one batched jitted JAX program (frames × FFT ride the VPU/MXU),
with hysteresis and hangover smoothing on the host. This is a classical
DSP detector, not a learned one — the capability contract (float PCM in,
speech segments out, same JSON shape) is identical.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import (
    Backend, ModelLoadOptions, Result, StatusResponse, VADResponse,
    VADSegment,
)

log = logging.getLogger(__name__)

SAMPLE_RATE = 16000
FRAME = 512  # 32 ms
HOP = 160  # 10 ms


@jax.jit
def _frame_features(audio: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[n] f32 -> (rms energy [F], spectral flatness [F]) per frame."""
    n_frames = (audio.shape[0] - FRAME) // HOP + 1
    idx = jnp.arange(n_frames)[:, None] * HOP + jnp.arange(FRAME)[None, :]
    frames = audio[idx]  # [F, FRAME]
    window = jnp.hanning(FRAME)
    rms = jnp.sqrt(jnp.mean(jnp.square(frames), axis=-1) + 1e-12)
    spec = jnp.abs(jnp.fft.rfft(frames * window, axis=-1)) + 1e-10
    # speech is spectrally peaky (low flatness); noise is flat (~1)
    flat = jnp.exp(jnp.mean(jnp.log(spec), axis=-1)) / jnp.mean(spec, axis=-1)
    return rms, flat


class JaxVADBackend(Backend):
    def __init__(self) -> None:
        self._state = "UNINITIALIZED"
        self.threshold = 2.5  # over noise floor (DSP mode); learned mode
        # reinterprets values <= 1 as the probability threshold
        self.min_speech_s = 0.25
        self.min_silence_s = 0.25
        self._net = None  # learned silero-class model (models/vad_net)

    def load_model(self, opts: ModelLoadOptions) -> Result:
        import os

        self._net = None
        for kv in opts.options:
            k, _, v = kv.partition("=")
            if k == "threshold":
                self.threshold = float(v)
            elif k == "min_speech_s":
                self.min_speech_s = float(v)
            elif k == "min_silence_s":
                self.min_silence_s = float(v)
        model = opts.model
        if model and not os.path.isabs(model):
            cand = os.path.join(opts.model_path or "", model)
            model = cand if os.path.exists(cand) else model
        if model and not os.path.exists(model):
            # a configured-but-missing model must fail loudly, not
            # silently degrade to the DSP heuristic
            self._state = "ERROR"
            return Result(False, f"vad model not found: {opts.model!r}")
        if model:
            try:
                from ..models import vad_net

                if model.endswith((".jit", ".pt", ".pth", ".ts")):
                    try:  # torchscript archive (the silero download)
                        self._net = vad_net.load_torchscript(model)
                    except Exception as e:
                        log.warning("torchscript parse of %s failed "
                                    "(%r); retrying as a state_dict "
                                    "checkpoint", model, e)
                        import torch

                        self._net = vad_net.load_state_dict(
                            torch.load(model, map_location="cpu",
                                       weights_only=True))
                elif model.endswith(".safetensors"):
                    from safetensors import safe_open

                    with safe_open(model, framework="np") as f:
                        sd = {k: f.get_tensor(k) for k in f.keys()}
                    self._net = vad_net.load_state_dict(sd)
                else:
                    self._state = "ERROR"
                    return Result(False, (
                        f"unsupported VAD model format: {model!r} "
                        "(.jit/.pt/.pth/.safetensors)"))
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"vad model load failed: {e}")
        self._state = "READY"
        return Result(True, "vad ready (learned silero-class model)"
                      if self._net is not None
                      else "vad ready (DSP detector)")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def vad(self, audio: list[float]) -> VADResponse:
        pcm = np.asarray(audio, np.float32)
        if self._net is not None:
            from ..models import vad_net

            if pcm.shape[0] < vad_net.CHUNK:
                return VADResponse()
            probs = vad_net.speech_probs(self._net, pcm)
            thr = self.threshold if self.threshold <= 1.0 else 0.5
            segs = vad_net.probs_to_segments(
                probs, threshold=thr, min_speech_s=self.min_speech_s,
                min_silence_s=self.min_silence_s)
            return VADResponse(segments=[
                VADSegment(start=round(s, 3), end=round(e, 3))
                for s, e in segs
            ])
        if pcm.shape[0] < FRAME:
            return VADResponse()
        # pad to a power-of-two bucket so the jitted FFT program compiles
        # once per bucket, not once per input length
        n_valid = (pcm.shape[0] - FRAME) // HOP + 1
        bucket = 1 << (pcm.shape[0] - 1).bit_length()
        padded = np.zeros(bucket, np.float32)
        padded[: pcm.shape[0]] = pcm
        rms, flat = _frame_features(jnp.asarray(padded))
        rms = np.asarray(rms)[:n_valid]
        flat = np.asarray(flat)[:n_valid]
        # adaptive noise floor: the quietest quarter of frames
        floor = max(float(np.percentile(rms, 25)), 1e-6)
        speech = (rms > floor * self.threshold) & (flat < 0.5)
        segs = _smooth(speech, self.min_speech_s, self.min_silence_s)
        return VADResponse(segments=[
            VADSegment(start=round(s * HOP / SAMPLE_RATE, 3),
                       end=round((e * HOP + FRAME) / SAMPLE_RATE, 3))
            for s, e in segs
        ])


def _smooth(speech: np.ndarray, min_speech_s: float,
            min_silence_s: float) -> list[tuple[int, int]]:
    """Merge gaps < min_silence, drop islands < min_speech (the hangover
    logic every practical VAD needs)."""
    frames_per_s = SAMPLE_RATE / HOP
    min_speech = int(min_speech_s * frames_per_s)
    min_silence = int(min_silence_s * frames_per_s)
    segs: list[tuple[int, int]] = []
    start: Optional[int] = None
    for i, on in enumerate(speech):
        if on and start is None:
            start = i
        elif not on and start is not None:
            segs.append((start, i - 1))
            start = None
    if start is not None:
        segs.append((start, len(speech) - 1))
    merged: list[tuple[int, int]] = []
    for s, e in segs:
        if merged and s - merged[-1][1] <= min_silence:
            merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return [(s, e) for s, e in merged if e - s + 1 >= min_speech]
