"""Remote OpenAI-compatible backend (external workers).

Capability counterpart of two reference mechanisms: external gRPC
backends registered via ``external_backends.json`` / ``--external-backend``
(pkg/model loads any proto-conformant address — SURVEY.md §4 mocks row)
and the langchain-huggingface remote-API passthrough backend
(backend/go/llm/langchain, last-resort in the autoload order). Here the
wire contract for external workers is the OpenAI REST surface itself: any
server speaking it (another LocalAI instance, vLLM, llama.cpp server...)
can be mounted as a backend.
"""

from __future__ import annotations

import json
import urllib.error
from typing import Iterator, Optional

from ..utils.http import json_request
from .base import (
    Backend, EmbeddingResult, ModelLoadOptions, PredictOptions, Reply,
    Result, StatusResponse, TokenizationResponse,
)


class RemoteOpenAIBackend(Backend):
    """Proxies predict/embedding calls to a remote OpenAI-compatible API."""

    def __init__(self, base_url: str = "", api_key: str = "") -> None:
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.model = ""
        self._state = "UNINITIALIZED"

    # ------------------------------------------------------------ plumbing

    def _req(self, path: str, payload: dict):
        return json_request(self.base_url + path, payload,
                            api_key=self.api_key)

    # ----------------------------------------------------------- lifecycle

    def load_model(self, opts: ModelLoadOptions) -> Result:
        if opts.extra.get("base_url"):
            self.base_url = str(opts.extra["base_url"]).rstrip("/")
        if opts.extra.get("api_key"):
            self.api_key = str(opts.extra["api_key"])
        for kv in opts.options:
            k, _, v = kv.partition("=")
            if k == "base_url":
                self.base_url = v.rstrip("/")
            elif k == "api_key":
                self.api_key = v
        if not self.base_url:
            return Result(False, "remote backend needs base_url")
        self.model = opts.model
        self._state = "READY"
        return Result(True, f"remote backend -> {self.base_url}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    # ----------------------------------------------------------- inference

    def _payload(self, opts: PredictOptions) -> dict:
        p: dict = {
            "model": self.model or None,
            "prompt": opts.prompt,
            "max_tokens": opts.tokens or None,
            "temperature": opts.temperature,
            "top_p": opts.top_p if opts.top_p < 1 else None,
            "stop": opts.stop_prompts or None,
            "seed": opts.seed,
        }
        return {k: v for k, v in p.items() if v is not None}

    def predict(self, opts: PredictOptions) -> Reply:
        try:
            with self._req("/v1/completions", self._payload(opts)) as r:
                data = json.load(r)
        except (urllib.error.URLError, OSError, ValueError) as e:
            return Reply(error=f"remote backend: {e}")
        choice = (data.get("choices") or [{}])[0]
        usage = data.get("usage") or {}
        return Reply(
            message=choice.get("text", ""),
            tokens=usage.get("completion_tokens", 0),
            prompt_tokens=usage.get("prompt_tokens", 0),
            finish_reason=choice.get("finish_reason", ""),
        )

    def predict_stream(self, opts: PredictOptions) -> Iterator[Reply]:
        payload = self._payload(opts)
        payload["stream"] = True
        try:
            with self._req("/v1/completions", payload) as r:
                for raw in r:
                    line = raw.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    if line == "data: [DONE]":
                        break
                    try:
                        d = json.loads(line[6:])
                    except ValueError:
                        continue
                    ch = (d.get("choices") or [{}])[0]
                    text = ch.get("text") or (
                        (ch.get("delta") or {}).get("content", ""))
                    if text:
                        yield Reply(message=text)
                    if ch.get("finish_reason"):
                        yield Reply(finish_reason=ch["finish_reason"])
                        return
            yield Reply(finish_reason="stop")
        except (urllib.error.URLError, OSError) as e:
            yield Reply(error=f"remote backend: {e}")

    def embedding(self, opts: PredictOptions) -> EmbeddingResult:
        try:
            with self._req("/v1/embeddings", {
                "model": self.model or None,
                "input": opts.embeddings or opts.prompt,
            }) as r:
                data = json.load(r)
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise RuntimeError(f"remote backend: {e}")
        emb = ((data.get("data") or [{}])[0]).get("embedding") or []
        return EmbeddingResult(embeddings=[float(x) for x in emb])

    def tokenize_string(self, opts: PredictOptions) -> TokenizationResponse:
        try:
            with self._req("/v1/tokenize", {"content": opts.prompt}) as r:
                data = json.load(r)
            toks = data.get("tokens") or []
            return TokenizationResponse(length=len(toks), tokens=toks)
        except (urllib.error.URLError, OSError, ValueError):
            return TokenizationResponse()
