"""Shared scaffolding for encoder-model workers (embeddings, rerank).

One place for checkpoint resolution, lifecycle state, and bucketed batch
padding — the per-worker classes contribute only their jitted programs
(counterpart of the reference's shared Python-backend scaffolding,
backend/python/common/libbackend.sh).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from ..engine.tokenizer import Tokenizer, load_tokenizer
from ..models.encoder import EncoderSpec, EncParams, load_encoder_params
from .base import Backend, ModelLoadOptions, Result, StatusResponse


class EncoderWorkerBase(Backend):
    LEN_BUCKETS: tuple[int, ...] = (32, 128, 256, 512)

    def __init__(self) -> None:
        self.spec: Optional[EncoderSpec] = None
        self.params: Optional[EncParams] = None
        self.tokenizer: Optional[Tokenizer] = None
        self._state = "UNINITIALIZED"
        self._lock = threading.Lock()

    def _compile(self) -> None:
        """Build the worker's jitted programs; spec/params are loaded."""
        raise NotImplementedError

    def load_model(self, opts: ModelLoadOptions) -> Result:
        with self._lock:
            try:
                model_dir = opts.model
                if not os.path.isabs(model_dir):
                    model_dir = os.path.join(opts.model_path or "", model_dir)
                if not os.path.isdir(model_dir):
                    raise FileNotFoundError(
                        f"model directory not found: {model_dir}")
                self.spec, self.params = load_encoder_params(model_dir)
                self.tokenizer = load_tokenizer(model_dir)
                self._compile()
                self._state = "READY"
                return Result(True, "encoder model loaded")
            except Exception as e:
                self._state = "ERROR"
                return Result(False, f"load failed: {e}")

    def health(self) -> bool:
        return self._state == "READY"

    def status(self) -> StatusResponse:
        return StatusResponse(state=self._state)

    def shutdown(self) -> None:
        self.spec = self.params = self.tokenizer = None
        self._state = "UNINITIALIZED"

    # --------------------------------------------------------- batching

    def _bucket(self, n: int) -> int:
        cap = self.spec.max_position
        for b in self.LEN_BUCKETS:
            if n <= b <= cap:
                return b
        return cap

    def _batch(
        self, seqs: list[list[int]],
        type_seqs: Optional[list[list[int]]] = None,
    ) -> tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """Pad to the next length bucket -> (tokens, mask, type_ids?)."""
        T = self._bucket(max(len(s) for s in seqs))
        toks = np.zeros((len(seqs), T), np.int32)
        mask = np.zeros((len(seqs), T), np.int32)
        types = np.zeros((len(seqs), T), np.int32) if type_seqs else None
        for r, s in enumerate(seqs):
            s = s[:T]
            toks[r, : len(s)] = s
            mask[r, : len(s)] = 1
            if types is not None:
                ts = type_seqs[r][:T]
                types[r, : len(ts)] = ts
        return toks, mask, types
